"""Runtime configuration system.

Re-design of /root/reference/pkg/option/{config.go,option.go,
runtime_options.go,monitor.go}: a global DaemonConfig plus an option
LIBRARY — per-option descriptors carrying a define symbol, a
description, dependency requirements, and optional parse/verify/format
hooks — over a mutable option map with dependency propagation
(option.go:419 enabling an option enables what it requires;
option.go:445 disabling one disables its dependents).

In the TPU framework, option values that affect verdict computation
become part of the compiler cache key (the analog of
config-as-#defines in the generated BPF headers, pkg/endpoint
writeHeaderfile): changing them invalidates compiled tables.  Options
that gate OBSERVABILITY (drop/trace/verdict notifications, debug
logging, conntrack accounting) hook the monitor fold and the host CT
path directly — see Daemon.config_patch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

# Policy enforcement modes (pkg/option/config.go)
DEFAULT_ENFORCEMENT = "default"
ALWAYS_ENFORCE = "always"
NEVER_ENFORCE = "never"

# AllowLocalhost modes
ALLOW_LOCALHOST_AUTO = "auto"
ALLOW_LOCALHOST_ALWAYS = "always"
ALLOW_LOCALHOST_POLICY = "policy"

# Mutable option names (pkg/option/runtime_options.go)
POLICY_TRACING = "PolicyTracing"
DEBUG = "Debug"
DEBUG_LB = "DebugLB"
DROP_NOTIFICATION = "DropNotification"
TRACE_NOTIFICATION = "TraceNotification"
POLICY_VERDICT_NOTIFICATION = "PolicyVerdictNotification"
CONNTRACK = "Conntrack"
CONNTRACK_ACCOUNTING = "ConntrackAccounting"
CONNTRACK_LOCAL = "ConntrackLocal"
MONITOR_AGGREGATION = "MonitorAggregationLevel"
NAT46 = "NAT46"

# MonitorAggregationLevel settings (pkg/option/monitor.go): 0 = every
# packet traced; higher = progressively aggregated
MONITOR_AGG_NONE = 0
MONITOR_AGG_LOWEST = 1
MONITOR_AGG_LOW = 2
MONITOR_AGG_MEDIUM = 3
MONITOR_AGG_MAX = MONITOR_AGG_MEDIUM

_MONITOR_AGG_NAMES = {
    "": MONITOR_AGG_NONE,
    "none": MONITOR_AGG_NONE,
    "disabled": MONITOR_AGG_NONE,
    "lowest": MONITOR_AGG_LOWEST,
    "low": MONITOR_AGG_LOW,
    "medium": MONITOR_AGG_MEDIUM,
    "max": MONITOR_AGG_MAX,
    "maximum": MONITOR_AGG_MAX,
}


def _parse_bool(value) -> int:
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, int) and value in (0, 1):
        return value
    if isinstance(value, str):
        low = value.strip().lower()
        if low in ("true", "on", "enable", "enabled", "1"):
            return 1
        if low in ("false", "off", "disable", "disabled", "0"):
            return 0
    raise ValueError(f"expected a boolean, got {value!r}")


def parse_monitor_aggregation(value) -> int:
    """ParseMonitorAggregationLevel (monitor.go): names or 0..3."""
    if isinstance(value, bool):
        return MONITOR_AGG_MAX if value else MONITOR_AGG_NONE
    if isinstance(value, int):
        if 0 <= value <= MONITOR_AGG_MAX:
            return value
        raise ValueError(
            f"invalid monitor aggregation level {value!r}"
        )
    if isinstance(value, str):
        low = value.strip().lower()
        if low in _MONITOR_AGG_NAMES:
            return _MONITOR_AGG_NAMES[low]
        if low.isdigit() and 0 <= int(low) <= MONITOR_AGG_MAX:
            return int(low)
    raise ValueError(f"invalid monitor aggregation level {value!r}")


def format_monitor_aggregation(value: int) -> str:
    for name, v in _MONITOR_AGG_NAMES.items():
        if v == value and name not in ("", "disabled", "maximum"):
            return name
    return str(value)


def _verify_nat46(key: str, value) -> None:
    if _parse_bool(value):
        # the reference gates NAT46 on IPv4 being enabled
        # (runtime_options.go ErrNAT46ReqIPv4); this datapath has no
        # NAT46 lowering yet, so enabling it must fail loudly rather
        # than silently do nothing
        raise ValueError(
            "NAT46 translation is not supported by this datapath"
        )


@dataclass(frozen=True)
class OptionSpec:
    """One library entry (option.go:41 Option)."""

    define: str  # the compile-key symbol (≙ the BPF #define)
    description: str
    requires: Tuple[str, ...] = ()
    parse: Callable = _parse_bool
    verify: Optional[Callable] = None
    format: Callable = lambda v: "Enabled" if v else "Disabled"


# DaemonMutableOptionLibrary (pkg/option/daemon.go:28) + the
# policy-verdict option later reference versions add
DAEMON_MUTABLE_OPTION_LIBRARY: Dict[str, OptionSpec] = {
    CONNTRACK: OptionSpec(
        "CONNTRACK", "Enable stateful connection tracking"
    ),
    CONNTRACK_ACCOUNTING: OptionSpec(
        "CONNTRACK_ACCOUNTING",
        "Enable per flow (conntrack) statistics",
        requires=(CONNTRACK,),
    ),
    CONNTRACK_LOCAL: OptionSpec(
        "CONNTRACK_LOCAL",
        "Use endpoint dedicated tracking table instead of global one",
        requires=(CONNTRACK,),
    ),
    DEBUG: OptionSpec(
        "DEBUG", "Enable debugging trace statements"
    ),
    DEBUG_LB: OptionSpec(
        "LB_DEBUG",
        "Enable debugging trace statements for load balancer",
    ),
    DROP_NOTIFICATION: OptionSpec(
        "DROP_NOTIFY", "Enable drop notifications"
    ),
    TRACE_NOTIFICATION: OptionSpec(
        "TRACE_NOTIFY", "Enable trace notifications"
    ),
    POLICY_VERDICT_NOTIFICATION: OptionSpec(
        "POLICY_VERDICT_NOTIFY",
        "Enable policy verdict notifications",
    ),
    MONITOR_AGGREGATION: OptionSpec(
        "MONITOR_AGGREGATION",
        "Set the level of aggregation for monitor events in the "
        "datapath",
        parse=parse_monitor_aggregation,
        format=format_monitor_aggregation,
    ),
    NAT46: OptionSpec(
        "ENABLE_NAT46",
        "Enable automatic NAT46 translation",
        requires=(CONNTRACK,),
        verify=_verify_nat46,
    ),
}

# DaemonOptionLibrary = mutable + PolicyTracing (daemon.go:24)
DAEMON_OPTION_LIBRARY: Dict[str, OptionSpec] = {
    **DAEMON_MUTABLE_OPTION_LIBRARY,
    POLICY_TRACING: OptionSpec(
        "POLICY_TRACING", "Enable tracing of policy decisions"
    ),
}

KNOWN_OPTIONS = set(DAEMON_OPTION_LIBRARY)


class OptionMap(dict):
    """Named options with parse/verify + dependency propagation
    (option.go:41 IntOptions over an OptionLibrary)."""

    library: Dict[str, OptionSpec] = DAEMON_OPTION_LIBRARY

    def is_enabled(self, name: str) -> bool:
        return bool(self.get(name, 0))

    def level(self, name: str) -> int:
        return int(self.get(name, 0))

    def parse_validate(self, name: str, value) -> int:
        """Library parse + verify for one (name, value); raises on
        unknown options or invalid values WITHOUT mutating."""
        spec = self.library.get(name)
        if spec is None:
            raise ValueError(f"unknown option {name}")
        parsed = spec.parse(value)
        if spec.verify is not None:
            spec.verify(name, value)
        return parsed

    def apply(self, changes: Dict[str, object],
              changed_hook: Optional[Callable] = None) -> int:
        """Parse/verify every change first, then apply with
        dependency propagation: enabling an option enables what it
        requires (option.go:419); disabling one disables dependents
        (option.go:445)."""
        parsed = {
            k: self.parse_validate(k, v) for k, v in changes.items()
        }
        n = 0

        def _set(k: str, v: int) -> None:
            nonlocal n
            if self.get(k, 0) != v:
                self[k] = v
                n += 1
                if changed_hook:
                    changed_hook(k, v)

        for k, v in parsed.items():
            spec = self.library[k]
            if v:
                for dep in spec.requires:
                    _set(dep, 1)
            else:
                for name, other in self.library.items():
                    if k in other.requires:
                        _set(name, 0)
            _set(k, v)
        return n

    def describe(self) -> Dict[str, Dict[str, str]]:
        """The option library rendered for GET /config."""
        return {
            name: {
                "define": spec.define,
                "description": spec.description,
                "requires": list(spec.requires),
                "value": spec.format(self.get(name, 0)),
            }
            for name, spec in sorted(self.library.items())
        }


def default_opts() -> OptionMap:
    """Boot-time defaults, as the reference daemon enables them
    (daemon bootstrap: conntrack + accounting + drop/trace
    notifications on)."""
    opts = OptionMap()
    opts.update(
        {
            CONNTRACK: 1,
            CONNTRACK_ACCOUNTING: 1,
            DROP_NOTIFICATION: 1,
            TRACE_NOTIFICATION: 1,
            # per-packet traces only when an operator dials the
            # aggregation down to `none`: the monitor fold is a
            # host-side Python loop, so the default keeps its cost on
            # the denied/sampled slice only
            MONITOR_AGGREGATION: MONITOR_AGG_MEDIUM,
        }
    )
    return opts


@dataclass
class DaemonConfig:
    """Global daemon configuration (pkg/option/config.go)."""

    policy_enforcement: str = DEFAULT_ENFORCEMENT
    allow_localhost: str = ALLOW_LOCALHOST_AUTO
    # HostAllowsWorld: legacy 1.0 behaviour, world shares host policy
    # (config.go:183).
    host_allows_world: bool = False
    dry_mode: bool = False
    # EndpointGenerationTimeout (pkg/endpoint/bpf.go:442): how long a
    # regeneration waits for proxy redirect ACKs before failing and
    # keeping old state
    redirect_ack_timeout: float = 30.0
    opts: OptionMap = field(default_factory=default_opts)

    # TPU-side knobs (compiler cache key components).
    identity_pad: int = 1024          # pad identity axis to multiples
    filter_pad: int = 64              # pad L4-filter axis to multiples
    device_batch: int = 1 << 20       # tuples per device step

    def always_allow_localhost(self) -> bool:
        """config.go:277."""
        return self.allow_localhost == ALLOW_LOCALHOST_ALWAYS

    def tracing_enabled(self) -> bool:
        return self.opts.is_enabled(POLICY_TRACING)

    def cache_key(self) -> tuple:
        """Verdict-affecting config as a hashable compiler cache key."""
        return (
            self.policy_enforcement,
            self.allow_localhost,
            self.host_allows_world,
            self.identity_pad,
            self.filter_pad,
        )


# The process-global config, mirroring option.Config.
Config = DaemonConfig()
