"""Runtime configuration system.

Re-design of /root/reference/pkg/option/{config.go,option.go}: a global
DaemonConfig plus a bitmask-style mutable option set with per-option
verify/parse hooks.  In the TPU framework, option values that affect
verdict computation become part of the compiler cache key (the analog of
config-as-#defines in the generated BPF headers, pkg/endpoint
writeHeaderfile): changing them invalidates compiled tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

# Policy enforcement modes (pkg/option/config.go)
DEFAULT_ENFORCEMENT = "default"
ALWAYS_ENFORCE = "always"
NEVER_ENFORCE = "never"

# AllowLocalhost modes
ALLOW_LOCALHOST_AUTO = "auto"
ALLOW_LOCALHOST_ALWAYS = "always"
ALLOW_LOCALHOST_POLICY = "policy"

# Mutable boolean options (pkg/option/option.go library)
POLICY_TRACING = "PolicyTracing"
DEBUG = "Debug"
DROP_NOTIFICATION = "DropNotification"
TRACE_NOTIFICATION = "TraceNotification"
POLICY_VERDICT_NOTIFICATION = "PolicyVerdictNotification"
CONNTRACK = "Conntrack"
CONNTRACK_ACCOUNTING = "ConntrackAccounting"

KNOWN_OPTIONS = {
    POLICY_TRACING,
    DEBUG,
    DROP_NOTIFICATION,
    TRACE_NOTIFICATION,
    POLICY_VERDICT_NOTIFICATION,
    CONNTRACK,
    CONNTRACK_ACCOUNTING,
}


class OptionMap(dict):
    """Named boolean options with change tracking (option.go:41)."""

    def is_enabled(self, name: str) -> bool:
        return bool(self.get(name, False))

    def apply(self, changes: Dict[str, bool],
              changed_hook: Optional[Callable] = None) -> int:
        n = 0
        for k, v in changes.items():
            if k not in KNOWN_OPTIONS:
                raise ValueError(f"unknown option {k}")
            if self.get(k, False) != v:
                self[k] = v
                n += 1
                if changed_hook:
                    changed_hook(k, v)
        return n


@dataclass
class DaemonConfig:
    """Global daemon configuration (pkg/option/config.go)."""

    policy_enforcement: str = DEFAULT_ENFORCEMENT
    allow_localhost: str = ALLOW_LOCALHOST_AUTO
    # HostAllowsWorld: legacy 1.0 behaviour, world shares host policy
    # (config.go:183).
    host_allows_world: bool = False
    dry_mode: bool = False
    # EndpointGenerationTimeout (pkg/endpoint/bpf.go:442): how long a
    # regeneration waits for proxy redirect ACKs before failing and
    # keeping old state
    redirect_ack_timeout: float = 30.0
    opts: OptionMap = field(default_factory=OptionMap)

    # TPU-side knobs (compiler cache key components).
    identity_pad: int = 1024          # pad identity axis to multiples
    filter_pad: int = 64              # pad L4-filter axis to multiples
    device_batch: int = 1 << 20       # tuples per device step

    def always_allow_localhost(self) -> bool:
        """config.go:277."""
        return self.allow_localhost == ALLOW_LOCALHOST_ALWAYS

    def tracing_enabled(self) -> bool:
        return self.opts.is_enabled(POLICY_TRACING)

    def cache_key(self) -> tuple:
        """Verdict-affecting config as a hashable compiler cache key."""
        return (
            self.policy_enforcement,
            self.allow_localhost,
            self.host_allows_world,
            self.identity_pad,
            self.filter_pad,
        )


# The process-global config, mirroring option.Config.
Config = DaemonConfig()
