"""Label model: the atoms of policy identity.

A TPU-native re-design of the reference label model
(/root/reference/pkg/labels/labels.go, array.go, cidr.go).  Labels are
host-side control-plane objects; they never reach the device.  The device
sees only numeric identities (see cilium_tpu.identity) and the
selector->identity bitmask matrices produced by cilium_tpu.compiler.

Semantics reproduced bit-for-bit:
  * label sources (labels.go:124-162): unspec/any/k8s/container/reserved/cidr
  * ``$`` shorthand for reserved labels (labels.go:579-600)
  * extended keys ``source.key`` used by k8s-style selectors
    (labels.go:404-433)
  * LabelArray Has/Get with any-source semantics (array.go:90-131)
  * sorted-list serialization + sha256 used as identity key
    (labels.go:515-540)
  * CIDR -> label conversion (cidr.go:28-80): ':' -> '-', zero padding
"""

from __future__ import annotations

import hashlib
import ipaddress
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, Iterable, List, Optional

PATH_DELIMITER = "."

# Reserved label names (labels.go:31-53)
ID_NAME_ALL = "all"
ID_NAME_HOST = "host"
ID_NAME_WORLD = "world"
ID_NAME_CLUSTER = "cluster"
ID_NAME_HEALTH = "health"
ID_NAME_INIT = "init"
ID_NAME_UNKNOWN = "unknown"

# Label sources (labels.go:124-162)
SOURCE_UNSPEC = "unspec"
SOURCE_ANY = "any"
SOURCE_ANY_KEY_PREFIX = SOURCE_ANY + "."
SOURCE_K8S = "k8s"
SOURCE_MESOS = "mesos"
SOURCE_K8S_KEY_PREFIX = SOURCE_K8S + "."
SOURCE_CONTAINER = "container"
SOURCE_RESERVED = "reserved"
SOURCE_CIDR = "cidr"
SOURCE_RESERVED_KEY_PREFIX = SOURCE_RESERVED + "."
SOURCE_CILIUM_GENERATED = "cilium-generated"

LABEL_KEY_FIXED_IDENTITY = "io.cilium.fixed-identity"


@dataclass(frozen=True)
class Label:
    """A single ``source:key=value`` label (labels.go:165)."""

    key: str
    value: str = ""
    source: str = SOURCE_UNSPEC

    def equals(self, other: "Label") -> bool:
        """Label equality honoring the any-source wildcard (labels.go:312)."""
        if not self.is_any_source():
            if self.source != other.source:
                return False
        return self.key == other.key and self.value == other.value

    def is_all_label(self) -> bool:
        return self.source == SOURCE_RESERVED and self.key == ID_NAME_ALL

    def is_any_source(self) -> bool:
        return self.source == SOURCE_ANY

    def is_reserved_source(self) -> bool:
        return self.source == SOURCE_RESERVED

    def matches(self, target: "Label") -> bool:
        """True if self matches target (labels.go:337)."""
        return self.is_all_label() or self.equals(target)

    def get_extended_key(self) -> str:
        """``source.key`` form used by selectors (labels.go:405)."""
        return self.source + PATH_DELIMITER + self.key

    def is_valid(self) -> bool:
        return self.key != ""

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"


def parse_source(s: str) -> tuple:
    """Split a label string into (source, rest) (labels.go:579).

    ``$x`` is shorthand for ``reserved:x``.
    """
    if s == "":
        return "", ""
    if s[0] == "$":
        s = s.replace("$", SOURCE_RESERVED + ":", 1)
    parts = s.split(":", 1)
    if len(parts) != 2:
        nxt = parts[0]
        src = ""
        if nxt.startswith(SOURCE_RESERVED):
            src = SOURCE_RESERVED
            if nxt.startswith(SOURCE_RESERVED_KEY_PREFIX):
                nxt = nxt[len(SOURCE_RESERVED_KEY_PREFIX):]
        return src, nxt
    src = parts[0] if parts[0] != "" else ""
    return src, parts[1]


def new_label(key: str, value: str = "", source: str = "") -> Label:
    """Construct a label, parsing an embedded source prefix (labels.go:289)."""
    src, key = parse_source(key)
    if source == "":
        source = src if src != "" else SOURCE_UNSPEC
    if src == SOURCE_RESERVED and key == "":
        key = value
        value = ""
    return Label(key=key, value=value, source=source)


@lru_cache(maxsize=4096)
def parse_label(s: str) -> Label:
    """Parse ``[source:]key[=value]`` (labels.go:605).  Label is
    frozen, so the parse memoizes safely — selector matching parses
    the same handful of key strings millions of times per sweep."""
    src, nxt = parse_source(s)
    source = src if src != "" else SOURCE_UNSPEC
    key_split = nxt.split("=", 1)
    key = key_split[0]
    value = ""
    if len(key_split) > 1:
        if src == SOURCE_RESERVED and key_split[0] == "":
            key = key_split[1]
        else:
            value = key_split[1]
    return Label(key=key, value=value, source=source)


def parse_select_label(s: str) -> Label:
    """Like parse_label but unspecified source becomes ``any`` (labels.go:629)."""
    lbl = parse_label(s)
    if lbl.source == SOURCE_UNSPEC:
        return Label(key=lbl.key, value=lbl.value, source=SOURCE_ANY)
    return lbl


def get_cilium_key_from(ext_key: str) -> str:
    """``source.key`` extended key -> ``source:key`` (labels.go:411)."""
    parts = ext_key.split(PATH_DELIMITER, 1)
    if len(parts) == 2:
        return parts[0] + ":" + parts[1]
    return SOURCE_ANY + ":" + parts[0]


def get_extended_key_from(s: str) -> str:
    """``k8s:foo=bar`` -> ``k8s.foo``; ``foo`` -> ``any.foo`` (labels.go:424)."""
    src, nxt = parse_source(s)
    if src == "":
        src = SOURCE_ANY
    nxt = nxt.split("=", 2)[0]
    return src + PATH_DELIMITER + nxt


class LabelArray(list):
    """An ordered set of labels; the context unit of policy matching.

    Implements the k8s ``Labels`` interface semantics the selectors match
    against (array.go:90-131): ``has``/``get`` take extended keys and treat
    ``any.`` as source-wildcard.
    """

    @staticmethod
    def parse(*labels: str) -> "LabelArray":
        return LabelArray(parse_label(s) for s in labels)

    @staticmethod
    def parse_select(*labels: str) -> "LabelArray":
        return LabelArray(parse_select_label(s) for s in labels)

    def contains(self, needed: "LabelArray") -> bool:
        """True if every needed label matches one of ours (array.go:58)."""
        return all(any(n.matches(l) for l in self) for n in needed)

    def lacks(self, needed: "LabelArray") -> "LabelArray":
        return LabelArray(
            n for n in needed if not any(n.matches(l) for l in self)
        )

    def has(self, ext_key: str) -> bool:
        """k8s Labels.Has with any-source handling (array.go:92)."""
        ck = get_cilium_key_from(ext_key)
        key_label = parse_label(ck)
        if key_label.is_any_source():
            return any(l.key == key_label.key for l in self)
        return any(l.get_extended_key() == ext_key for l in self)

    def get(self, ext_key: str) -> str:
        """k8s Labels.Get with any-source handling (array.go:114)."""
        ck = get_cilium_key_from(ext_key)
        key_label = parse_label(ck)
        if key_label.is_any_source():
            for l in self:
                if l.key == key_label.key:
                    return l.value
        else:
            for l in self:
                if l.get_extended_key() == ext_key:
                    return l.value
        return ""

    def get_model(self) -> List[str]:
        return [str(l) for l in self]

    def sorted_list(self) -> bytes:
        """Canonical serialization used as the identity key (labels.go:525)."""
        by_key: Dict[str, Label] = {}
        for l in self:
            by_key[l.key] = l
        out = ""
        for k in sorted(by_key):
            l = by_key[k]
            out += f"{l.source}:{k}={l.value};"
        return out.encode()

    def sha256sum(self) -> str:
        """SHA-512/256 of the sorted list (labels.go:517)."""
        return hashlib.new("sha512_256", self.sorted_list()).hexdigest()

    def __hash__(self):  # type: ignore[override]
        return hash(self.sorted_list())


class Labels(dict):
    """Map key -> Label (labels.go:175)."""

    @staticmethod
    def from_model(base: Iterable[str]) -> "Labels":
        lbls = Labels()
        for s in base:
            l = parse_label(s)
            if l.key != "":
                lbls[l.key] = l
        return lbls

    @staticmethod
    def from_sorted_list(s: str) -> "Labels":
        return Labels.from_model(s.split(";"))

    def merge(self, other: "Labels") -> None:
        for k, v in other.items():
            self[k] = v

    def to_label_array(self) -> LabelArray:
        return LabelArray(self[k] for k in sorted(self))

    def sorted_list(self) -> bytes:
        out = ""
        for k in sorted(self):
            l = self[k]
            out += f"{l.source}:{k}={l.value};"
        return out.encode()

    def sha256sum(self) -> str:
        return hashlib.new("sha512_256", self.sorted_list()).hexdigest()

    def find_reserved(self) -> Optional["Labels"]:
        found = Labels(
            {k: l for k, l in self.items() if l.source == SOURCE_RESERVED}
        )
        return found if found else None

    def equals(self, other: "Labels") -> bool:
        if len(self) != len(other):
            return False
        for k, l1 in self.items():
            l2 = other.get(k)
            if l2 is None:
                return False
            if (l1.source, l1.key, l1.value) != (l2.source, l2.key, l2.value):
                return False
        return True


# ---------------------------------------------------------------------------
# CIDR labels (pkg/labels/cidr.go)
# ---------------------------------------------------------------------------


def _masked_ip_to_label_string(ip: str, prefix: int) -> str:
    """Serialize ip/prefix into a selectable label string (cidr.go:28-45).

    IPv6 ':' becomes '-'; a leading/trailing '-' gets a '0' guard.
    """
    ip_no_colons = ip.replace(":", "-")
    pre = "0" if ip_no_colons[0] == "-" else ""
    post = "0" if ip_no_colons[-1] == "-" else ""
    return f"{SOURCE_CIDR}:{pre}{ip_no_colons}{post}/{prefix}"


def ip_net_to_label(network: ipaddress._BaseNetwork) -> Label:
    """CIDR network -> label (cidr.go:49)."""
    return parse_label(
        _masked_ip_to_label_string(str(network.network_address),
                                   network.prefixlen)
    )


def ip_string_to_label(ip: str) -> Optional[Label]:
    """Parse an IP or CIDR string into a cidr: label (cidr.go:57-73)."""
    try:
        net = ipaddress.ip_network(ip, strict=False)
    except ValueError:
        return None
    return ip_net_to_label(net)


def masked_ip_net_to_label_string(network: ipaddress._BaseNetwork,
                                  prefix: int) -> str:
    """Mask a network to 'prefix' bits then serialize (cidr.go:76)."""
    bits = network.max_prefixlen
    masked = ipaddress.ip_network(
        (int(network.network_address) & _mask_int(prefix, bits), prefix),
        strict=False,
    )
    return _masked_ip_to_label_string(str(masked.network_address), prefix)


def _mask_int(prefix: int, bits: int) -> int:
    if prefix <= 0:
        return 0
    return ((1 << prefix) - 1) << (bits - prefix)


def get_cidr_labels(network: ipaddress._BaseNetwork) -> LabelArray:
    """All-prefix-length label expansion of a CIDR (pkg/labels/cidr/cidr.go).

    A /24 yields labels for /0../24 plus reserved:world, so that a CIDR
    identity is selectable by any covering prefix.
    """
    out = LabelArray()
    out.append(Label(key=ID_NAME_WORLD, value="", source=SOURCE_RESERVED))
    for plen in range(0, network.prefixlen + 1):
        out.append(parse_label(masked_ip_net_to_label_string(network, plen)))
    return out


def labels_from_json(items: list) -> "Labels":
    """Wire/checkpoint label decoding: [{key, value?, source?}] →
    Labels.  One definition for every JSON surface (REST endpoint
    create, endpoint checkpoints) — raises ValueError on an item
    without a key, so transports can classify it as a client fault."""
    out = {}
    for item in items:
        if "key" not in item:
            raise ValueError(f"label item without key: {item!r}")
        out[item["key"]] = Label(
            key=item["key"],
            value=item.get("value", ""),
            source=item.get("source", "unspec"),
        )
    return Labels(out)
