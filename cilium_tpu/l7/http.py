"""HTTP L7 policy: rules → DFA tables → batched device matching.

Reference semantics being reproduced (bit-identically):
  * pkg/envoy/server.go:316 getHTTPRule — Path/Method/Host become
    Envoy regex HeaderMatchers, which FULL-match the value; all fields
    of one PortRuleHTTP must match (AND); a request is allowed if ANY
    rule of the relevant L7Rules matches (OR) — envoy route semantics
    in cilium_l7policy.cc (deny → 403).
  * pkg/policy/l4.go:118 GetRelevantRules — rules apply per remote
    identity through their selector; an entry with EMPTY L7Rules is an
    L7 allow-all for the selected identities (wildcardL3L4Rules,
    repository.go:170).
  * Header constraints (PortRuleHTTP.Headers) are exact present-match
    pairs; they stay host-evaluated (like Envoy evaluates them in C++
    on the host CPU) — rules carrying headers are excluded from the
    device tables and merged back by `evaluate_with_host_fallback`.

Device layout (R rules per port filter, W = ceil(R/32) mask words —
rule r lives in bit r%32 of word r//32; no 32-rule cap):
  method/path/host DFAs — union DFAs with per-rule accept bits
                           (accept u32 [S, W]);
  absent_<field> u32 [W] — rules that omit the field (auto-match);
  ident_rules u32 [N, W] — bit r set ⟺ rule r's selector admits
                           identity index n (includes allow-all
                           pseudo-rules, which also have all fields
                           absent).

Requests whose method/path/host exceed the padded field budgets are
FLAGGED (`overflow`) and re-evaluated host-side by
`evaluate_with_host_fallback` — never silently truncated: a truncated
byte tensor could both falsely full-match a prefix-shaped pattern and
miss a long-match, in either direction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.l7.regex_dfa import (
    DFA,
    RegexTooComplex,
    RegexUnsupported,
    compile_union,
)

# Sanity ceiling only (accept masks are multi-word): guards against a
# pathological compile blowing up accept-table width, not a semantic
# limit — the reference's per-filter rule count is bounded by policy
# size, not a constant.
MAX_RULES = 4096


@dataclass
class HTTPRuleSpec:
    """One (selector-scope, PortRuleHTTP) pair, pre-resolved: the
    identity indices the selector admits over the current universe."""

    identity_indices: Sequence[int]  # indices into the padded universe
    path: str = ""
    method: str = ""
    host: str = ""
    headers: Tuple[str, ...] = ()
    # fleet-scoped compiles (l7/fleet.py) key each rule to its
    # (endpoint, direction, L4 slot); None = filter-local rule.
    # Participates in dedupe: rules only merge within one scope.
    scope_key: "object" = None


@dataclass
class HTTPTables:
    """Device tables for one (endpoint, port, direction) HTTP filter."""

    # DFAs (trans u16 [S,C], accept u32 [S,W], classes u8 [256], start)
    method_dfa: DFA
    path_dfa: DFA
    host_dfa: DFA
    absent_method: np.ndarray  # u32 [W] bitmask
    absent_path: np.ndarray
    absent_host: np.ndarray
    ident_rules: np.ndarray  # u32 [N, W] per-identity rule bits
    n_rules: int
    n_words: int
    # strided forms (None = fall back to the byte-at-a-time scan)
    method_sdfa: "Optional[StridedDFA]" = None
    path_sdfa: "Optional[StridedDFA]" = None
    host_sdfa: "Optional[StridedDFA]" = None


@dataclass
class HTTPPolicy:
    """Compiled HTTP policy + host-fallback rules."""

    tables: HTTPTables
    host_rules: List[HTTPRuleSpec]  # header-carrying rules
    # Deduped device rules retained for the host path: overflowed
    # requests (fields beyond the padded budgets) re-evaluate against
    # these with re.fullmatch instead of the truncated tensors.
    device_rules: List[HTTPRuleSpec] = field(default_factory=list)


def resolve_selector_indices(
    selector, identity_cache, id_index, selector_cache=None
) -> List[int]:
    """selector → dense identity indices.  With a SelectorCache the
    resolution is one memoized set lookup (O(matched)); without, it
    falls back to the per-identity matches() walk — identical result,
    O(identities) (compiler/selectorcache.py docstring derivation)."""
    if selector_cache is not None:
        return [
            id_index[num_id]
            for num_id in selector_cache.matches(selector)
            if num_id in id_index
        ]
    return [
        id_index[num_id]
        for num_id, labels in identity_cache.items()
        if selector.matches(labels) and num_id in id_index
    ]


def specs_from_filter(
    l4_filter, identity_cache, id_index, selector_cache=None
) -> List["HTTPRuleSpec"]:
    """L4Filter.l7_rules_per_ep (selector → L7Rules, pkg/policy/l4.go:31)
    → flat HTTPRuleSpec list over the identity universe.

    A selector entry with EMPTY L7Rules becomes an allow-all
    pseudo-rule (all fields absent ⇒ matches every request) — the
    L3-override / wildcard entries of createL4IngressFilter
    (l4.go:209) and wildcardL3L4Rules (repository.go:170).
    """
    specs: List[HTTPRuleSpec] = []
    for selector, l7 in l4_filter.l7_rules_per_ep.items():
        indices = resolve_selector_indices(
            selector, identity_cache, id_index, selector_cache
        )
        http_rules = l7.http or []
        if not http_rules:
            specs.append(HTTPRuleSpec(identity_indices=indices))
            continue
        for rule in http_rules:
            specs.append(
                HTTPRuleSpec(
                    identity_indices=indices,
                    path=rule.path or "",
                    method=rule.method or "",
                    host=rule.host or "",
                    headers=tuple(rule.headers or ()),
                )
            )
    return specs


def _dedupe_specs(rules: List[HTTPRuleSpec]) -> List[HTTPRuleSpec]:
    """Rules with identical patterns are one device rule with the
    union of their identity sets — allowed = OR over rules, so this
    is semantics-preserving.  The dominant case is the allow-all
    pseudo-rules that every L3-only rule wildcards into each L7
    filter (repository.go:170): they all collapse to one."""
    merged: Dict[Tuple[str, str, str, object], set] = {}
    order: List[Tuple[str, str, str, object]] = []
    for rule in rules:
        key = (rule.method, rule.path, rule.host, rule.scope_key)
        if key not in merged:
            merged[key] = set()
            order.append(key)
        merged[key].update(rule.identity_indices)
    return [
        HTTPRuleSpec(
            identity_indices=sorted(merged[key]),
            method=key[0],
            path=key[1],
            host=key[2],
            scope_key=key[3],
        )
        for key in order
    ]


def compile_http_rules(
    rules: Sequence[HTTPRuleSpec],
    n_identities: int,
    max_states: int = 4096,
) -> HTTPPolicy:
    """Split rules into device/host sets and build the union DFAs."""
    device_rules: List[HTTPRuleSpec] = []
    host_rules: List[HTTPRuleSpec] = []
    for rule in rules:
        if rule.headers:
            host_rules.append(rule)
            continue
        device_rules.append(rule)
    device_rules = _dedupe_specs(device_rules)
    if len(device_rules) > MAX_RULES:
        raise RegexTooComplex(
            f"more than {MAX_RULES} device HTTP rules per filter"
        )
    n_words = max(1, -(-len(device_rules) // 32))

    def _to_words(mask: int) -> np.ndarray:
        return np.array(
            [(mask >> (32 * w)) & 0xFFFFFFFF for w in range(n_words)],
            dtype=np.uint32,
        )

    def union_for(field_name: str) -> Tuple[DFA, np.ndarray]:
        """DFA over the present patterns; absent bitmask for the rest.
        Pattern bit positions == rule positions (absent patterns
        compile as never-matching placeholders via the absent mask)."""
        patterns = []
        absent = 0
        for i, rule in enumerate(device_rules):
            pattern = getattr(rule, field_name)
            if pattern == "":
                absent |= 1 << i
                patterns.append("[^\\x00-\\xff]")  # matches nothing
            else:
                patterns.append(pattern)
        try:
            dfa = compile_union(patterns, max_states=max_states)
        except (RegexUnsupported, RegexTooComplex):
            raise
        return dfa, _to_words(absent)

    method_dfa, absent_method = union_for("method")
    path_dfa, absent_path = union_for("path")
    host_dfa, absent_host = union_for("host")

    ident_rules = np.zeros((n_identities, n_words), dtype=np.uint32)
    for i, rule in enumerate(device_rules):
        for idx in rule.identity_indices:
            ident_rules[idx, i // 32] |= np.uint32(1 << (i % 32))

    tables = HTTPTables(
        method_sdfa=build_strided(method_dfa),
        path_sdfa=build_strided(path_dfa),
        host_sdfa=build_strided(host_dfa),
        method_dfa=method_dfa,
        path_dfa=path_dfa,
        host_dfa=host_dfa,
        absent_method=absent_method,
        absent_path=absent_path,
        absent_host=absent_host,
        ident_rules=ident_rules,
        n_rules=len(device_rules),
        n_words=n_words,
    )
    return HTTPPolicy(
        tables=tables, host_rules=host_rules, device_rules=device_rules
    )


# ---------------------------------------------------------------------------
# device kernel
# ---------------------------------------------------------------------------


@dataclass
class StridedDFA:
    """A DFA squared k times: one scan step consumes 2^k bytes.

    The sequential byte-at-a-time scan is the HTTP path's cost center;
    squaring the transition table — with column deduplication between
    rounds and an artificial identity class so padding can never move
    the state — divides the step count by the stride.  The union DFAs
    here are tiny (tens of states), so the tables stay kilobytes.

    Level map l takes a pair of level-(l-1) classes to a level-l
    class.  The device evaluation maps byte values to level-0 classes
    and folds pairs level by level BEFORE the scan — and because every
    lookup's table is small, the folds run as one-hot × table matmuls
    on the MXU (measured 5-35× faster than XLA's gather lowering for
    K ≤ ~2k on v5e; gathers cost ~5-9 ns/element, the systolic array
    ~0.4 ns) — then scans the remaining positions with the transition
    table OF THAT LEVEL (level_trans[d], retained per round)."""

    classes: np.ndarray  # byte → level-0 class (identity class added)
    id_class0: int
    base_trans: np.ndarray  # [S, nc0] incl identity column (level 0)
    level_maps: List[np.ndarray]  # [nc_prev * nc_prev] → class id
    level_ncs: List[int]  # nc INPUT of each level
    level_ids: List[int]  # identity class id at each level OUTPUT
    # transition table AFTER each level (level_trans[k] pairs with a
    # class sequence folded through level_maps[:k+1]); the MXU scan
    # picks its fold depth by table size, so every depth's table is
    # kept (they are kilobytes)
    level_trans: List[np.ndarray]
    trans: np.ndarray  # [S, nc_final] == level_trans[-1]
    start: int
    accept: np.ndarray


# one-hot×table matmul beats XLA's gather lowering up to roughly this
# table size on v5e (measured crossover ~2-4k; gathers win above)
MXU_LOOKUP_MAX_K = 2048


def build_strided(
    dfa: DFA, rounds: int = 4, max_table_bytes: int = 1 << 22
) -> "Optional[StridedDFA]":
    """Square the transition table `rounds` times (stride 2^rounds),
    deduping equivalent columns between rounds and carrying an
    identity class for padding."""
    trans = dfa.trans.astype(np.int64)
    s_count, nc = trans.shape
    # identity column: padding bytes leave the state unchanged, so a
    # stride group that crosses the end of the string is exact
    trans = np.concatenate(
        [trans, np.arange(s_count, dtype=np.int64)[:, None]], axis=1
    )
    id_class = nc
    nc += 1
    base_trans = trans.astype(np.int32)

    level_maps: List[np.ndarray] = []
    level_ncs: List[int] = []
    level_ids: List[int] = []
    level_trans: List[np.ndarray] = []
    cur_id = id_class
    for _ in range(rounds):
        if s_count * nc * nc * 8 > max_table_bytes:
            break
        # T2[s, c1, c2] = trans[trans[s, c1], c2]
        t2 = trans[trans, :]  # t2[s, c1, c2] = trans[trans[s, c1], c2]
        flat = t2.reshape(s_count, nc * nc)
        cols, inverse = np.unique(flat.T, axis=0, return_inverse=True)
        level_maps.append(inverse.astype(np.int32))
        level_ncs.append(nc)
        trans = cols.T.astype(np.int64)  # [S, n_unique]
        cur_id = int(inverse[cur_id * nc + cur_id])
        level_ids.append(cur_id)
        level_trans.append(trans.astype(np.int32))
        nc = trans.shape[1]

    if not level_maps:
        # squaring never fit the budget: no strided form — callers
        # use the byte-at-a-time scan
        return None

    return StridedDFA(
        classes=dfa.classes.astype(np.int32),
        id_class0=id_class,
        base_trans=base_trans,
        level_maps=level_maps,
        level_ncs=level_ncs,
        level_ids=level_ids,
        level_trans=level_trans,
        trans=level_trans[-1],
        start=dfa.start,
        accept=dfa.accept,
    )


def _mxu_lookup(idx, table: np.ndarray):
    """Integer table lookup lowered as one-hot(idx) × table on the
    MXU instead of a gather (the gather lowering on TPU costs ~5-9 ns
    PER ELEMENT; the matmul streams at systolic-array rate).  Exact:
    the one-hot operand is 0/1, bf16 represents integers ≤ 256
    exactly, and tables with larger values split into lo/hi byte
    planes recombined after the f32-accumulated dot."""
    import jax
    import jax.numpy as jnp

    k = table.shape[0]
    iota = jnp.arange(k, dtype=jnp.int32)
    oh = (idx[..., None] == iota).astype(jnp.bfloat16)
    dims = (((oh.ndim - 1,), (0,)), ((), ()))

    def dot(vals: np.ndarray):
        return jax.lax.dot_general(
            oh,
            jnp.asarray(vals.astype(np.float32), jnp.bfloat16),
            dims,
            preferred_element_type=jnp.float32,
        )

    if int(table.max(initial=0)) <= 256:
        out = dot(table)
    else:
        out = dot(table % 256) + 256.0 * dot(table // 256)
    return out.astype(jnp.int32)


def _dfa_scan_strided(sdfa: StridedDFA, data, lengths):
    """[B, L] u8 → accept bitmask.  Positions past the string length
    become the identity class before the level folding, so padding is
    state-neutral by construction.  Byte-classing and the small-table
    pair folds run on the MXU (_mxu_lookup); folding stops at the
    first level whose pair table exceeds MXU_LOOKUP_MAX_K, and the
    remaining positions scan sequentially with that level's
    transition table (scan-step gathers are the one gather shape that
    stays cheap: [B] elements per step)."""
    import jax
    import jax.numpy as jnp

    b, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)
    p = jnp.where(
        pos[None, :] < lengths[:, None],
        data.astype(jnp.int32),
        jnp.int32(256),  # pad pseudo-byte
    )
    # byte → level-0 class on the MXU (K = 257)
    classes_e = np.concatenate(
        [sdfa.classes.astype(np.int64), [sdfa.id_class0]]
    )
    c = _mxu_lookup(p, classes_e)  # [B, L]
    pad_id = sdfa.id_class0

    depth = -1
    for k, (pair_map, nc_in, out_id) in enumerate(
        zip(sdfa.level_maps, sdfa.level_ncs, sdfa.level_ids)
    ):
        if nc_in * nc_in > MXU_LOOKUP_MAX_K:
            break
        if c.shape[1] % 2:
            c = jnp.concatenate(
                [c, jnp.full((b, 1), pad_id, jnp.int32)], axis=1
            )
        c = _mxu_lookup(
            c[:, 0::2] * nc_in + c[:, 1::2], pair_map
        )  # [B, L/2]
        pad_id = out_id
        depth = k

    # scan with the transition table of the deepest folded level
    # (base table when even the first pair map exceeded the budget —
    # a pathological byte-class count; the scan is then per-byte)
    trans = jnp.asarray(
        sdfa.base_trans if depth < 0 else sdfa.level_trans[depth]
    )
    nc_final = trans.shape[1]
    flat = trans.reshape(-1)
    state0 = jnp.full((b,), sdfa.start, dtype=jnp.int32)

    def step(state, col):
        return flat[state * nc_final + col], None

    cols = jnp.moveaxis(c, 1, 0)  # [L', B]
    state, _ = jax.lax.scan(step, state0, cols)
    return jnp.asarray(sdfa.accept)[state]


def _dfa_scan(dfa: DFA, data, lengths):
    """Step a [B, L] u8 byte tensor through the DFA; returns accept
    bitmask u32 [B, W].  One [B]-gather per position via lax.scan — the
    'dense take_along_axis stepping' of SURVEY §7 step 3."""
    import jax
    import jax.numpy as jnp

    trans = jnp.asarray(dfa.trans.astype(np.int32))
    classes = jnp.asarray(dfa.classes.astype(np.int32))
    accept = jnp.asarray(dfa.accept)
    n_classes = trans.shape[1]
    flat = trans.reshape(-1)

    b, l = data.shape
    state0 = jnp.full((b,), dfa.start, dtype=jnp.int32)

    def step(state, inputs):
        byte_col, pos = inputs
        c = classes[byte_col.astype(jnp.int32)]
        nxt = flat[state * n_classes + c]
        state = jnp.where(pos < lengths, nxt, state)
        return state, None

    cols = jnp.moveaxis(data, 1, 0)  # [L, B]
    state, _ = jax.lax.scan(
        step, state0, (cols, jnp.arange(l, dtype=jnp.int32))
    )
    return accept[state]


def evaluate_http_batch(
    tables: HTTPTables,
    method: "np.ndarray",  # u8 [B, Lm]
    method_len: "np.ndarray",  # i32 [B]
    path: "np.ndarray",
    path_len: "np.ndarray",
    host: "np.ndarray",
    host_len: "np.ndarray",
    ident_idx: "np.ndarray",  # i32 [B] identity index (from engine._index)
    known: "np.ndarray",  # bool [B]
    scope_bits=None,  # u32 [B, W] per-flow rule-scope mask (fleet mode)
):
    """Returns (allowed bool [B], matched_rules u32 [B, W])."""
    import jax.numpy as jnp

    def scan(dfa, sdfa, data, lens):
        if sdfa is not None:
            return _dfa_scan_strided(sdfa, data, lens)
        return _dfa_scan(dfa, data, lens)

    acc_m = scan(
        tables.method_dfa, tables.method_sdfa, method, method_len
    )  # [B, W]
    acc_p = scan(tables.path_dfa, tables.path_sdfa, path, path_len)
    acc_h = scan(tables.host_dfa, tables.host_sdfa, host, host_len)

    matched = (
        (acc_m | jnp.asarray(tables.absent_method)[None, :])
        & (acc_p | jnp.asarray(tables.absent_path)[None, :])
        & (acc_h | jnp.asarray(tables.absent_host)[None, :])
    )
    ident_bits = jnp.asarray(tables.ident_rules)[
        jnp.clip(ident_idx, 0, tables.ident_rules.shape[0] - 1)
    ]  # [B, W]
    matched = matched & ident_bits & jnp.where(
        known, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
    )[:, None]
    if scope_bits is not None:
        matched = matched & scope_bits
    return jnp.any(matched != 0, axis=1), matched


# ---------------------------------------------------------------------------
# host oracle + fallback
# ---------------------------------------------------------------------------


def http_rule_matches_host(
    rule: HTTPRuleSpec,
    method: bytes,
    path: bytes,
    host: bytes,
    headers: Optional[Dict[str, str]] = None,
) -> bool:
    """Host reference matcher (Python re.fullmatch ≙ Envoy regex
    HeaderMatcher full-match)."""
    import re

    if rule.method and not re.fullmatch(
        rule.method.encode(), method, re.DOTALL
    ):
        return False
    if rule.path and not re.fullmatch(rule.path.encode(), path, re.DOTALL):
        return False
    if rule.host and not re.fullmatch(rule.host.encode(), host, re.DOTALL):
        return False
    for header in rule.headers:
        # "Name: value" exact or "Name" presence (server.go:352-366)
        if ":" in header:
            name, _, value = header.partition(":")
            want = value.strip()
        else:
            name, want = header, None
        got = (headers or {}).get(name.strip().lower())
        if got is None:
            return False
        if want is not None and got != want:
            return False
    return True


def pad_requests(
    requests: Sequence[Tuple[bytes, bytes, bytes]],
    lm: int = 16,
    lp: int = 128,
    lh: int = 64,
):
    """(method, path, host) bytes → padded u8 tensors + lengths +
    overflow flags.

    A field longer than its budget is NOT silently truncated into the
    tensors-with-shorter-length (that would corrupt full-match
    semantics in both directions); the row is flagged `overflow` and
    must be routed to the host matcher (evaluate_with_host_fallback
    does this).  The tensor row still carries the truncated prefix so
    shapes stay static, but its device verdict is discarded."""
    b = len(requests)
    method = np.zeros((b, lm), dtype=np.uint8)
    path = np.zeros((b, lp), dtype=np.uint8)
    host = np.zeros((b, lh), dtype=np.uint8)
    lens = np.zeros((3, b), dtype=np.int32)
    overflow = np.zeros(b, dtype=bool)
    for i, (m, p, h) in enumerate(requests):
        overflow[i] = len(m) > lm or len(p) > lp or len(h) > lh
        m, p, h = m[:lm], p[:lp], h[:lh]
        method[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        path[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        host[i, : len(h)] = np.frombuffer(h, dtype=np.uint8)
        lens[0, i], lens[1, i], lens[2, i] = len(m), len(p), len(h)
    return method, lens[0], path, lens[1], host, lens[2], overflow


def trim_packed(
    data: "np.ndarray", lengths: "np.ndarray", min_width: int = 8
) -> "np.ndarray":
    """Slice a padded [B, L] byte tensor down to the smallest
    power-of-two column count covering every row's actual length.
    The DFA scans cost per PROCESSED byte (pad positions fold through
    the identity class but still pay their gathers/matmuls), so a
    batch of short requests should not pay the full field budget.
    Pow2 buckets keep the jit cache small."""
    data = np.asarray(data)
    need = int(np.max(lengths, initial=0))
    width = min_width
    while width < need:
        width *= 2
    return data[:, : min(width, data.shape[1])]


def evaluate_with_host_fallback(
    policy: HTTPPolicy,
    requests: Sequence[Tuple[bytes, bytes, bytes]],
    ident_idx: "np.ndarray",  # i32 [B] identity index
    known: "np.ndarray",  # bool [B]
    headers: Optional[Sequence[Optional[Dict[str, str]]]] = None,
    lm: int = 16,
    lp: int = 128,
    lh: int = 64,
) -> np.ndarray:
    """Full HTTP policy verdict: device DFAs + host-side merge.

    Reference semantics (pkg/envoy/server.go:316,448 +
    envoy/cilium_l7policy.cc): a request is allowed if ANY rule of the
    filter matches — including header-carrying rules, which the device
    tables exclude.  Three host merges over the device verdict:

      1. header rules (policy.host_rules): evaluated with re.fullmatch
         + header present/exact checks, OR-ed into the device verdict;
      2. overflow rows (fields beyond the padded budgets): the device
         verdict for those rows is discarded and recomputed from
         policy.device_rules host-side — never decided from truncated
         bytes;
      3. unknown identities stay denied.

    Returns allowed bool [B].
    """
    packed = pad_requests(requests, lm=lm, lp=lp, lh=lh)
    m, mlen, p, plen, h, hlen, overflow = packed
    allowed_dev, _ = evaluate_http_batch(
        policy.tables,
        trim_packed(m, mlen), mlen,
        trim_packed(p, plen), plen,
        trim_packed(h, hlen), hlen,
        ident_idx, known,
    )
    allowed = np.asarray(allowed_dev).copy()
    ident_idx = np.asarray(ident_idx)
    known = np.asarray(known)

    # 2: overflowed rows re-evaluate the device rules host-side.
    for i in np.nonzero(overflow)[0]:
        mm, pp, hh = requests[i]
        allowed[i] = bool(known[i]) and any(
            int(ident_idx[i]) in spec.identity_indices
            and http_rule_matches_host(spec, mm, pp, hh)
            for spec in policy.device_rules
        )

    # 1: header rules can only widen (OR semantics across rules).
    if policy.host_rules:
        for i in np.nonzero(~allowed & known)[0]:
            mm, pp, hh = requests[i]
            hdrs = headers[i] if headers is not None else None
            if any(
                int(ident_idx[i]) in spec.identity_indices
                and http_rule_matches_host(spec, mm, pp, hh, hdrs)
                for spec in policy.host_rules
            ):
                allowed[i] = True
    return allowed
