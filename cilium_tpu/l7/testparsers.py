"""The proxylib test parsers — framing edge-case consumers of the
generic parser registry.

Ports of /root/reference/proxylib/testparsers/{lineparser,blockparser,
headerparser}.go: the reference ships these to exercise the parser
framework's framing contract (partial frames, length-prefixed blocks,
multi-frame buffers, invalid lengths) independently of any real
protocol.  Registering them here proves the same contract for this
framework's registry (l7/proxylib.py) beyond the bundled memcached
parser:

  * test.lineparser — newline-delimited frames; a line passes when it
    starts with "PASS" (lineparser.go:96-104's data-driven verdict);
  * test.blockparser — "<digits>:<content>" frames where the digit
    prefix counts the WHOLE block excluding the ':'; malformed or
    short lengths are framing errors (blockparser.go getBlock);
  * test.headerparser — line frames matched against policy rules with
    HasPrefix / Contains / HasSuffix keys over the whitespace-trimmed
    line (headerparser.go HeaderRule.Matches); no rule matching ⇒
    deny (fail closed, as the reference drops with a Denied log).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

from cilium_tpu.l7.proxylib import (
    L7Request,
    ParserEntry,
    register_parser,
)


class FramingError(ValueError):
    """Invalid frame (blockparser's ERROR_INVALID_FRAME_LENGTH)."""


# -- line framing ------------------------------------------------------------


def _decode_lines(data: bytes, proto: str):
    requests: List[L7Request] = []
    consumed = 0
    while True:
        idx = data.find(b"\n", consumed)
        if idx < 0:
            break  # partial line: wait for more (lineparser MORE)
        line = data[consumed : idx + 1]
        requests.append(
            L7Request(
                proto=proto,
                fields=(
                    ("line", line.decode("latin-1")),
                ),
            )
        )
        consumed = idx + 1
    return requests, consumed


# -- test.lineparser ---------------------------------------------------------


@dataclass(frozen=True)
class PassRule:
    """Data-driven verdict: lines starting with PASS pass."""

    identity_indices: Tuple[int, ...] = ()


def _line_compile(rules: Sequence[dict], identity_indices):
    # the line parser's verdict is data-driven; one pseudo-rule per
    # selector keeps the identity gating contract
    return [PassRule(identity_indices=tuple(identity_indices))]

def _line_matches(request: L7Request, spec) -> bool:
    return request.get("line").startswith("PASS")


register_parser(
    ParserEntry(
        name="test.lineparser",
        decode_stream=lambda data: _decode_lines(
            data, "test.lineparser"
        ),
        compile_rules=_line_compile,
        rule_matches=_line_matches,
        deny_response=lambda req: b"DROPPED\n",
    )
)


# -- test.blockparser --------------------------------------------------------


def _decode_blocks(data: bytes):
    """"<digits>:<content>" frames; the digit prefix counts digits +
    content (excluding ':').  Raises FramingError on a non-numeric or
    too-short length, exactly where the reference returns
    ERROR_INVALID_FRAME_LENGTH."""
    requests: List[L7Request] = []
    consumed = 0
    while True:
        colon = data.find(b":", consumed)
        if colon < 0:
            break  # no full length prefix yet
        digits = data[consumed:colon]
        if not digits.isdigit():
            raise FramingError(f"invalid block length {digits!r}")
        block_len = int(digits)
        if block_len <= len(digits):
            raise FramingError("block length too short")
        content_len = block_len - len(digits)
        if colon + 1 + content_len > len(data):
            break  # partial frame: wait for more
        content = data[colon + 1 : colon + 1 + content_len]
        requests.append(
            L7Request(
                proto="test.blockparser",
                fields=(("block", content.decode("latin-1")),),
            )
        )
        consumed = colon + 1 + content_len
    return requests, consumed


def _block_matches(request: L7Request, spec) -> bool:
    return request.get("block").startswith("PASS")


register_parser(
    ParserEntry(
        name="test.blockparser",
        decode_stream=_decode_blocks,
        compile_rules=_line_compile,
        rule_matches=_block_matches,
        # length counts digits + content: 1 + len("DROPPED") = 8
        deny_response=lambda req: b"8:DROPPED",
    )
)


# -- test.headerparser -------------------------------------------------------


@dataclass(frozen=True)
class HeaderRule:
    """headerparser.go HeaderRule: all present fields must match the
    whitespace-trimmed line."""

    identity_indices: Tuple[int, ...] = ()
    has_prefix: str = ""
    contains: str = ""
    has_suffix: str = ""


def _header_compile(rules: Sequence[dict], identity_indices):
    specs = []
    for rule in rules:
        specs.append(
            HeaderRule(
                identity_indices=tuple(identity_indices),
                has_prefix=rule.get("HasPrefix", ""),
                contains=rule.get("Contains", ""),
                has_suffix=rule.get("HasSuffix", ""),
            )
        )
    return specs


def _header_matches(request: L7Request, spec: HeaderRule) -> bool:
    line = request.get("line").strip()
    if spec.has_prefix and not line.startswith(spec.has_prefix):
        return False
    if spec.contains and spec.contains not in line:
        return False
    if spec.has_suffix and not line.endswith(spec.has_suffix):
        return False
    return True


register_parser(
    ParserEntry(
        name="test.headerparser",
        decode_stream=lambda data: _decode_lines(
            data, "test.headerparser"
        ),
        compile_rules=_header_compile,
        rule_matches=_header_matches,
    )
)
