"""L7 protocol matchers (the proxy verdict path).

In the reference, L7 matching runs in Envoy C++ filters
(envoy/cilium_l7policy.cc) / the Go Kafka proxy (pkg/proxy/kafka.go),
fed by NPDS policy (pkg/envoy/server.go getHTTPRule: Path/Method/Host
become Envoy regex HeaderMatchers — i.e. FULL-string matches).

Here the hot path is tensorized: HTTP rules compile to per-field
union DFAs with per-rule accept bitmasks (`regex_dfa`), evaluated by
the device engine over padded request byte tensors (`http`); Kafka
rules compile to field-equality tables (`kafka`).  Pathological
regexes and header constraints fall back to host evaluation, like the
reference keeps Envoy host-side.

Generic parsers (`proxylib`) register themselves by name at import —
importing this package loads the bundled ones, as the reference's
proxylib init() hooks do.
"""

from cilium_tpu.l7 import memcached as _memcached  # noqa: F401
from cilium_tpu.l7 import testparsers as _testparsers  # noqa: F401
