"""Binary-memcached parser for the generic L7 framework.

Behavioral port of the reference proxylib parser
(/root/reference/proxylib/memcached/binary/parser.go): 24-byte binary
header (magic 0x80 request / 0x81 response), opcode at byte 1, key of
keyLength bytes after the extras; rules name an opcode or opcode
group plus at most one of keyExact / keyPrefix / keyRegex; denied
requests are answered with the 'access denied' response frame
(DeniedMsgBase, parser.go:293).

TPU-first matching (the l7/kafka.py design): opcodes become a 256-bit
rule mask (8 u32 words), exact keys intern to dense u32 ids, and the
batch evaluates as pure integer [B, R] compares on device; rules with
keyPrefix/keyRegex are host-only — the device result flags any row
whose identity owns such a rule for host fallback, so the fast path
never false-denies (nor false-allows: flagged rows are re-run, not
trusted).
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.l7.kafka import Interner
from cilium_tpu.l7.proxylib import (
    L7Request,
    ParserEntry,
    register_parser,
)

PARSER_NAME = "binarymemcache"
HEADER_SIZE = 24
REQUEST_MAGIC = 0x80
RESPONSE_MAGIC = 0x81

# parser.go:306 MemcacheOpCodeMap — names and groups to opcodes
OPCODE_MAP: Dict[str, Tuple[int, ...]] = {
    "get": (0,), "set": (1,), "add": (2,), "replace": (3,),
    "delete": (4,), "increment": (5,), "decrement": (6,), "quit": (7,),
    "flush": (8,), "getq": (9,), "noop": (10,), "version": (11,),
    "getk": (12,), "getkq": (13,), "append": (14,), "prepend": (15,),
    "stat": (16,), "setq": (17,), "addq": (18,), "replaceq": (19,),
    "deleteq": (20,), "incrementq": (21,), "decrementq": (22,),
    "quitq": (23,), "flushq": (24,), "appendq": (25,), "prependq": (26,),
    "verbosity": (27,), "touch": (28,), "gat": (29,), "gatq": (30,),
    "sasl-list-mechs": (32,), "sasl-auth": (33,), "sasl-step": (34,),
    "rget": (48,), "rset": (49,), "rsetq": (50,), "rappend": (51,),
    "rappendq": (52,), "rprepend": (53,), "rprependq": (54,),
    "rdelete": (55,), "rdeleteq": (56,), "rincr": (57,), "rincrq": (58,),
    "rdecr": (59,), "rdecrq": (60,), "set-vbucket": (61,),
    "get-vbucket": (62,), "del-vbucket": (63,), "tap-connect": (64,),
    "tap-mutation": (65,), "tap-delete": (66,), "tap-flush": (67,),
    "tap-opaque": (68,), "tap-vbucket-set": (69,),
    "tap-checkpoint-start": (70,), "tap-checkpoint-end": (71,),
    "readGroup": (0, 9, 12, 13),
    "writeGroup": (
        1, 2, 3, 4, 5, 6, 14, 15, 17, 18, 19, 20, 21, 22, 25, 26,
        28, 29, 30,
    ),
}

# parser.go:293 DeniedMsgBase: status 0x0008, body 'access denied'
DENIED_MSG = bytes(
    [0x81, 0, 0, 0, 0, 0, 0, 8, 0, 0, 0, 0x0D, 0, 0, 0, 0,
     0, 0, 0, 0, 0, 0, 0, 0]
) + b"access denied"


class MemcacheParseError(ValueError):
    pass


@dataclass(frozen=True)
class MemcacheRuleSpec:
    """One compiled rule (BinaryMemcacheRule, parser.go:32)."""

    identity_indices: frozenset
    op_codes: Tuple[int, ...]
    key_exact: str = ""
    key_prefix: str = ""
    key_regex: str = ""

    def __post_init__(self):
        object.__setattr__(
            self, "identity_indices", frozenset(self.identity_indices)
        )

    @property
    def device_expressible(self) -> bool:
        return self.key_prefix == "" and self.key_regex == ""


def compile_rules(
    dicts: Sequence[dict], identity_indices: Sequence[int]
) -> List[MemcacheRuleSpec]:
    """L7BinaryMemcacheRuleParser (parser.go:89): each dict carries
    `opCode` (required) and at most one key matcher.  An EMPTY dict
    list is the wildcard allow-all for the selector."""
    if not dicts:
        return [
            MemcacheRuleSpec(
                identity_indices=identity_indices,
                op_codes=tuple(range(256)),
            )
        ]
    specs = []
    for d in dicts:
        op_name = d.get("opCode", "")
        if op_name not in OPCODE_MAP:
            raise ValueError(
                f"unsupported or missing opCode {op_name!r}"
            )
        unknown = set(d) - {"opCode", "keyExact", "keyPrefix", "keyRegex"}
        if unknown:
            raise ValueError(f"unsupported keys: {sorted(unknown)}")
        matchers = [
            k
            for k in ("keyExact", "keyPrefix", "keyRegex")
            if d.get(k, "")
        ]
        if len(matchers) > 1:
            raise ValueError(
                f"at most one key matcher allowed, got {matchers}"
            )
        specs.append(
            MemcacheRuleSpec(
                identity_indices=identity_indices,
                op_codes=OPCODE_MAP[op_name],
                key_exact=d.get("keyExact", ""),
                key_prefix=d.get("keyPrefix", ""),
                key_regex=d.get("keyRegex", ""),
            )
        )
    return specs


def rule_matches(request: L7Request, spec: MemcacheRuleSpec) -> bool:
    """BinaryMemcacheRule.Matches (parser.go:52)."""
    opcode = int(request.get("opcode", "-1"))
    if opcode not in spec.op_codes:
        return False
    key = request.get("key")
    if spec.key_exact != "":
        return spec.key_exact == key
    if spec.key_prefix != "":
        return key.startswith(spec.key_prefix)
    if spec.key_regex != "":
        return re.search(spec.key_regex, key) is not None
    return True  # no key rule: match by opcode


def decode_stream(buf: bytes) -> Tuple[List[L7Request], int]:
    """Parse complete request frames; returns (requests, consumed).
    Trailing partial frames stay unconsumed (proxylib.MORE); a
    response-magic frame in the request direction is connection-fatal
    (parser.go getOpcodeAndKey ERROR_INVALID_FRAME_TYPE)."""
    requests = []
    off = 0
    while off + HEADER_SIZE <= len(buf):
        magic = buf[off]
        if magic != REQUEST_MAGIC:
            # includes response magic 0x81 in the request direction:
            # connection-fatal, as the reference's
            # ERROR_INVALID_FRAME_TYPE
            raise MemcacheParseError(
                f"invalid request magic 0x{magic:02x}"
            )
        opcode = buf[off + 1]
        key_len = struct.unpack_from(">H", buf, off + 2)[0]
        extras_len = buf[off + 4]
        body_len = struct.unpack_from(">I", buf, off + 8)[0]
        if extras_len + key_len > body_len:
            raise MemcacheParseError(
                f"frame claims extras {extras_len} + key {key_len} "
                f"beyond body length {body_len}"
            )
        total = HEADER_SIZE + body_len
        if off + total > len(buf):
            break  # MORE
        key = b""
        if key_len:
            ks = off + HEADER_SIZE + extras_len
            key = buf[ks : ks + key_len]
        requests.append(
            L7Request(
                proto=PARSER_NAME,
                fields=(
                    ("opcode", str(opcode)),
                    ("key", key.decode("utf-8", "replace")),
                ),
            )
        )
        off += total
    return requests, off


def encode_request(
    opcode: int, key: str = "", extras: bytes = b"", value: bytes = b""
) -> bytes:
    """Wire synthesis for tests/bench (the reverse of decode)."""
    kb = key.encode()
    body = extras + kb + value
    return (
        struct.pack(
            ">BBHBBHIIQ",
            REQUEST_MAGIC,
            opcode & 0xFF,
            len(kb),
            len(extras),
            0,
            0,
            len(body),
            0,
            0,
        )
        + body
    )


def deny_response(request: L7Request) -> bytes:
    return DENIED_MSG


@dataclass
class MemcacheDeviceTables:
    """Integer-tensor form: [R] rules with 256-bit opcode masks and
    interned exact keys; [W]-word identity membership bitmasks."""

    opcode_mask: np.ndarray  # u32 [R, 8]
    key_id: np.ndarray  # u32 [R] (0 = no exact-key constraint)
    device_ok: np.ndarray  # bool [R] (False: prefix/regex, host only)
    ident_rules: np.ndarray  # u32 [N, W] rule-membership bits
    interner: Interner
    specs: List[MemcacheRuleSpec]

    def evaluate(self, requests, ident_idx, known):
        """(allowed [B], needs_host [B]): pure integer compares on
        device; needs_host marks rows whose identity owns a
        host-only rule AND the device path denied (a prefix/regex
        rule might still allow them)."""
        import jax.numpy as jnp

        b = len(requests)
        opcode = np.zeros(b, np.int32)
        key_id = np.zeros(b, np.uint32)
        for i, request in enumerate(requests):
            opcode[i] = int(request.get("opcode", "-1"))
            key_id[i] = self.interner.lookup(request.get("key"))

        r = len(self.specs)
        if r == 0:
            return np.zeros(b, bool), np.zeros(b, bool)
        op = jnp.clip(jnp.asarray(opcode), 0, 255)
        op_word = (op >> 5).astype(jnp.int32)
        op_bit = (op & 31).astype(jnp.uint32)
        mask = jnp.asarray(self.opcode_mask)  # [R, 8]
        # select each request's mask word first ([B, R]), then test
        # the bit — no [B, R, 8] intermediate
        words = mask.T[op_word]  # [B, R]
        op_ok = ((words >> op_bit[:, None]) & 1).astype(bool)
        op_ok = op_ok & (jnp.asarray(opcode)[:, None] >= 0)

        rk = jnp.asarray(self.key_id)[None, :]
        key_ok = (rk == 0) | (
            rk == jnp.asarray(key_id)[:, None]
        )

        word = jnp.arange(r) // 32
        bit = (jnp.arange(r) % 32).astype(jnp.uint32)
        ident_bits = jnp.asarray(self.ident_rules)[
            jnp.clip(
                jnp.asarray(ident_idx), 0, self.ident_rules.shape[0] - 1
            )
        ]  # [B, W]
        rule_bit = (
            (ident_bits[:, word] >> bit[None, :]) & 1
        ).astype(bool)
        base = rule_bit & jnp.asarray(known)[:, None]

        dev_ok = jnp.asarray(self.device_ok)[None, :]
        allowed = jnp.any(base & dev_ok & op_ok & key_ok, axis=1)
        has_host_rule = jnp.any(base & ~dev_ok, axis=1)
        needs_host = has_host_rule & ~allowed
        return np.asarray(allowed), np.asarray(needs_host)


def compile_device(
    specs: Sequence[MemcacheRuleSpec], n_identities: int
) -> MemcacheDeviceTables:
    r = len(specs)
    opcode_mask = np.zeros((max(r, 1), 8), np.uint32)
    key_id = np.zeros(max(r, 1), np.uint32)
    device_ok = np.zeros(max(r, 1), bool)
    w = max((r + 31) // 32, 1)
    ident_rules = np.zeros((max(n_identities, 1), w), np.uint32)
    interner = Interner()
    for j, spec in enumerate(specs):
        for oc in spec.op_codes:
            opcode_mask[j, oc >> 5] |= np.uint32(1 << (oc & 31))
        key_id[j] = interner.intern(spec.key_exact)
        device_ok[j] = spec.device_expressible
        for idx in spec.identity_indices:
            if 0 <= idx < n_identities:
                ident_rules[idx, j >> 5] |= np.uint32(1 << (j & 31))
    return MemcacheDeviceTables(
        opcode_mask=opcode_mask,
        key_id=key_id,
        device_ok=device_ok,
        ident_rules=ident_rules,
        interner=interner,
        specs=list(specs),
    )


register_parser(
    ParserEntry(
        name=PARSER_NAME,
        decode_stream=decode_stream,
        compile_rules=compile_rules,
        rule_matches=rule_matches,
        compile_device=compile_device,
        deny_response=deny_response,
    )
)
