"""Kafka wire-format request parsing (+ response correlation).

Behavioral analog of /root/reference/pkg/kafka/request.go:88
(ReadRequest → topic extraction per API key) and
correlation_cache.go:97 (correlation-ID cache pairing responses with
their requests).  The reference parses frames with a vendored
Sarama-style decoder; here a minimal big-endian struct reader covers
the v0 request layouts of the topic-carrying keys the policy engine
checks (Produce/Fetch/ListOffsets/Metadata/OffsetCommit/OffsetFetch).

A frame that cannot be structurally parsed — unknown API key,
unsupported version, short buffer — still yields a KafkaRequest when
the generic header decodes: `parsed=False`, topics empty.  That is
exactly the reference's degraded mode, where `matchNonTopicRequests`
(policy.go:54) refuses topic rules for topic-typed keys and skips the
ClientID check (GH-3097 quirk, reproduced in kafka.py).

Wire layout (all big-endian):
  frame   := size:i32 body
  body    := api_key:i16 api_version:i16 correlation_id:i32
             client_id:nullable_string payload
  string  := len:i16 bytes           (len == -1 → null)
  array   := count:i32 element*
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.l7.kafka import KafkaRequest

MAX_FRAME = 64 * 1024 * 1024  # sarama MaxRequestSize analog


class KafkaParseError(ValueError):
    """Structurally malformed frame — connection-fatal in the
    reference proxy (an unparseable header cannot be re-framed)."""


class KafkaIncompleteFrame(KafkaParseError):
    """Not enough bytes for a complete frame — the caller should keep
    the remainder buffered and retry when more data arrives."""


class _Reader:
    __slots__ = ("buf", "off", "end")

    def __init__(self, buf: bytes, off: int, end: int) -> None:
        self.buf = buf
        self.off = off
        self.end = end

    def _take(self, n: int) -> bytes:
        if self.off + n > self.end:
            raise KafkaParseError("short buffer")
        out = self.buf[self.off : self.off + n]
        self.off += n
        return out

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n == -1:
            return None
        if n < 0:
            raise KafkaParseError("negative string length")
        return self._take(n).decode("utf-8", "replace")

    def kbytes(self) -> Optional[bytes]:
        n = self.i32()
        if n == -1:
            return None
        if n < 0:
            raise KafkaParseError("negative bytes length")
        return self._take(n)

    def array_count(self) -> int:
        n = self.i32()
        if n < 0 or n > (self.end - self.off):
            raise KafkaParseError("bad array count")
        return n


def _topics_produce(r: _Reader) -> List[str]:
    r.i16()  # required_acks
    r.i32()  # timeout
    topics = []
    for _ in range(r.array_count()):
        topics.append(r.string() or "")
        for _ in range(r.array_count()):  # partitions
            r.i32()  # partition
            r.kbytes()  # message set
    return topics


def _topics_fetch(r: _Reader) -> List[str]:
    r.i32()  # replica_id
    r.i32()  # max_wait_time
    r.i32()  # min_bytes
    topics = []
    for _ in range(r.array_count()):
        topics.append(r.string() or "")
        for _ in range(r.array_count()):
            r.i32()  # partition
            r.i64()  # fetch_offset
            r.i32()  # max_bytes
    return topics


def _topics_list_offsets(r: _Reader) -> List[str]:
    r.i32()  # replica_id
    topics = []
    for _ in range(r.array_count()):
        topics.append(r.string() or "")
        for _ in range(r.array_count()):
            r.i32()  # partition
            r.i64()  # timestamp
            r.i32()  # max_num_offsets
    return topics


def _topics_metadata(r: _Reader) -> List[str]:
    return [r.string() or "" for _ in range(r.array_count())]


def _topics_offset_commit(r: _Reader) -> List[str]:
    r.string()  # group id
    topics = []
    for _ in range(r.array_count()):
        topics.append(r.string() or "")
        for _ in range(r.array_count()):
            r.i32()  # partition
            r.i64()  # offset
            r.string()  # metadata
    return topics


def _topics_offset_fetch(r: _Reader) -> List[str]:
    r.string()  # group id
    topics = []
    for _ in range(r.array_count()):
        topics.append(r.string() or "")
        for _ in range(r.array_count()):
            r.i32()  # partition
    return topics


# api_key → (max structurally-supported version, payload parser)
_PARSERS = {
    0: (0, _topics_produce),
    1: (0, _topics_fetch),
    2: (0, _topics_list_offsets),
    3: (0, _topics_metadata),
    8: (0, _topics_offset_commit),
    9: (0, _topics_offset_fetch),
}


def decode_request(buf: bytes, off: int = 0) -> Tuple[KafkaRequest, int, int]:
    """One framed request starting at `buf[off]`.

    Returns (request, correlation_id, next_offset).  Raises
    KafkaParseError only when even the generic header is unreadable
    (the connection-fatal case in the reference proxy); a readable
    header with an unparseable payload degrades to parsed=False.
    """
    if off + 4 > len(buf):
        raise KafkaIncompleteFrame("short frame header")
    size = struct.unpack(">i", buf[off : off + 4])[0]
    if size < 8 or size > MAX_FRAME:
        raise KafkaParseError(f"bad frame size {size}")
    if off + 4 + size > len(buf):
        raise KafkaIncompleteFrame("partial frame body")
    end = off + 4 + size
    r = _Reader(buf, off + 4, end)
    api_key = r.i16()
    api_version = r.i16()
    correlation_id = r.i32()
    client_id = r.string() or ""
    if api_key < 0:
        # int16 api keys are non-negative on the wire; a negative key
        # would alias into the device matcher's clipped kind range
        # (kafka.py evaluate_kafka_batch) and false-allow — treat as a
        # malformed header, like the reference's sarama decoder
        raise KafkaParseError(f"negative api_key {api_key}")

    parsed = False
    topics: Sequence[str] = ()
    entry = _PARSERS.get(api_key)
    if entry is not None and api_version <= entry[0]:
        try:
            topics = tuple(entry[1](r))
            parsed = True
        except KafkaParseError:
            parsed = False
            topics = ()
    return (
        KafkaRequest(
            kind=api_key,
            version=api_version,
            client_id=client_id,
            topics=tuple(topics),
            parsed=parsed,
        ),
        correlation_id,
        end,
    )


def decode_stream(buf: bytes) -> List[Tuple[KafkaRequest, int]]:
    """All complete frames in a connection buffer → [(request, correlation_id)].
    Trailing partial frames are ignored (a real proxy would keep them
    buffered until more bytes arrive); a structurally malformed frame
    propagates KafkaParseError — connection-fatal, never silently
    skipped."""
    out = []
    off = 0
    while off + 4 <= len(buf):
        try:
            req, cid, off = decode_request(buf, off)
        except KafkaIncompleteFrame:
            break
        out.append((req, cid))
    return out


# ---------------------------------------------------------------------------
# encoding (for tests / bench workload synthesis and deny responses)
# ---------------------------------------------------------------------------


def _enc_string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def encode_request(
    request: KafkaRequest, correlation_id: int = 0
) -> bytes:
    """KafkaRequest → wire frame (v0 layouts, empty partition arrays —
    partitions don't affect policy)."""
    body = struct.pack(
        ">hhi", request.kind, request.version, correlation_id
    ) + _enc_string(request.client_id or None)
    entry = _PARSERS.get(request.kind)
    if entry is not None and request.version <= entry[0]:
        if request.kind == 0:
            body += struct.pack(">hi", 1, 1000)  # acks, timeout
            body += struct.pack(">i", len(request.topics))
            for t in request.topics:
                body += _enc_string(t) + struct.pack(">i", 0)
        elif request.kind == 1:
            body += struct.pack(">iii", -1, 100, 1)
            body += struct.pack(">i", len(request.topics))
            for t in request.topics:
                body += _enc_string(t) + struct.pack(">i", 0)
        elif request.kind == 2:
            body += struct.pack(">i", -1)
            body += struct.pack(">i", len(request.topics))
            for t in request.topics:
                body += _enc_string(t) + struct.pack(">i", 0)
        elif request.kind == 3:
            body += struct.pack(">i", len(request.topics))
            for t in request.topics:
                body += _enc_string(t)
        elif request.kind in (8, 9):
            body += _enc_string("group")
            body += struct.pack(">i", len(request.topics))
            for t in request.topics:
                body += _enc_string(t) + struct.pack(">i", 0)
    return struct.pack(">i", len(body)) + body


def encode_deny_response(request: KafkaRequest, correlation_id: int) -> bytes:
    """Minimal error response for a denied request — the
    'broker-in-the-middle' deny of pkg/proxy/kafka.go (the reference
    synthesizes a per-kind error response; error code 29 =
    TopicAuthorizationFailed)."""
    body = struct.pack(">i", correlation_id)
    if request.kind == 0:  # produce v0: [topic [partition err offset]]
        body += struct.pack(">i", len(request.topics))
        for t in request.topics:
            body += _enc_string(t) + struct.pack(">i", 0)
    else:
        body += struct.pack(">h", 29)
    return struct.pack(">i", len(body)) + body


class CorrelationCache:
    """correlation_cache.go:97 — outstanding request bookkeeping so
    responses (which carry only the correlation id) can be matched
    back to the request that the policy verdict was computed for."""

    def __init__(self, max_outstanding: int = 4096) -> None:
        self._pending: Dict[int, KafkaRequest] = {}
        self._max = max_outstanding

    def record(self, correlation_id: int, request: KafkaRequest) -> None:
        if len(self._pending) >= self._max:
            raise KafkaParseError("too many outstanding requests")
        if correlation_id in self._pending:
            # the reference sidesteps duplicates by rewriting IDs to
            # unique sequence numbers (correlation_cache.go
            # HandleRequest); we keep client IDs on the wire, so a
            # duplicate would mis-pair a response — reject it
            raise KafkaParseError(
                f"duplicate correlation_id {correlation_id}"
            )
        self._pending[correlation_id] = request

    def match(self, correlation_id: int) -> Optional[KafkaRequest]:
        return self._pending.pop(correlation_id, None)

    def __len__(self) -> int:
        return len(self._pending)
