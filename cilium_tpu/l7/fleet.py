"""Fleet-scoped L7 policy: ONE compiled matcher set for every redirect.

The reference hands each redirected flow to its proxy listener, which
enforces the L7 rules of the L4Filter that redirected it
(/root/reference/envoy/cilium_l7policy.cc:193 for HTTP,
/root/reference/pkg/proxy/kafka.go:116 for Kafka).  A per-redirect
device dispatch would cost one program launch per (endpoint, port);
instead the union DFA / field tensors span the WHOLE fleet and a
per-flow scope mask — each compiled rule lives in exactly one
(endpoint, direction, L4 slot) scope — restricts matching to the
redirecting filter's rules.  One jitted program then evaluates any mix
of redirected flows, which is what lets the replay loop run L7
verdicts inline with the fused datapath step (the combined
datapath+proxy number of BASELINE config 5).

Scope tables are indexed by the datapath's own outputs: the fused
verdict exposes the matched L4 slot (`DatapathVerdicts.l4_slot`), so a
redirected flow's scope is (ep_index, direction, l4_slot) with no
extra probes.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

import numpy as np

from cilium_tpu.l7.http import (
    HTTPPolicy,
    compile_http_rules,
    evaluate_http_batch,
    resolve_selector_indices,
    specs_from_filter,
)
from cilium_tpu.l7.kafka import (
    KafkaRuleSpec,
    KafkaTables,
    compile_kafka_rules,
    evaluate_kafka_batch,
    rule_spec_from_port_rule,
)
from cilium_tpu.policy.l4 import (
    PARSER_TYPE_HTTP as PARSER_HTTP,
    PARSER_TYPE_KAFKA as PARSER_KAFKA,
)

PARSER_NONE_ID = 0
PARSER_HTTP_ID = 1
PARSER_KAFKA_ID = 2


@dataclass
class FleetL7:
    """Fleet-wide compiled L7 matchers + per-(ep, dir, slot) scoping."""

    http: Optional[HTTPPolicy]
    kafka: Optional[KafkaTables]
    scope_http: np.ndarray  # u32 [E, 2, Kg, Wh] rule-scope bits
    scope_kafka: np.ndarray  # u32 [E, 2, Kg, Wk]
    parser_kind: np.ndarray  # u8 [E, 2, Kg] PARSER_*_ID


def compile_fleet_l7(daemon) -> FleetL7:
    """Walk every endpoint's desired L4 policy, collect redirect
    filters' L7 rules tagged with their (ep, dir, slot) scope, and
    compile one fleet-wide matcher set per parser."""
    id_index, n_identities = daemon.endpoint_manager.identity_index()
    _, tables, ep_index = daemon.endpoint_manager.published()
    if tables is None:
        raise ValueError("no published tables — regenerate first")
    e_count, _, kg = tables.l4_meta.shape
    port_slot = tables.port_slot  # u16 [256, 65536]
    cache = daemon.identity_cache()
    sel_cache = daemon.selector_cache

    http_specs: List = []
    kafka_specs: List[KafkaRuleSpec] = []
    parser_kind = np.zeros((e_count, 2, kg), np.uint8)

    from cilium_tpu.compiler.tables import NO_SLOT

    for ep in daemon.endpoint_manager.endpoints():
        e = ep_index.get(ep.id)
        l4pol = ep.desired_l4_policy
        if e is None or l4pol is None:
            continue
        for dirv, pmap in ((0, l4pol.ingress), (1, l4pol.egress)):
            for l4 in pmap.values():
                if not l4.is_redirect():
                    continue
                j = int(port_slot[l4.u8proto & 0xFF, l4.port])
                if j == int(NO_SLOT):
                    continue  # filter not realized in the slot space
                scope = (e, dirv, j)
                if l4.l7_parser == PARSER_KAFKA:
                    parser_kind[e, dirv, j] = PARSER_KAFKA_ID
                    for selector, l7 in l4.l7_rules_per_ep.items():
                        indices = resolve_selector_indices(
                            selector, cache, id_index, sel_cache
                        )
                        rules = l7.kafka or []
                        if not rules:
                            kafka_specs.append(
                                KafkaRuleSpec(
                                    identity_indices=indices,
                                    scope_key=scope,
                                )
                            )
                        for rule in rules:
                            kafka_specs.append(
                                replace(
                                    rule_spec_from_port_rule(
                                        rule, indices
                                    ),
                                    scope_key=scope,
                                )
                            )
                elif l4.l7_parser == PARSER_HTTP:
                    parser_kind[e, dirv, j] = PARSER_HTTP_ID
                    for spec in specs_from_filter(
                        l4, cache, id_index, sel_cache
                    ):
                        http_specs.append(
                            replace(spec, scope_key=scope)
                        )
                # generic proxylib parsers stay on their per-redirect
                # wire path (l7/proxylib.py); the fleet fast path
                # covers the two tensorized protocols

    http = (
        compile_http_rules(http_specs, n_identities)
        if http_specs
        else None
    )
    kafka = (
        compile_kafka_rules(kafka_specs, n_identities)
        if kafka_specs
        else None
    )

    def scope_table(rules, n_rules) -> np.ndarray:
        w = max(1, -(-max(n_rules, 1) // 32))
        table = np.zeros((e_count, 2, kg, w), np.uint32)
        for r, spec in enumerate(rules):
            if spec.scope_key is None:
                continue
            e, dirv, j = spec.scope_key
            table[e, dirv, j, r // 32] |= np.uint32(1 << (r % 32))
        return table

    scope_http = scope_table(
        http.device_rules if http else [], http.tables.n_rules if http else 0
    )
    scope_kafka = scope_table(
        kafka.specs if kafka else [], kafka.n_rules if kafka else 0
    )
    if http and http.host_rules:
        raise ValueError(
            "fleet L7 compile does not support header rules on the "
            "device path (host_rules present)"
        )
    return FleetL7(
        http=http,
        kafka=kafka,
        scope_http=scope_http,
        scope_kafka=scope_kafka,
        parser_kind=parser_kind,
    )


def evaluate_fleet_l7(
    fleet: FleetL7,
    ep_index,  # i32 [B]
    direction,  # i32 [B]
    l4_slot,  # i32 [B] from DatapathVerdicts.l4_slot
    ident_idx,  # i32 [B]
    known,  # bool [B]
    http_fields: Optional[Tuple] = None,  # (m, ml, p, pl, h, hl)
    kafka_fields: Optional[Tuple] = None,  # pad_kafka_requests order
):
    """L7 verdicts for a batch of redirected flows (traced; call
    inside a jit).  Returns allowed bool [B]: flows whose scope has no
    parser are denied (a redirect with no compiled policy must fail
    closed, as the proxy denies without a NetworkPolicy)."""
    import jax.numpy as jnp

    e_count, _, kg = fleet.parser_kind.shape
    lin = (
        ep_index.astype(jnp.int32) * (2 * kg)
        + direction.astype(jnp.int32) * kg
        + jnp.clip(l4_slot, 0, kg - 1)
    )
    kind = jnp.asarray(fleet.parser_kind).reshape(-1)[lin]
    allowed = jnp.zeros(ep_index.shape, bool)
    if fleet.http is not None and http_fields is not None:
        wh = fleet.scope_http.shape[-1]
        scope = jnp.asarray(fleet.scope_http).reshape(-1, wh)[lin]
        ok, _ = evaluate_http_batch(
            fleet.http.tables, *http_fields, ident_idx, known,
            scope_bits=scope,
        )
        allowed = jnp.where(kind == PARSER_HTTP_ID, ok, allowed)
    if fleet.kafka is not None and kafka_fields is not None:
        wk = fleet.scope_kafka.shape[-1]
        scope = jnp.asarray(fleet.scope_kafka).reshape(-1, wk)[lin]
        ok = evaluate_kafka_batch(
            fleet.kafka, *kafka_fields, ident_idx, known,
            scope_bits=scope,
        )
        allowed = jnp.where(kind == PARSER_KAFKA_ID, ok, allowed)
    return allowed
