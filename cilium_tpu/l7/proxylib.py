"""Generic L7 parser framework — the proxylib analog.

The reference's extensibility story is proxylib: a parser registered
by name gets wire bytes per connection (OnNewConnection/OnData,
/root/reference/proxylib/proxylib.go:57,142) and matches parsed
requests against NPDS-downloaded key/value rules
(/root/reference/proxylib/proxylib/policymap.go:150).  Policy rules
carry `l7proto` + a list of key/value dicts (api/l7.go PortRuleL7),
which this framework dispatches to the registered parser's rule
compiler and matcher.

TPU-first split, same as the Kafka design (l7/kafka.py): parsers
compile their rules into integer tensors wherever the match is
tensorizable (exact-value fields via string interning, set-membership
via bitmasks), batch-evaluate on device, and host-fallback only the
rows the device form cannot express (regex/prefix rules, oversized
requests).  A parser that provides no device matcher simply runs its
host matcher — the registry contract is the extension point, not the
acceleration.

Registered parsers: `binarymemcache` (l7/memcached.py — the reference
proxylib's memcached binary parser,
/root/reference/proxylib/memcached/binary/parser.go:142).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class L7Request:
    """One parsed request: the protocol name plus the parser's field
    dict (the cilium.L7LogEntry 'fields' shape)."""

    proto: str
    fields: Tuple[Tuple[str, str], ...]

    def get(self, key: str, default: str = "") -> str:
        for k, v in self.fields:
            if k == key:
                return v
        return default


@dataclass
class ParserEntry:
    """Registry row (proxylib.RegisterParserFactory +
    RegisterL7RuleParser collapsed into one registration)."""

    name: str
    # bytes → ([parsed requests], consumed bytes)
    decode_stream: Callable[[bytes], Tuple[List[L7Request], int]]
    # rule dicts + identity indices → list of compiled rule specs
    compile_rules: Callable[[Sequence[dict], Sequence[int]], list]
    # host matcher: (request, spec) → bool
    rule_matches: Callable[[L7Request, object], bool]
    # optional device compiler: (specs, n_identities) → tables with
    # an `evaluate(requests, ident_idx, known) -> allowed [B]` —
    # None = host-only parser
    compile_device: Optional[Callable[[list, int], object]] = None
    # denied-response synthesizer (the broker-in-the-middle deny)
    deny_response: Optional[Callable[[L7Request], bytes]] = None


_REGISTRY: Dict[str, ParserEntry] = {}


def register_parser(entry: ParserEntry) -> None:
    """proxylib.RegisterParserFactory: last registration wins, as the
    reference's init() hooks overwrite by name."""
    _REGISTRY[entry.name] = entry


def get_parser(name: str) -> ParserEntry:
    entry = _REGISTRY.get(name)
    if entry is None:
        raise KeyError(
            f"no L7 parser registered for l7proto {name!r} "
            f"(known: {sorted(_REGISTRY)})"
        )
    return entry


def known_parsers() -> List[str]:
    return sorted(_REGISTRY)


@dataclass
class GenericL7Tables:
    """Compiled per-redirect state for a generic parser: the specs
    (host path), per-identity rule-membership bitmask, and the
    parser's device tables when it provides them."""

    parser: ParserEntry
    specs: list
    n_identities: int
    device: object = None

    def identity_rules(self, ident_idx: int) -> list:
        return [
            s
            for s in self.specs
            if ident_idx in s.identity_indices
        ]


def compile_generic_rules(
    l7proto: str,
    per_selector: Sequence[Tuple[Sequence[int], Sequence[dict]]],
    n_identities: int,
) -> GenericL7Tables:
    """Lower {selector identity-indices → rule dicts} for one
    redirect.  An empty dict list is the L7 allow-all wildcard, like
    an empty kafka/http rule set."""
    parser = get_parser(l7proto)
    specs: list = []
    for indices, dicts in per_selector:
        specs.extend(parser.compile_rules(dicts, indices))
    device = (
        parser.compile_device(specs, n_identities)
        if parser.compile_device is not None
        else None
    )
    return GenericL7Tables(
        parser=parser,
        specs=specs,
        n_identities=n_identities,
        device=device,
    )


def matches_rules_host(
    tables: GenericL7Tables, request: L7Request, ident_idx: int
) -> bool:
    """proxylib policymap matching: any rule of the identity matches
    (wildcard specs match everything)."""
    for spec in tables.identity_rules(ident_idx):
        if tables.parser.rule_matches(request, spec):
            return True
    return False


def evaluate_requests(
    tables: GenericL7Tables,
    requests: Sequence[L7Request],
    ident_idx,
    known,
) -> np.ndarray:
    """Batched verdicts: device path when the parser compiled one,
    host loop otherwise; device-inexpressible rows fall back to the
    host matcher (the parser's device tables flag them)."""
    ident_idx = np.asarray(ident_idx)
    known = np.asarray(known)
    if tables.device is not None:
        allowed, needs_host = tables.device.evaluate(
            requests, ident_idx, known
        )
        allowed = np.asarray(allowed).copy()
        for i in np.nonzero(np.asarray(needs_host))[0]:
            allowed[i] = bool(known[i]) and matches_rules_host(
                tables, requests[i], int(ident_idx[i])
            )
        return allowed
    out = np.zeros(len(requests), dtype=bool)
    for i, request in enumerate(requests):
        out[i] = bool(known[i]) and matches_rules_host(
            tables, request, int(ident_idx[i])
        )
    return out
