"""POSIX-ERE-subset regex → byte-class DFA compiler.

Compiles the reference's HTTP rule regexes (pkg/policy/api/http.go:28
"extended POSIX regex", enforced FULL-match by Envoy's
HeaderMatcher_RegexMatch, pkg/envoy/server.go:332) into dense integer
transition tables the TPU engine can step with gathers:

  parse (recursive descent ERE) → Thompson NFA → byte-class
  compression → subset construction → Moore minimization.

Union automata: `compile_union` builds ONE DFA for a list of regexes
whose accept states carry a bitmask of which patterns matched — the
union of R rules costs one pass instead of R (SURVEY.md §7 step 3).

Unsupported constructs (backrefs, lookaround, internal anchors,
inline flags) raise RegexUnsupported; state blowup past `max_states`
raises RegexTooComplex.  Callers fall back to host `re` evaluation —
mirroring how the reference keeps L7 matching host-side in Envoy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

ALL_BYTES = (1 << 256) - 1
DEFAULT_MAX_STATES = 4096
# Dead state is always state 0 in the emitted tables.
DEAD = 0


class RegexUnsupported(ValueError):
    """Construct outside the supported ERE subset."""


class RegexTooComplex(ValueError):
    """DFA state count exceeded the cap."""


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Node:
    pass


@dataclass
class Char(Node):
    mask: int  # 256-bit set of accepted bytes


@dataclass
class Concat(Node):
    parts: List[Node]


@dataclass
class Alt(Node):
    options: List[Node]


@dataclass
class Repeat(Node):
    node: Node
    lo: int
    hi: Optional[int]  # None = unbounded


@dataclass
class Empty(Node):
    pass


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_SPECIAL = set("|()[]{}*+?.^$\\")

_PERL_CLASSES = {
    "d": sum(1 << b for b in range(ord("0"), ord("9") + 1)),
    "w": (
        sum(1 << b for b in range(ord("0"), ord("9") + 1))
        | sum(1 << b for b in range(ord("a"), ord("z") + 1))
        | sum(1 << b for b in range(ord("A"), ord("Z") + 1))
        | (1 << ord("_"))
    ),
    "s": sum(1 << ord(c) for c in " \t\n\r\f\v"),
}
_PERL_CLASSES["D"] = ALL_BYTES & ~_PERL_CLASSES["d"]
_PERL_CLASSES["W"] = ALL_BYTES & ~_PERL_CLASSES["w"]
_PERL_CLASSES["S"] = ALL_BYTES & ~_PERL_CLASSES["s"]

_POSIX_CLASSES = {
    "alpha": sum(1 << b for b in range(256) if chr(b).isalpha() and b < 128),
    "digit": _PERL_CLASSES["d"],
    "alnum": sum(
        1 << b for b in range(128) if chr(b).isalnum()
    ),
    "upper": sum(1 << b for b in range(ord("A"), ord("Z") + 1)),
    "lower": sum(1 << b for b in range(ord("a"), ord("z") + 1)),
    "space": _PERL_CLASSES["s"],
    "blank": (1 << ord(" ")) | (1 << ord("\t")),
    "punct": sum(
        1 << b
        for b in range(33, 127)
        if not chr(b).isalnum()
    ),
    "xdigit": (
        _PERL_CLASSES["d"]
        | sum(1 << b for b in range(ord("a"), ord("f") + 1))
        | sum(1 << b for b in range(ord("A"), ord("F") + 1))
    ),
    "print": sum(1 << b for b in range(32, 127)),
    "graph": sum(1 << b for b in range(33, 127)),
    "cntrl": sum(1 << b for b in range(32)) | (1 << 127),
}

# '.' matches any byte except newline (Go regexp / Python re default).
DOT_MASK = ALL_BYTES & ~(1 << ord("\n"))


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self) -> Node:
        # Leading ^ / trailing $ are redundant under full-match.
        if self.peek() == "^":
            self.next()
        node = self.parse_alt()
        if self.i < len(self.p):
            raise RegexUnsupported(
                f"unexpected {self.p[self.i]!r} at {self.i} in {self.p!r}"
            )
        return node

    def parse_alt(self) -> Node:
        options = [self.parse_concat()]
        while self.peek() == "|":
            self.next()
            options.append(self.parse_concat())
        return options[0] if len(options) == 1 else Alt(options)

    def parse_concat(self) -> Node:
        parts: List[Node] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            if c == "$":
                # Valid only at the very end (full-match makes it a
                # no-op); elsewhere it's an internal anchor.
                self.next()
                nxt = self.peek()
                if nxt is not None and nxt not in "|)":
                    raise RegexUnsupported("internal $ anchor")
                continue
            if c == "^":
                raise RegexUnsupported("internal ^ anchor")
            parts.append(self.parse_repeat())
        if not parts:
            return Empty()
        return parts[0] if len(parts) == 1 else Concat(parts)

    def parse_repeat(self) -> Node:
        atom = self.parse_atom()
        while True:
            c = self.peek()
            if c == "*":
                self.next()
                atom = Repeat(atom, 0, None)
            elif c == "+":
                self.next()
                atom = Repeat(atom, 1, None)
            elif c == "?":
                self.next()
                atom = Repeat(atom, 0, 1)
            elif c == "{":
                save = self.i
                rep = self._try_brace()
                if rep is None:
                    self.i = save
                    break
                lo, hi = rep
                if hi is not None and (hi < lo or hi > 255):
                    raise RegexUnsupported("bad {m,n} bounds")
                atom = Repeat(atom, lo, hi)
            else:
                break
            # Non-greedy suffixes don't change the matched LANGUAGE,
            # only submatch boundaries — accept and ignore for a
            # recognizer ... but flag them to stay conservative.
            if self.peek() == "?":
                raise RegexUnsupported("non-greedy quantifier")
        return atom

    def _try_brace(self) -> Optional[Tuple[int, Optional[int]]]:
        # consume '{'; return None if not a valid counted repeat
        # (Go/POSIX treat a non-numeric '{' literally).
        self.next()
        digits = ""
        while self.peek() is not None and self.peek().isdigit():
            digits += self.next()
        if self.peek() == "}":
            if not digits:
                return None
            self.next()
            n = int(digits)
            return (n, n)
        if self.peek() == ",":
            self.next()
            digits2 = ""
            while self.peek() is not None and self.peek().isdigit():
                digits2 += self.next()
            if self.peek() == "}" and digits:
                self.next()
                lo = int(digits)
                hi = int(digits2) if digits2 else None
                return (lo, hi)
        return None

    def parse_atom(self) -> Node:
        c = self.peek()
        if c is None:
            return Empty()
        if c == "(":
            self.next()
            if self.peek() == "?":
                # (?:...) non-capturing is fine; other (?...) are not.
                self.next()
                if self.peek() == ":":
                    self.next()
                else:
                    raise RegexUnsupported("inline flags / lookaround")
            node = self.parse_alt()
            if self.peek() != ")":
                raise RegexUnsupported("unbalanced paren")
            self.next()
            return node
        if c == "[":
            return self.parse_class()
        if c == ".":
            self.next()
            return Char(DOT_MASK)
        if c == "\\":
            self.next()
            return Char(self.parse_escape())
        if c in "*+?{":
            if c == "{":
                # literal '{' when not a valid counted repeat
                self.next()
                return Char(1 << ord("{"))
            raise RegexUnsupported(f"dangling quantifier {c!r}")
        self.next()
        return Char(1 << (ord(c) & 0xFF)) if ord(c) < 256 else Char(
            self._utf8_mask(c)
        )

    def _utf8_mask(self, c: str) -> int:
        raise RegexUnsupported("non-ASCII literal")

    def parse_escape(self) -> int:
        c = self.peek()
        if c is None:
            raise RegexUnsupported("trailing backslash")
        self.next()
        if c in _PERL_CLASSES:
            return _PERL_CLASSES[c]
        simple = {
            "n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
            "a": "\a", "0": "\0",
        }
        if c in simple:
            return 1 << ord(simple[c])
        if c == "x":
            h = ""
            while len(h) < 2 and self.peek() is not None and self.peek() in "0123456789abcdefABCDEF":
                h += self.next()
            if not h:
                raise RegexUnsupported(r"bad \x escape")
            return 1 << int(h, 16)
        if c.isdigit():
            raise RegexUnsupported("backreference")
        if c.isalpha():
            raise RegexUnsupported(f"unsupported escape \\{c}")
        return 1 << (ord(c) & 0xFF)

    def parse_class(self) -> Node:
        self.next()  # '['
        negate = False
        if self.peek() == "^":
            negate = True
            self.next()
        mask = 0
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexUnsupported("unterminated class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            if c == "[" and self.p[self.i : self.i + 2] == "[:":
                end = self.p.find(":]", self.i)
                if end < 0:
                    raise RegexUnsupported("bad [: :] class")
                name = self.p[self.i + 2 : end]
                if name not in _POSIX_CLASSES:
                    raise RegexUnsupported(f"unknown class [:{name}:]")
                mask |= _POSIX_CLASSES[name]
                self.i = end + 2
                continue
            if c == "\\":
                self.next()
                m = self.parse_escape()
                # range like \x41-\x5a
                if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                    if bin(m).count("1") != 1:
                        raise RegexUnsupported("class range from multi-set")
                    lo = m.bit_length() - 1
                    self.next()
                    hi = self._class_endpoint()
                    mask |= self._range_mask(lo, hi)
                else:
                    mask |= m
                continue
            self.next()
            if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                self.next()
                hi = self._class_endpoint()
                mask |= self._range_mask(ord(c), hi)
            else:
                mask |= 1 << (ord(c) & 0xFF)
        if negate:
            mask = ALL_BYTES & ~mask
        return Char(mask)

    def _class_endpoint(self) -> int:
        c = self.next()
        if c == "\\":
            m = self.parse_escape()
            if bin(m).count("1") != 1:
                raise RegexUnsupported("class range to multi-set")
            return m.bit_length() - 1
        return ord(c)

    @staticmethod
    def _range_mask(lo: int, hi: int) -> int:
        if hi < lo or hi > 255:
            raise RegexUnsupported("bad class range")
        return sum(1 << b for b in range(lo, hi + 1))


def parse(pattern: str) -> Node:
    return _Parser(pattern).parse()


# ---------------------------------------------------------------------------
# Thompson NFA
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[int, int]]] = []  # (mask, target)

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add(self, node: Node, start: int, end: int) -> None:
        """Wire `node` between start and end."""
        if isinstance(node, Empty):
            self.eps[start].append(end)
        elif isinstance(node, Char):
            self.trans[start].append((node.mask, end))
        elif isinstance(node, Concat):
            cur = start
            for part in node.parts[:-1]:
                nxt = self.new_state()
                self.add(part, cur, nxt)
                cur = nxt
            self.add(node.parts[-1], cur, end)
        elif isinstance(node, Alt):
            for option in node.options:
                self.add(option, start, end)
        elif isinstance(node, Repeat):
            # bounded repeats were rewritten by _expand_bounded
            assert node.hi is None, "bounded Repeat must be pre-expanded"
            cur = start
            for _ in range(node.lo):
                nxt = self.new_state()
                self.add(node.node, cur, nxt)
                cur = nxt
            loop = self.new_state()
            self.eps[cur].append(loop)
            self.add(node.node, loop, loop)
            self.eps[loop].append(end)
        else:  # pragma: no cover
            raise AssertionError(node)


def _expand_bounded(node: Node) -> Node:
    """Rewrite Repeat(lo, hi≠None) into concats/options so the NFA
    builder only sees unbounded loops."""
    if isinstance(node, Repeat):
        inner = _expand_bounded(node.node)
        if node.hi is None:
            return Repeat(inner, node.lo, None)
        parts: List[Node] = [inner] * node.lo
        for _ in range(node.hi - node.lo):
            parts.append(Alt([inner, Empty()]))
        if not parts:
            return Empty()
        return Concat(parts) if len(parts) > 1 else parts[0]
    if isinstance(node, Concat):
        return Concat([_expand_bounded(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_expand_bounded(o) for o in node.options])
    return node


# ---------------------------------------------------------------------------
# DFA
# ---------------------------------------------------------------------------


@dataclass
class DFA:
    """Dense byte-class DFA.

    trans  u16 [n_states, n_classes]   state 0 = dead (all self-loops)
    accept u32 [n_states, n_words]     per-pattern accept bitmask,
                                       pattern i → word i//32 bit i%32
    classes u8 [256]                   byte → class
    start  int
    """

    trans: np.ndarray
    accept: np.ndarray
    classes: np.ndarray
    start: int

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    @property
    def n_words(self) -> int:
        return self.accept.shape[1]

    def run(self, data: bytes) -> int:
        """Host reference stepping; returns the accept bitmask (an
        arbitrary-width python int assembled from the accept words)."""
        s = self.start
        for b in data:
            s = int(self.trans[s, self.classes[b]])
        out = 0
        for w in range(self.accept.shape[1]):
            out |= int(self.accept[s, w]) << (32 * w)
        return out


def compile_union(
    patterns: Sequence[str], max_states: int = DEFAULT_MAX_STATES
) -> DFA:
    """One DFA accepting the union of full-match patterns, accept
    states labeled with the bitmask of patterns matched (multi-word:
    pattern i sets bit i%32 of accept word i//32 — there is no
    32-pattern cap; wide unions cost accept-table width, not states)."""
    n_words = max(1, -(-len(patterns) // 32))

    nfa = _NFA()
    start = nfa.new_state()
    accept_of: Dict[int, int] = {}  # nfa state -> pattern bit
    for bit, pattern in enumerate(patterns):
        node = _expand_bounded(parse(pattern))
        acc = nfa.new_state()
        nfa.add(node, start, acc)
        accept_of[acc] = 1 << bit

    # -- byte classes: partition 0-255 by the set of NFA masks that
    # contain each byte ------------------------------------------------------
    masks = sorted(
        {mask for trans in nfa.trans for (mask, _) in trans}
    )
    signatures: Dict[Tuple[bool, ...], int] = {}
    classes = np.zeros(256, dtype=np.uint8)
    for b in range(256):
        sig = tuple(bool(mask >> b & 1) for mask in masks)
        if sig not in signatures:
            signatures[sig] = len(signatures)
        classes[b] = signatures[sig]
    n_classes = max(len(signatures), 1)
    class_byte = [0] * n_classes  # a representative byte per class
    for b in range(255, -1, -1):
        class_byte[classes[b]] = b

    # -- epsilon closures ----------------------------------------------------
    def closure(states: FrozenSet[int]) -> FrozenSet[int]:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    # -- subset construction -------------------------------------------------
    dead = frozenset()
    start_set = closure(frozenset([start]))
    index: Dict[FrozenSet[int], int] = {dead: 0, start_set: 1}
    order = [dead, start_set]
    rows: List[List[int]] = []
    accepts: List[int] = []

    i = 0
    while i < len(order):
        current = order[i]
        i += 1
        acc = 0
        for s in current:
            acc |= accept_of.get(s, 0)
        accepts.append(acc)
        row = []
        for c in range(n_classes):
            byte = class_byte[c]
            nxt = set()
            for s in current:
                for mask, t in nfa.trans[s]:
                    if mask >> byte & 1:
                        nxt.add(t)
            target = closure(frozenset(nxt)) if nxt else dead
            if target not in index:
                if len(index) >= max_states:
                    raise RegexTooComplex(
                        f"more than {max_states} DFA states"
                    )
                index[target] = len(order)
                order.append(target)
            row.append(index[target])
        rows.append(row)

    trans = np.array(rows, dtype=np.uint16)
    accept = np.zeros((len(accepts), n_words), dtype=np.uint32)
    for s, acc in enumerate(accepts):
        for w in range(n_words):
            accept[s, w] = (acc >> (32 * w)) & 0xFFFFFFFF

    return _minimize(
        DFA(trans=trans, accept=accept, classes=classes, start=1)
    )


def _minimize(dfa: DFA) -> DFA:
    """Moore partition refinement (keeps state 0 dead, start first)."""
    n, c = dfa.trans.shape
    # initial partition by accept mask (dead state isolated by its id 0
    # only if it behaves identically to another all-reject state — safe
    # to merge, we just need SOME dead representative)
    part = {}
    block = np.zeros(n, dtype=np.int64)
    for s in range(n):
        key = tuple(int(w) for w in dfa.accept[s])
        if key not in part:
            part[key] = len(part)
        block[s] = part[key]

    while True:
        keys = {}
        new_block = np.zeros(n, dtype=np.int64)
        for s in range(n):
            key = (block[s],) + tuple(block[dfa.trans[s]])
            if key not in keys:
                keys[key] = len(keys)
            new_block[s] = keys[key]
        if len(keys) == len(set(block.tolist())):
            block = new_block
            break
        block = new_block

    # renumber: dead block of state 0 → 0, start block → 1 (unless same)
    remap: Dict[int, int] = {int(block[0]): 0}
    if int(block[dfa.start]) not in remap:
        remap[int(block[dfa.start])] = 1
    for s in range(n):
        b = int(block[s])
        if b not in remap:
            remap[b] = len(remap)
    m = len(remap)
    trans = np.zeros((m, c), dtype=np.uint16)
    accept = np.zeros((m, dfa.accept.shape[1]), dtype=np.uint32)
    for s in range(n):
        b = remap[int(block[s])]
        trans[b] = [remap[int(block[t])] for t in dfa.trans[s]]
        accept[b] = dfa.accept[s]
    return DFA(
        trans=trans,
        accept=accept,
        classes=dfa.classes,
        start=remap[int(block[dfa.start])],
    )
