"""Kafka L7 policy: field-equality rule matching as tensor ops.

Behavioral port of /root/reference/pkg/kafka/policy.go:
  - RequestMessage.MatchesRule (policy.go:200): a request is allowed
    if a topic-less (or topic-free-request) rule matches, OR if every
    topic of the request is covered by some matching rule naming it —
    "all topics must be allowed";
  - ruleMatches (policy.go:144): APIKey/Role set membership, exact
    APIVersion (wildcard when unset), ClientID exact (only for the
    request structs that carry one — ConsumerMetadata and unknown
    kinds skip the check, policy.go:182-195);
  - matchNonTopicRequests (policy.go:54): an unparsed request can
    never satisfy a topic rule if its API key is topic-typed; its
    ClientID is NOT checked (reference TODO GH-3097 — reproduced).

Strings (client ids, topics) are interned host-side to u32 ids, so the
device work is pure integer equality over [B, R] / [B, T, R] tensors
— the "easy tensor case" of SURVEY.md §7 step 7.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Sanity ceiling only (ident_rules masks are multi-word; base matrix
# is [B, R] regardless) — not a semantic limit.
MAX_RULES = 4096
MAX_TOPICS = 8  # topics per request tensor row (excess → host path)

# api/kafka.go:110-133 — API keys whose REQUEST carries topics.
TOPIC_API_KEYS = frozenset(
    [0, 1, 2, 3, 4, 5, 6, 8, 9, 10, 11, 19, 20, 21, 23, 24, 27, 28,
     34, 35, 37]
)

# Request kinds whose parsed struct carries a checked ClientID
# (policy.go:71-130: Produce/Fetch/Offset/Metadata/OffsetCommit/
# OffsetFetch).
CLIENT_CHECKED_KINDS = frozenset([0, 1, 2, 3, 8, 9])


class Interner:
    """Host-side string → dense u32 id (0 reserved for 'absent')."""

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}

    def intern(self, s: str) -> int:
        if s == "":
            return 0
        got = self._ids.get(s)
        if got is None:
            got = len(self._ids) + 1
            self._ids[s] = got
        return got

    def lookup(self, s: str) -> int:
        """0 when unseen — an unseen string can never equal a rule's."""
        return self._ids.get(s, 0) if s else 0


@dataclass
class KafkaRequest:
    """A parsed request (pkg/kafka/request.go RequestMessage)."""

    kind: int  # api key int16
    version: int
    client_id: str = ""
    topics: Tuple[str, ...] = ()
    parsed: bool = True  # False ⇒ only the generic header was parsed


@dataclass
class KafkaRuleSpec:
    """One (selector-scope, PortRuleKafka) pair, pre-resolved."""

    identity_indices: Sequence[int]
    api_keys: Tuple[int, ...] = ()  # empty = wildcard (post Role expand)
    api_version: Optional[int] = None  # None = wildcard
    client_id: str = ""
    topic: str = ""
    # fleet-scoped compiles (l7/fleet.py): rules merge only within
    # one (endpoint, direction, L4 slot) scope
    scope_key: "object" = None


@dataclass
class KafkaTables:
    """Device tables for one (endpoint, port, direction) Kafka filter."""

    rule_keys_lo: np.ndarray  # u32 [R] api keys 0-31 bitmask
    rule_keys_hi: np.ndarray  # u32 [R] api keys 32-63
    rule_keys_any: np.ndarray  # u8 [R] wildcard
    rule_version: np.ndarray  # i32 [R]; -1 = wildcard
    rule_client: np.ndarray  # u32 [R]; 0 = wildcard
    rule_topic: np.ndarray  # u32 [R]; 0 = wildcard
    ident_rules: np.ndarray  # u32 [N, W] per-identity rule bits
    n_rules: int
    interner: Interner = field(default_factory=Interner)
    # Deduped specs retained for the host path (requests with more
    # topics than the tensor rows hold re-run MatchesRule host-side).
    specs: List[KafkaRuleSpec] = field(default_factory=list)


def rule_spec_from_port_rule(rule, identity_indices) -> KafkaRuleSpec:
    """PortRuleKafka (sanitized) → spec."""
    return KafkaRuleSpec(
        identity_indices=identity_indices,
        api_keys=tuple(rule.api_key_int),
        api_version=rule.api_version_int,
        client_id=rule.client_id,
        topic=rule.topic,
    )


def _dedupe_specs(specs: Sequence[KafkaRuleSpec]) -> List[KafkaRuleSpec]:
    """Specs with identical match fields are one device rule with the
    union of their identity sets (allowed = OR over rules) — collapses
    the per-selector allow-all pseudo-rules that L3-only rules
    wildcard into every L7 filter (repository.go:170)."""
    merged: Dict[tuple, set] = {}
    order: List[tuple] = []
    for spec in specs:
        key = (
            tuple(sorted(spec.api_keys)),
            spec.api_version,
            spec.client_id,
            spec.topic,
            spec.scope_key,
        )
        if key not in merged:
            merged[key] = set()
            order.append(key)
        merged[key].update(spec.identity_indices)
    return [
        KafkaRuleSpec(
            identity_indices=sorted(merged[key]),
            api_keys=key[0],
            api_version=key[1],
            client_id=key[2],
            topic=key[3],
            scope_key=key[4],
        )
        for key in order
    ]


def compile_kafka_rules(
    specs: Sequence[KafkaRuleSpec], n_identities: int
) -> KafkaTables:
    specs = _dedupe_specs(specs)
    if len(specs) > MAX_RULES:
        raise ValueError(f"more than {MAX_RULES} Kafka rules per filter")
    r = max(len(specs), 1)
    n_words = max(1, -(-r // 32))
    interner = Interner()
    keys_lo = np.zeros(r, dtype=np.uint32)
    keys_hi = np.zeros(r, dtype=np.uint32)
    keys_any = np.zeros(r, dtype=np.uint8)
    version = np.full(r, -1, dtype=np.int32)
    client = np.zeros(r, dtype=np.uint32)
    topic = np.zeros(r, dtype=np.uint32)
    ident = np.zeros((n_identities, n_words), dtype=np.uint32)

    for i, spec in enumerate(specs):
        if not spec.api_keys:
            keys_any[i] = 1
        for k in spec.api_keys:
            if k < 32:
                keys_lo[i] |= np.uint32(1 << k)
            elif k < 64:
                keys_hi[i] |= np.uint32(1 << (k - 32))
            else:
                raise ValueError(f"api key {k} out of range")
        if spec.api_version is not None:
            version[i] = spec.api_version
        client[i] = interner.intern(spec.client_id)
        topic[i] = interner.intern(spec.topic)
        for idx in spec.identity_indices:
            ident[idx, i // 32] |= np.uint32(1 << (i % 32))

    return KafkaTables(
        rule_keys_lo=keys_lo,
        rule_keys_hi=keys_hi,
        rule_keys_any=keys_any,
        rule_version=version,
        rule_client=client,
        rule_topic=topic,
        ident_rules=ident,
        n_rules=len(specs),
        interner=interner,
        specs=list(specs),
    )


def pad_kafka_requests(
    tables: KafkaTables, requests: Sequence[KafkaRequest]
):
    """Requests → integer tensors (strings resolved via the tables'
    interner; unseen strings become 0 ≠ any rule value).

    A request with more unique topics than the tensor row holds is
    FLAGGED `overflow` (last return) — its device verdict must be
    discarded and the request re-run through matches_rules_host
    (evaluate_with_host_fallback does this)."""
    b = len(requests)
    kind = np.zeros(b, dtype=np.int32)
    version = np.zeros(b, dtype=np.int32)
    client = np.zeros(b, dtype=np.uint32)
    topics = np.zeros((b, MAX_TOPICS), dtype=np.uint32)
    # Sentinel for "no topic in this slot": topic ids are ≥1, and
    # 0xFFFFFFFF never equals an interned id.
    topics[:] = 0xFFFFFFFF
    topic_count = np.zeros(b, dtype=np.int32)
    parsed = np.zeros(b, dtype=bool)
    checks_client = np.zeros(b, dtype=bool)
    overflow = np.zeros(b, dtype=bool)
    for i, request in enumerate(requests):
        kind[i] = request.kind
        version[i] = request.version
        client[i] = tables.interner.lookup(request.client_id)
        # MatchesRule dedupes topics via reqTopicsMap (policy.go:205)
        uniq = list(dict.fromkeys(request.topics))
        if len(uniq) > MAX_TOPICS:
            overflow[i] = True
            uniq = uniq[:MAX_TOPICS]
        for j, t in enumerate(uniq):
            topics[i, j] = tables.interner.lookup(t)
        topic_count[i] = len(uniq)
        parsed[i] = request.parsed
        checks_client[i] = request.parsed and (
            request.kind in CLIENT_CHECKED_KINDS
        )
    return (
        kind, version, client, topics, topic_count, parsed,
        checks_client, overflow,
    )


def evaluate_with_host_fallback(
    tables: KafkaTables,
    requests: Sequence[KafkaRequest],
    ident_idx,
    known,
) -> np.ndarray:
    """Full Kafka verdict: device tensors + host re-run for requests
    whose topic list exceeds the tensor rows.  Returns allowed bool [B]."""
    packed = pad_kafka_requests(tables, requests)
    overflow = packed[-1]
    allowed = np.asarray(
        evaluate_kafka_batch(tables, *packed, ident_idx, known)
    ).copy()
    ident_idx = np.asarray(ident_idx)
    known = np.asarray(known)
    for i in np.nonzero(overflow)[0]:
        allowed[i] = bool(known[i]) and matches_rules_host(
            requests[i], tables.specs, int(ident_idx[i])
        )
    return allowed


def evaluate_kafka_batch(
    tables: KafkaTables,
    kind,
    version,
    client,
    topics,
    topic_count,
    parsed,
    checks_client,
    overflow,
    ident_idx,
    known,
    scope_bits=None,  # u32 [B, W] per-flow rule-scope mask (fleet mode)
):
    """Returns allowed bool [B].  Pure integer [B,R]/[B,T,R] compares.

    Rows flagged `overflow` (topic list truncated by
    pad_kafka_requests) are force-DENIED — only
    evaluate_with_host_fallback may re-run them with the full topic
    list; a direct caller dropping the flag must never see a
    truncated row allowed."""
    import jax.numpy as jnp

    keys_lo = jnp.asarray(tables.rule_keys_lo)
    keys_hi = jnp.asarray(tables.rule_keys_hi)
    keys_any = jnp.asarray(tables.rule_keys_any).astype(bool)
    rule_version = jnp.asarray(tables.rule_version)
    rule_client = jnp.asarray(tables.rule_client)
    rule_topic = jnp.asarray(tables.rule_topic)

    kind = jnp.asarray(kind)[:, None]  # [B,1]
    version = jnp.asarray(version)[:, None]
    client = jnp.asarray(client)[:, None]
    parsed_b = jnp.asarray(parsed)[:, None]
    checks_client_b = jnp.asarray(checks_client)[:, None]

    # api-key membership (CheckAPIKeyRole, kafka.go:247); negative
    # keys (structurally invalid, rejected at the wire parser) must
    # not alias into the clipped shift range — gate them out here too
    in_lo = (keys_lo[None, :] >> jnp.clip(kind, 0, 31).astype(jnp.uint32)) & 1
    in_hi = (keys_hi[None, :] >> jnp.clip(kind - 32, 0, 31).astype(jnp.uint32)) & 1
    key_ok = (kind >= 0) & (
        keys_any[None, :]
        | jnp.where(
            kind < 32, in_lo, jnp.where(kind < 64, in_hi, 0)
        ).astype(bool)
    )

    ver_ok = (rule_version[None, :] < 0) | (rule_version[None, :] == version)

    client_ok = (rule_client[None, :] == 0) | (
        rule_client[None, :] == client
    )
    # ClientID only checked for parsed structs that carry it
    # (policy.go switch); unparsed requests skip it (GH-3097 TODO).
    client_ok = client_ok | ~checks_client_b

    # matchNonTopicRequests: unparsed + topic rule + topic-typed kind
    # → rule can't match.
    is_topic_kind = jnp.isin(
        kind, jnp.asarray(sorted(TOPIC_API_KEYS), dtype=kind.dtype)
    )
    nontopic_ok = ~(
        (rule_topic[None, :] != 0) & is_topic_kind & ~parsed_b
    )

    base = key_ok & ver_ok & client_ok & nontopic_ok  # [B, R]

    ident_bits = jnp.asarray(tables.ident_rules)[
        jnp.clip(jnp.asarray(ident_idx), 0, tables.ident_rules.shape[0] - 1)
    ]  # [B, W]
    r = base.shape[1]
    word_of_rule = jnp.arange(r) // 32
    bit_of_rule = (jnp.arange(r) % 32).astype(jnp.uint32)
    rule_bit = (ident_bits[:, word_of_rule] >> bit_of_rule[None, :]) & 1
    base = base & rule_bit.astype(bool) & jnp.asarray(known)[:, None]
    if scope_bits is not None:
        scope_bit = (
            scope_bits[:, word_of_rule] >> bit_of_rule[None, :]
        ) & 1
        base = base & scope_bit.astype(bool)

    # MatchesRule: topic-less rule (or topic-less request) matching →
    # allow everything...
    topic_count_b = jnp.asarray(topic_count)[:, None]
    allow_all = jnp.any(
        base & ((rule_topic[None, :] == 0) | (topic_count_b == 0)), axis=1
    )
    # ...else every request topic must be covered by a matching rule
    # naming it.
    topics_b = jnp.asarray(topics)  # [B, T]
    covered = jnp.any(
        base[:, None, :] & (rule_topic[None, None, :] == topics_b[:, :, None]),
        axis=2,
    )  # [B, T]
    slot_active = (
        jnp.arange(topics_b.shape[1])[None, :]
        < jnp.asarray(topic_count)[:, None]
    )
    all_covered = (jnp.asarray(topic_count) > 0) & jnp.all(
        covered | ~slot_active, axis=1
    )
    return (allow_all | all_covered) & ~jnp.asarray(overflow)


# ---------------------------------------------------------------------------
# host oracle (exact MatchesRule port)
# ---------------------------------------------------------------------------


def rule_matches_host(request: KafkaRequest, spec: KafkaRuleSpec) -> bool:
    """ruleMatches (policy.go:144)."""
    if spec.api_keys and request.kind not in spec.api_keys:
        return False
    if spec.api_version is not None and spec.api_version != request.version:
        return False
    if spec.topic == "" and spec.client_id == "":
        return True
    if not request.parsed:
        # matchNonTopicRequests (policy.go:54)
        if spec.topic != "" and request.kind in TOPIC_API_KEYS:
            return False
        return True
    if request.kind in CLIENT_CHECKED_KINDS:
        if spec.client_id != "" and spec.client_id != request.client_id:
            return False
        return True
    # ConsumerMetadataReq / default: no further checks (policy.go:183,195)
    return True


def matches_rules_host(
    request: KafkaRequest, specs: Sequence[KafkaRuleSpec],
    identity_index: Optional[int] = None,
) -> bool:
    """MatchesRule (policy.go:200), optionally identity-scoped."""
    scoped = [
        s
        for s in specs
        if identity_index is None or identity_index in s.identity_indices
    ]
    remaining = dict.fromkeys(request.topics, True)
    for spec in scoped:
        if spec.topic == "" or len(request.topics) == 0:
            if rule_matches_host(request, spec):
                return True
        elif remaining.get(spec.topic):
            if rule_matches_host(request, spec):
                del remaining[spec.topic]
                if not remaining:
                    return True
    return False
