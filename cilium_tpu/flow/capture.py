"""Verdict batches → FlowRecords (the perf-ring→Hubble fold).

``capture_batch`` folds one evaluated batch's per-tuple columns into
the FlowStore: EVERY drop becomes a record (a dropped flow is the
thing an operator greps for) and allows are head-sampled under the
same knob the monitor fold uses (MonitorAggregationLevel — the
aggregate counters stay exact in the telemetry plane; only the
per-record fan-out is sampled).

Drop classification goes through ``engine.verdict.telemetry_masks``
— the ONE definition set the device [2, TELEM_COLS] histogram and
the host telemetry fold already share — so a record's ``drop_reason``
is by construction the TELEM_DROP_* column that counted it in the
PR 1 histogram: the FlowStore's per-reason counts and
``cilium_drop_count_total`` can never disagree.  Paths without the
full DatapathVerdicts columns (the lattice-only audit path of
Daemon.process_flows) pass zeros for the missing stages, which is
exactly what those stages contributed to their histogram.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from cilium_tpu.engine.verdict import (
    TELEM_DROP_FRAG,
    TELEM_DROP_POLICY,
    TELEM_DROP_PREFILTER,
    telemetry_masks,
)
from cilium_tpu.flow.store import (
    VERDICT_DROPPED,
    VERDICT_FORWARDED,
    FlowRecord,
    FlowStore,
)
from cilium_tpu.option import (
    MONITOR_AGG_NONE,
)
from cilium_tpu.telemetry import DROP_COLUMN_REASONS

# drop column → canonical reason, in classification order (the masks
# are disjoint and partition the denials — telemetry_consistent)
_DROP_COLUMNS = (
    TELEM_DROP_PREFILTER,
    TELEM_DROP_POLICY,
    TELEM_DROP_FRAG,
)

# MonitorAggregationLevel → per-batch allow-record budget: `none`
# captures every allow (per-packet visibility, the level that also
# enables per-flow TraceNotify); each higher level cuts the head
# sample — drops are NEVER sampled
_ALLOW_SAMPLE_BY_LEVEL = {0: None, 1: 1024, 2: 256, 3: 64}


def allow_sample_for_level(level: int) -> Optional[int]:
    """Allowed-flow head-sample budget for a MonitorAggregationLevel
    (None = capture every allow)."""
    if level == MONITOR_AGG_NONE:
        return None
    return _ALLOW_SAMPLE_BY_LEVEL.get(
        int(level), _ALLOW_SAMPLE_BY_LEVEL[3]
    )


def chip_of_rows(n_rows: int, n_chips: int) -> np.ndarray:
    """Chip ordinal per batch row under even batch sharding (the
    mesh evaluator splits the batch axis into n_chips contiguous
    shards) — the tag flow records carry on a mesh."""
    if n_chips <= 1:
        return np.zeros(n_rows, np.int32)
    shard = n_rows // n_chips
    return np.minimum(
        np.arange(n_rows, dtype=np.int32) // max(shard, 1),
        n_chips - 1,
    )


def capture_batch(
    store: FlowStore,
    *,
    ep_ids,
    src_identities,
    dst_identities,
    dports,
    protos,
    directions,
    allowed,
    match_kind,
    proxy_port=None,
    pre_dropped=None,
    ct_result=None,
    ct_delete=None,
    lb_slave=None,
    ipcache_miss=None,
    chip=0,
    allow_sample: Optional[int] = None,
    now: Optional[float] = None,
    metrics_registry=None,
    trace_id: str = "",
    cache_hit=None,
    tenant="",
    diff_status=None,
) -> int:
    """Fold one batch's per-tuple columns into the store.  All
    columns are host arrays of one length (the batch's VALID prefix —
    callers slice padding off first).  ``chip`` is a scalar ordinal
    or a per-tuple array; ``allow_sample`` caps allowed-flow records
    for this batch (None = all; 0 = drops only).
    ``metrics_registry`` additionally feeds
    flow_records_captured_total / flow_store_evicted (None = no
    metrics — tools and benches that must not touch the process
    registry).  ``trace_id`` stamps the span-plane join key on every
    record of a traced batch (GET /flows?trace-id=...).
    ``cache_hit`` is the per-tuple verdict-cache hit column of a
    memoized dispatch (None = uncached path, records carry False) —
    `cilium-tpu observe --cache-hit` filters on it.  ``tenant`` is
    the submitting tenant/namespace — a scalar string (the one-shot
    REST path) or a per-tuple object array (the serving plane's
    coalesced multi-tenant batches); `observe --tenant` filters on
    it.  ``diff_status`` is the per-tuple shadow verdict-diff
    transition code column (cilium_tpu.shadow TRANS_* u8; None =
    unsampled batch, records carry "") — `observe --diff-status`
    joins flow records to the armed diff window.  Returns the
    number of records captured."""
    allowed = np.asarray(allowed).astype(bool)
    kind = np.asarray(match_kind)
    b = len(allowed)
    zeros = np.zeros(b, np.int32)

    def _col(a):
        return zeros if a is None else np.asarray(a)

    proxy = _col(proxy_port)
    ct_res = _col(ct_result)
    masks = telemetry_masks(
        _col(pre_dropped), ct_res, kind, allowed, _col(ct_delete),
        proxy, _col(lb_slave), _col(ipcache_miss), xp=np,
    )
    # per-tuple reason attribution straight from the histogram's own
    # drop columns (disjoint; partition the denials)
    reason = np.full(b, "", dtype=object)
    for col in _DROP_COLUMNS:
        reason[masks[col]] = DROP_COLUMN_REASONS[col]

    drop_idx = np.nonzero(~allowed)[0]
    allow_idx = np.nonzero(allowed)[0]
    if allow_sample is not None:
        allow_idx = allow_idx[: max(0, int(allow_sample))]
    # a batch with more drops than the ring holds: building records
    # the bounded deque would evict before anyone could read them
    # only amplifies the drop storm — keep the NEWEST capacity's
    # worth and charge the rest as evictions (visible loss, same
    # counter ring overflow uses).  Metrics below still count every
    # drop, so the counter plane stays exact.
    n_drops = len(drop_idx)
    truncated = max(0, n_drops - store.capacity)
    if truncated:
        drop_idx = drop_idx[-store.capacity:]
        allow_idx = allow_idx[:0]
    idx = np.concatenate([drop_idx, allow_idx])

    ep_ids = np.asarray(ep_ids)
    src_identities = np.asarray(src_identities)
    dst_identities = np.asarray(dst_identities)
    dports = np.asarray(dports)
    protos = np.asarray(protos)
    directions = np.asarray(directions)
    chips = (
        np.asarray(chip)
        if not np.isscalar(chip)
        else np.full(b, int(chip), np.int32)
    )
    hits = (
        np.zeros(b, bool)
        if cache_hit is None
        else np.asarray(cache_hit).astype(bool)
    )
    tenants = (
        np.asarray(tenant, dtype=object)
        if not isinstance(tenant, str)
        else np.full(b, tenant, dtype=object)
    )
    if diff_status is None:
        diff_names = None
    else:
        from cilium_tpu.shadow import TRANS_NAMES

        codes = np.asarray(diff_status)
        diff_names = np.full(b, "", dtype=object)
        for code, name in TRANS_NAMES.items():
            if name:
                diff_names[codes == code] = name
    ts = time.time() if now is None else now
    records = [
        FlowRecord(
            ts=ts,
            chip=int(chips[i]),
            ep_id=int(ep_ids[i]),
            src_identity=int(src_identities[i]),
            dst_identity=int(dst_identities[i]),
            dport=int(dports[i]),
            proto=int(protos[i]),
            direction=int(directions[i]),
            verdict=(
                VERDICT_FORWARDED if allowed[i] else VERDICT_DROPPED
            ),
            match_kind=int(kind[i]),
            drop_reason=str(reason[i]),
            proxy_port=int(proxy[i]),
            ct_state=int(ct_res[i]),
            trace_id=trace_id,
            cache_hit=bool(hits[i]),
            tenant=str(tenants[i]),
            diff_status=(
                "" if diff_names is None else str(diff_names[i])
            ),
        )
        for i in idx
    ]
    n = store.extend(records)
    store.charge_evicted(truncated)
    if metrics_registry is not None:
        if n_drops:
            metrics_registry.flow_records_captured_total.inc(
                VERDICT_DROPPED, value=n_drops
            )
        if len(allow_idx):
            metrics_registry.flow_records_captured_total.inc(
                VERDICT_FORWARDED, value=len(allow_idx)
            )
        metrics_registry.flow_store_evicted.set(value=store.evicted)
    return n
