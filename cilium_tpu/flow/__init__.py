"""Hubble-style per-flow observability plane.

Cilium grew the monitor perf ring into Hubble: a bounded in-agent
ring of structured flow records, queryable with filters and served
over an API (hubble/pkg/server observe + the `hubble observe` CLI).
This package is that plane for the TPU datapath:

  * ``store``   — FlowRecord + the bounded, lock-protected FlowStore
    ring with follow-mode wakeups and aggregation summaries;
  * ``capture`` — the fold from batched verdict outputs into records
    (all drops + head-sampled allows, classification derived from
    the SAME ``telemetry_masks`` definition set as the PR 1 device
    histogram, so the two planes are bit-consistent by construction).

Fed by ``Daemon.process_flows`` and ``replay.replay``; served by
``GET /flows`` / ``GET /flows/summary`` and ``cilium-tpu observe``.
"""

from cilium_tpu.flow.capture import (
    allow_sample_for_level,
    capture_batch,
    chip_of_rows,
)
from cilium_tpu.flow.store import (
    VERDICT_DROPPED,
    VERDICT_FORWARDED,
    FlowFilter,
    FlowRecord,
    FlowStore,
)

__all__ = [
    "FlowFilter",
    "FlowRecord",
    "FlowStore",
    "VERDICT_DROPPED",
    "VERDICT_FORWARDED",
    "allow_sample_for_level",
    "capture_batch",
    "chip_of_rows",
]
