"""The flow-record ring: Hubble's container/ring over this datapath.

Behavioral analog of hubble/pkg/container/ring + the observe filters
(hubble/pkg/filters): a bounded ring of FlowRecords with a monotonic
sequence number, guarded by one lock; follow-mode readers block on a
condition variable exactly like MonitorBus.wait_for_events (no spin —
the writer notifies).  Eviction is the ring's contract: the OLDEST
record falls off when full, and ``evicted`` counts what a late reader
can no longer see (the analog of hubble's lost-events accounting for
readers that fell behind the ring).
"""

from __future__ import annotations

import threading
from collections import Counter as _Counter
from collections import deque
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional

DIRECTION_INGRESS = 0
DIRECTION_EGRESS = 1

VERDICT_FORWARDED = "FORWARDED"
VERDICT_DROPPED = "DROPPED"

_DIRECTION_NAMES = {
    DIRECTION_INGRESS: "ingress",
    DIRECTION_EGRESS: "egress",
}
_PROTO_ALIASES = {"icmp": 1, "tcp": 6, "udp": 17, "icmpv6": 58}


@dataclass
class FlowRecord:
    """One captured flow (the flow.Flow proto of Hubble, reduced to
    this datapath's tuple space).  ``src_identity``/``dst_identity``
    orient the tuple as a src→dst pair regardless of direction: the
    local endpoint is the destination of an ingress flow and the
    source of an egress one."""

    ts: float  # capture wall-clock (time.time())
    chip: int  # device ordinal that evaluated the flow
    ep_id: int  # local endpoint id
    src_identity: int
    dst_identity: int
    dport: int
    proto: int
    direction: int  # 0=ingress 1=egress
    verdict: str  # FORWARDED | DROPPED
    match_kind: int  # MATCH_* lattice code
    drop_reason: str = ""  # canonical reason name ("" when forwarded)
    proxy_port: int = 0
    ct_state: int = 0  # CT_* result (0 = stateless/audit path)
    seq: int = 0  # store-assigned monotonic sequence
    trace_id: str = ""  # span-plane join key ("" when untraced)
    # verdict served from the device verdict cache (engine/memo.py);
    # False on uncached paths and degraded host-fold batches
    cache_hit: bool = False
    # submitting tenant/namespace (the serving plane's fairness
    # unit; "" on paths without tenant attribution) — fairness
    # decisions are debuggable end to end: a shed flow's Overload
    # record names WHO was shed
    tenant: str = ""
    # shadow verdict-diff status (cilium_tpu.shadow): "" when the
    # flow was not sampled into an armed shadow window or its two
    # worlds agree; else the transition the shadow world would apply
    # ("allow_to_deny" | "deny_to_allow" | "changed")
    diff_status: str = ""

    def to_dict(self) -> dict:
        d = asdict(self)
        d["direction"] = _DIRECTION_NAMES.get(
            self.direction, str(self.direction)
        )
        return d


def parse_direction(value) -> int:
    """'ingress'/'egress'/0/1 → direction code."""
    if isinstance(value, int):
        if value in (0, 1):
            return value
        raise ValueError(f"direction must be 0 or 1, got {value!r}")
    low = str(value).strip().lower()
    if low in ("ingress", "0"):
        return DIRECTION_INGRESS
    if low in ("egress", "1"):
        return DIRECTION_EGRESS
    raise ValueError(
        f"direction must be ingress or egress, got {value!r}"
    )


def parse_proto(value) -> int:
    """'tcp'/'udp'/number → IP protocol number."""
    low = str(value).strip().lower()
    if low in _PROTO_ALIASES:
        return _PROTO_ALIASES[low]
    try:
        return int(low)
    except ValueError:
        raise ValueError(f"unknown protocol {value!r}")


def _parse_since(value) -> float:
    """`since` filter: absolute unix seconds, or a relative
    '<n>s'/'<n>m'/'<n>h' window back from now."""
    import time as _time

    s = str(value).strip().lower()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0}.get(s[-1:] or "")
    if mult is not None:
        try:
            return _time.time() - float(s[:-1]) * mult
        except ValueError:
            raise ValueError(f"bad since window {value!r}")
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"bad since value {value!r}")


@dataclass
class FlowFilter:
    """Hubble-like observe filters over FlowRecords.  Every field is
    conjunctive; None = wildcard.  ``identity`` matches EITHER side
    of the pair (hubble's --identity semantics)."""

    verdict: Optional[str] = None
    drop_reason: Optional[str] = None
    identity: Optional[int] = None
    ep: Optional[int] = None
    port: Optional[int] = None
    proto: Optional[int] = None
    direction: Optional[int] = None
    since: Optional[float] = None
    chip: Optional[int] = None
    trace_id: Optional[str] = None
    cache_hit: Optional[bool] = None
    tenant: Optional[str] = None
    # "any" matches every re-verdicted flow; a specific transition
    # name matches exactly
    diff_status: Optional[str] = None

    # GET /flows query-param name → field + parser
    PARAM_FIELDS = {
        "verdict": ("verdict", lambda v: str(v).upper()),
        "drop-reason": ("drop_reason", str),
        "identity": ("identity", int),
        "ep": ("ep", int),
        "port": ("port", int),
        "proto": ("proto", parse_proto),
        "direction": ("direction", parse_direction),
        "since": ("since", _parse_since),
        "chip": ("chip", int),
        "trace-id": ("trace_id", lambda v: str(v).lower()),
        "cache-hit": (
            "cache_hit",
            lambda v: str(v).strip().lower()
            in ("1", "true", "yes", "on"),
        ),
        "tenant": ("tenant", str),
        "diff-status": (
            "diff_status",
            lambda v: str(v).strip().lower().replace("-", "_"),
        ),
    }

    @classmethod
    def from_params(cls, params: Dict[str, str]) -> "FlowFilter":
        """Build from (string-valued) query params; unknown keys are
        the caller's concern (the route strips its own pagination
        params first).  Raises ValueError on malformed values."""
        kwargs = {}
        for key, raw in params.items():
            spec = cls.PARAM_FIELDS.get(key)
            if spec is None:
                raise ValueError(f"unknown flow filter {key!r}")
            fld, parse = spec
            kwargs[fld] = parse(raw)
        flt = cls(**kwargs)
        if flt.verdict is not None and flt.verdict not in (
            VERDICT_FORWARDED, VERDICT_DROPPED,
        ):
            raise ValueError(
                f"verdict must be {VERDICT_FORWARDED} or "
                f"{VERDICT_DROPPED}, got {flt.verdict!r}"
            )
        return flt

    def matches(self, r: FlowRecord) -> bool:
        if self.verdict is not None and r.verdict != self.verdict:
            return False
        if (
            self.drop_reason is not None
            and r.drop_reason != self.drop_reason
        ):
            return False
        if self.identity is not None and self.identity not in (
            r.src_identity, r.dst_identity,
        ):
            return False
        if self.ep is not None and r.ep_id != self.ep:
            return False
        if self.port is not None and r.dport != self.port:
            return False
        if self.proto is not None and r.proto != self.proto:
            return False
        if self.direction is not None and r.direction != self.direction:
            return False
        if self.since is not None and r.ts < self.since:
            return False
        if self.chip is not None and r.chip != self.chip:
            return False
        if self.trace_id is not None and r.trace_id != self.trace_id:
            return False
        if (
            self.cache_hit is not None
            and bool(r.cache_hit) != self.cache_hit
        ):
            return False
        if self.tenant is not None and r.tenant != self.tenant:
            return False
        if self.diff_status is not None:
            if self.diff_status == "any":
                if not r.diff_status:
                    return False
            elif r.diff_status != self.diff_status:
                return False
        return True


class FlowStore:
    """Bounded ring of FlowRecords (hubble's ring buffer): appends
    assign a monotonic ``seq``, overflow evicts the OLDEST record,
    and follow-mode readers block on the condition variable until a
    record newer than their cursor lands."""

    def __init__(self, capacity: int = 65536) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._ring: deque = deque(maxlen=capacity)
        self._next_seq = 1
        self.captured_total = 0
        self.evicted = 0

    def extend(self, records: Iterable[FlowRecord]) -> int:
        """Append records (stamping seq), waking follow-mode readers
        once per batch.  Returns the number appended."""
        n = 0
        with self._cond:
            for r in records:
                r.seq = self._next_seq
                self._next_seq += 1
                if len(self._ring) == self.capacity:
                    self.evicted += 1
                self._ring.append(r)
                n += 1
            self.captured_total += n
            if n:
                self._cond.notify_all()
        return n

    def append(self, record: FlowRecord) -> None:
        self.extend((record,))

    def charge_evicted(self, n: int) -> None:
        """Account records a producer declined to build because this
        bounded ring could never retain them (capture_batch's
        drop-storm truncation): they are losses a reader should see,
        charged to the same counter as ring eviction."""
        if n > 0:
            with self._lock:
                self.evicted += n

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[FlowRecord]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def query(
        self,
        flt: Optional[FlowFilter] = None,
        last: Optional[int] = None,
        after_seq: Optional[int] = None,
    ) -> List[FlowRecord]:
        """Filtered read in ring (oldest→newest) order.  ``last``
        keeps only the newest N matches (hubble's --last);
        ``after_seq`` restricts to records newer than a follow
        cursor."""
        import itertools

        with self._lock:
            ring = self._ring
            if after_seq is not None and ring:
                # seqs are contiguous in the ring, so the cursor's
                # position is arithmetic — a follow wakeup copies
                # only the NEW records
                start = after_seq - ring[0].seq + 1
                if start >= len(ring):
                    src = []
                elif start > 0:
                    src = list(itertools.islice(ring, start, None))
                else:
                    src = list(ring)
            else:
                src = list(ring)
        # the Python-level filter pass runs OUTSIDE the lock: a
        # one-shot full-ring query must not stall the capture hot
        # path for the duration of per-record matches() calls (the
        # C-speed list copy above is the only time the lock is held)
        out = [r for r in src if flt is None or flt.matches(r)]
        if last is not None and last >= 0:
            out = out[-last:] if last else []
        return out

    def wait_for_flows(
        self,
        after_seq: int,
        timeout: float,
        flt: Optional[FlowFilter] = None,
    ) -> List[FlowRecord]:
        """Follow-mode long-poll: block until a MATCHING record with
        seq > after_seq lands or the timeout lapses (the
        MonitorBus.wait_for_events condvar pattern).  Returns the
        matching records (empty on timeout)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while True:
            # snapshot the write cursor BEFORE querying: any record
            # appended after this point re-triggers the query, so a
            # match landing between query() and wait() can't be
            # missed
            with self._lock:
                seen = self._next_seq - 1
            got = self.query(flt, after_seq=after_seq)
            if got:
                return got
            with self._cond:
                while self._next_seq - 1 == seen:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return []
                    self._cond.wait(timeout=remaining)

    def summary(self, top: int = 10) -> dict:
        """Aggregations over the ring (the `hubble observe
        --output=summary` / Grafana-panel shapes): top drop reasons,
        top denied (src identity, dst identity) pairs, per-chip flow
        counts with an imbalance ratio, verdict totals."""
        snap = self.snapshot()
        reasons: _Counter = _Counter()
        pairs: _Counter = _Counter()
        chips: _Counter = _Counter()
        verdicts: _Counter = _Counter()
        tenants: _Counter = _Counter()
        tenant_sheds: _Counter = _Counter()
        for r in snap:
            verdicts[r.verdict] += 1
            chips[r.chip] += 1
            if r.tenant:
                tenants[r.tenant] += 1
            if r.verdict == VERDICT_DROPPED:
                reasons[r.drop_reason] += 1
                pairs[(r.src_identity, r.dst_identity)] += 1
                if r.tenant and r.drop_reason == "Overload":
                    tenant_sheds[r.tenant] += 1
        chip_counts = {str(c): n for c, n in sorted(chips.items())}
        imbalance = (
            max(chips.values()) / max(1, min(chips.values()))
            if chips
            else 0.0
        )
        return {
            "records": len(snap),
            "captured_total": self.captured_total,
            "evicted": self.evicted,
            "verdicts": dict(verdicts),
            "top_drop_reasons": [
                {"reason": reason, "count": n}
                for reason, n in reasons.most_common(top)
            ],
            "top_denied_pairs": [
                {
                    "src_identity": src,
                    "dst_identity": dst,
                    "count": n,
                }
                for (src, dst), n in pairs.most_common(top)
            ],
            "per_chip": chip_counts,
            "chip_imbalance": round(imbalance, 3),
            "per_tenant": dict(tenants),
            "per_tenant_overload": dict(tenant_sheds),
        }
