"""In-process distributed tracing: the span plane.

A Dapper-style tracer for the verdict serving stack: every span
carries (trace_id, span_id, parent_id), a monotonic-clock duration,
attributes and a status, and lands in a bounded ring the API serves
(`GET /debug/traces`) and bugtool archives.  Context propagates two
ways:

  * in-process via a contextvar — a span opened anywhere under an
    active span becomes its child, so the REST handler's root span
    automatically parents `Daemon.process_flows`, which parents each
    batch's dispatch, which parents the per-chip children;
  * across processes via a W3C `traceparent`-style HTTP header
    (`00-<trace_id>-<span_id>-<flags>`) accepted and emitted by
    api/server — a client that stamps its own header sees its ids on
    every span, flow record and reply.

Determinism and cost are first-class: ids come from a seedable RNG
(tests pin exact ids), sampling is HEAD-based (the decision is made
once at the root — an unsampled request creates no spans at all, the
same shape as the flow plane's head-sampled allows), and the tracer
accounts its own bookkeeping time in `overhead_s` so bench.py's
`tracing_overhead_pct` gate and tools/trace_smoke.py measure the real
hot-path cost instead of an A/B of noisy wall clocks.

Join keys: the trace id is stamped into FlowRecords captured during a
traced batch (GET /flows?trace-id=...) and the jit/table metrics are
sampled by the same instrumented sites, so one id connects
`/debug/traces`, `/flows` and `/metrics/prometheus`.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

TRACEPARENT_HEADER = "traceparent"
TRACE_ID_HEADER = "X-Trace-Id"

# head-sampling default: record everything (the per-request span count
# is bounded — one span per phase/batch, never per flow — so the
# default mirrors the flow plane's "drops always" posture; operators
# turn the knob down under load, --trace-sample-rate)
DEFAULT_SAMPLE_RATE = 1.0


@dataclass
class SpanContext:
    """Propagated identity of a remote parent (parsed traceparent)."""

    trace_id: str
    span_id: str
    sampled: bool = True


@dataclass
class Span:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_id: str  # 16 hex chars, "" for a root
    name: str
    site: str  # instrumentation point, e.g. "engine.dispatch"
    ts: float  # wall-clock start (time.time()) — for rendering
    start: float  # perf_counter at start
    duration: float = 0.0  # seconds (0 while running)
    status: str = "ok"  # ok | error
    attrs: Dict[str, object] = field(default_factory=dict)
    events: List[dict] = field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        return self.duration * 1000.0

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "site": self.site,
            "ts": self.ts,
            "duration_ms": round(self.duration_ms, 4),
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """The unsampled stand-in: absorbs attribute writes and renders
    falsy ids, so instrumented code never branches on sampling."""

    trace_id = ""
    span_id = ""
    parent_id = ""
    duration = 0.0
    status = "ok"

    def __init__(self) -> None:
        self.attrs: Dict[str, object] = {}
        self.events: List[dict] = []


_NOOP = _NoopSpan()

# the active span of THIS execution context (contextvars: each API
# handler thread/task sees its own chain)
_current: contextvars.ContextVar = contextvars.ContextVar(
    "cilium_tpu_span", default=None
)


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """`00-<32 hex>-<16 hex>-<2 hex flags>` → SpanContext; anything
    malformed is ignored (None): a bad header must start a fresh
    trace, never 500 the request."""
    if not value:
        return None
    parts = str(value).strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(
        trace_id=trace_id.lower(),
        span_id=span_id.lower(),
        sampled=bool(int(flags, 16) & 1),
    )


def format_traceparent(span) -> str:
    flags = "01"
    return f"00-{span.trace_id}-{span.span_id}-{flags}"


class Tracer:
    """Bounded-ring tracer with contextvar propagation.

    `capacity` bounds the exporter ring (oldest spans fall off,
    counted in `dropped` — the FlowStore eviction contract);
    `sample_rate` is the head-sampling probability applied at ROOT
    span creation (children inherit the decision); `seed` pins the
    RNG so ids and sampling decisions reproduce in tests."""

    def __init__(
        self,
        capacity: int = 8192,
        sample_rate: float = DEFAULT_SAMPLE_RATE,
        seed: Optional[int] = None,
    ) -> None:
        self.capacity = capacity
        self.sample_rate = sample_rate
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.dropped = 0
        self.started_total = 0
        self.finished_total = 0
        # the tracer's own bookkeeping seconds (begin/finish, ring
        # append) — what tracing actually charges the instrumented
        # path; bench.py's tracing_overhead_pct reads this
        self.overhead_s = 0.0

    # -- id generation --------------------------------------------------------

    def _gen_ids(self, bits: int) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(bits):0{bits // 4}x}"

    def _sampled(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.sample_rate

    def reset(
        self,
        seed: Optional[int] = None,
        sample_rate: Optional[float] = None,
    ) -> None:
        """Clear the ring and (optionally) reseed/re-rate — tests and
        bench runs start from a known state."""
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self.started_total = 0
            self.finished_total = 0
            self.overhead_s = 0.0
            if seed is not None:
                self._rng = random.Random(seed)
        if sample_rate is not None:
            self.sample_rate = sample_rate

    # -- span lifecycle -------------------------------------------------------

    def begin(
        self,
        name: str,
        site: str = "",
        parent: Optional[SpanContext] = None,
        attrs: Optional[dict] = None,
    ):
        """Open a span and install it as the current context.  Returns
        (span, token); pair with finish().  `parent` overrides the
        contextvar chain (the HTTP header case).  An unsampled root
        yields the noop span — children of a noop stay noop."""
        t0 = time.perf_counter()
        cur = _current.get()
        if parent is not None:
            if not parent.sampled:
                token = _current.set(_NOOP)
                return _NOOP, token
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif cur is not None:
            if cur is _NOOP:
                token = _current.set(_NOOP)
                return _NOOP, token
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            if not self._sampled():
                token = _current.set(_NOOP)
                return _NOOP, token
            trace_id, parent_id = self._gen_ids(128), ""
        span = Span(
            trace_id=trace_id,
            span_id=self._gen_ids(64),
            parent_id=parent_id,
            name=name,
            site=site,
            ts=time.time(),
            start=t0,
            attrs=dict(attrs) if attrs else {},
        )
        self.started_total += 1
        token = _current.set(span)
        self.overhead_s += time.perf_counter() - t0
        return span, token

    def finish(self, span, token, status: Optional[str] = None) -> None:
        """Close a span, restore the outer context, export to the
        ring.  Noop spans only restore the context."""
        t0 = time.perf_counter()
        try:
            _current.reset(token)
        except ValueError:
            # token from another context (exotic caller); best-effort
            _current.set(None)
        if span is _NOOP:
            return
        span.duration = t0 - span.start
        if status is not None:
            span.status = status
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(span)
            self.finished_total += 1
        self.overhead_s += time.perf_counter() - t0

    def span(
        self,
        name: str,
        site: str = "",
        parent: Optional[SpanContext] = None,
        attrs: Optional[dict] = None,
    ):
        """Context-manager form: exceptions mark the span error and
        re-raise."""
        return _SpanCtx(self, name, site, parent, attrs)

    def record(
        self,
        name: str,
        site: str,
        duration: float,
        parent=None,
        attrs: Optional[dict] = None,
        status: str = "ok",
        ts: Optional[float] = None,
    ):
        """Export an already-measured span (jit compiles, synthesized
        per-chip children): no contextvar involvement.  `parent` is a
        Span (defaults to the current one).  Recording under an
        UNSAMPLED context is skipped — the head decision made at the
        root covers everything beneath it, so a sampled-out request
        exports nothing at all; with no context (background work
        outside any request) the span becomes its own root."""
        t0 = time.perf_counter()
        if parent is None:
            parent = _current.get()
        if parent is _NOOP or (
            parent is not None and not parent.trace_id
        ):
            return None
        if parent is None:
            trace_id, parent_id = self._gen_ids(128), ""
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        span = Span(
            trace_id=trace_id,
            span_id=self._gen_ids(64),
            parent_id=parent_id,
            name=name,
            site=site,
            ts=(time.time() - duration) if ts is None else ts,
            start=t0 - duration,
            duration=duration,
            status=status,
            attrs=dict(attrs) if attrs else {},
        )
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(span)
            self.started_total += 1
            self.finished_total += 1
        self.overhead_s += time.perf_counter() - t0
        return span

    # -- queries --------------------------------------------------------------

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def get_trace(self, trace_id: str) -> List[Span]:
        """Every exported span of one trace, oldest first."""
        trace_id = str(trace_id).lower()
        return sorted(
            (s for s in self.snapshot() if s.trace_id == trace_id),
            key=lambda s: s.start,
        )

    def query(
        self,
        trace_id: Optional[str] = None,
        min_duration_ms: Optional[float] = None,
        site: Optional[str] = None,
        last: Optional[int] = None,
    ) -> List[Span]:
        out = self.snapshot()
        if trace_id is not None:
            tid = str(trace_id).lower()
            out = [s for s in out if s.trace_id == tid]
        if site is not None:
            out = [s for s in out if s.site == site]
        if min_duration_ms is not None:
            out = [
                s for s in out if s.duration_ms >= min_duration_ms
            ]
        out.sort(key=lambda s: s.start)
        if last is not None and last >= 0:
            out = out[-last:] if last else []
        return out

    def slowest_traces(self, n: int = 10) -> List[dict]:
        """Traces ranked by ROOT span duration (the request-level
        latency), with per-trace span counts — `cilium-tpu trace
        --slowest N`."""
        spans = self.snapshot()
        by_trace: Dict[str, List[Span]] = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        rows = []
        for tid, group in by_trace.items():
            ids = {s.span_id for s in group}
            roots = [
                s for s in group
                if not s.parent_id or s.parent_id not in ids
            ]
            root = max(roots, key=lambda s: s.duration, default=None)
            if root is None:
                continue
            rows.append(
                {
                    "trace_id": tid,
                    "root": root.name,
                    "site": root.site,
                    "ts": root.ts,
                    "duration_ms": round(root.duration_ms, 4),
                    "status": root.status,
                    "spans": len(group),
                }
            )
        rows.sort(key=lambda r: r["duration_ms"], reverse=True)
        return rows[: max(0, n)]


class _SpanCtx:
    def __init__(self, tracer, name, site, parent, attrs) -> None:
        self._tracer = tracer
        self._args = (name, site, parent, attrs)
        self.span = None
        self._token = None

    def __enter__(self):
        name, site, parent, attrs = self._args
        self.span, self._token = self._tracer.begin(
            name, site=site, parent=parent, attrs=attrs
        )
        return self.span

    def __exit__(self, exc_type, exc, tb):
        status = None
        if exc_type is not None:
            status = "error"
            if self.span is not _NOOP:
                self.span.attrs.setdefault("error", repr(exc))
        self._tracer.finish(self.span, self._token, status=status)
        return False


# -- module-global tracer (the metrics-registry shape) ----------------------

tracer = Tracer()


def current_span():
    """The active span of this execution context (None/noop outside a
    trace)."""
    return _current.get()


def current_trace_id() -> str:
    """Trace id of the active context ("" when untraced/unsampled) —
    the join key stamped into FlowRecords."""
    cur = _current.get()
    return cur.trace_id if cur is not None else ""


def add_event(name: str, **attrs) -> None:
    """Attach a point-in-time event to the active span (breaker
    decisions, admission shedding, retries).  No-op outside a sampled
    span — the cheap path costs one contextvar read."""
    cur = _current.get()
    if cur is None or cur is _NOOP:
        return
    cur.events.append(
        {
            "name": name,
            "offset_ms": round(
                (time.perf_counter() - cur.start) * 1000.0, 4
            ),
            **attrs,
        }
    )


def record_chip_spans(
    trc: Tracer, parent, n_chips: int, rows: int, site: str
) -> None:
    """Synthesize per-chip dispatch children under a finished parent
    span: the device step is SPMD — every chip executes the same
    program over its batch shard in lockstep — so the parent's window
    partitions evenly across chips (the children sum to the parent,
    the smoke's tree-integrity invariant)."""
    if parent is None or parent is _NOOP or not parent.trace_id:
        return
    n_chips = max(1, int(n_chips))
    share = parent.duration / n_chips
    per_chip = rows // n_chips if n_chips else rows
    for chip in range(n_chips):
        trc.record(
            "chip.dispatch",
            site=site,
            duration=share,
            parent=parent,
            attrs={"chip": chip, "rows": per_chip},
            status=parent.status,
            ts=parent.ts,
        )


class StatSpan:
    """One clock window feeding BOTH accounting planes: a tracer span
    and a SpanStat phase accumulator.  Because the start/end
    timestamps are shared, `/debug/profile`'s phase totals and the
    span durations served by `/debug/traces` agree exactly (the old
    arrangement timed them separately).

    start()/end(success=) mirror SpanStat's verbs so call sites keep
    their shape; also usable as a context manager."""

    def __init__(
        self,
        trc: Tracer,
        stats,
        name: str,
        site: str = "",
        attrs: Optional[dict] = None,
    ) -> None:
        self._tracer = trc
        self._stat = stats.span(name)
        self._name = name
        self._site = site
        self._attrs = attrs
        self.span = None
        self._token = None
        self._t0 = 0.0

    def start(self) -> "StatSpan":
        self.span, self._token = self._tracer.begin(
            self._name, site=self._site, attrs=self._attrs
        )
        # the stat's own running state is NEVER engaged: end() feeds
        # it a measured duration (the span's, or this private clock
        # when unsampled), so a window abandoned by an exception can
        # never fold a bogus inter-request gap into the accumulator
        # on the next start()
        if self.span is _NOOP:
            self._t0 = time.perf_counter()
        return self

    def end(self, success: bool = True) -> "StatSpan":
        self._tracer.finish(
            self.span, self._token,
            status="ok" if success else "error",
        )
        d = (
            self.span.duration
            if self.span is not _NOOP
            else time.perf_counter() - self._t0
        )
        # the SAME duration lands in both planes (SpanStat.observe is
        # the one shared fold implementation)
        self._stat.observe(d, success=success)
        return self

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end(success=exc_type is None)
        return False


def stat_span(stats, name, site="", attrs=None, trc=None) -> StatSpan:
    """StatSpan over the module tracer (or an injected one)."""
    return StatSpan(
        trc or tracer, stats, name, site=site, attrs=attrs
    )


def track_jit(fn, site: str, trc: Optional[Tracer] = None):
    """Wrap a jax.jit callable with executable-cache observability:
    each call that GROWS the jit cache (a fresh trace+compile for a
    new shape class) counts a miss and charges its wall seconds to
    `cilium_jit_cache_compile_seconds{site}` plus a `jit.compile`
    span; cache-served calls count hits.  Compile seconds include the
    first execution — that is what the caller actually waits for on a
    recompile storm, and it is the number the HBM/metric scrape needs
    to explain a latency cliff."""

    def wrapped(*args, **kwargs):
        size_fn = getattr(fn, "_cache_size", None)
        if size_fn is None:  # not a jit callable (host fallback)
            return fn(*args, **kwargs)
        from cilium_tpu.metrics import registry as metrics

        before = size_fn()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if size_fn() > before:
            metrics.jit_cache_misses.inc(site)
            metrics.jit_compile_seconds.inc(site, value=dt)
            (trc or tracer).record(
                "jit.compile", site=site, duration=dt,
                attrs={"cache_size": size_fn()},
            )
        else:
            metrics.jit_cache_hits.inc(site)
        return out

    wrapped.__wrapped__ = fn
    return wrapped


# -- rendering (CLI tree view) ----------------------------------------------


def render_span_tree(spans: List[dict]) -> str:
    """Text tree of one trace's spans (dict form, as served by
    GET /debug/traces) with per-span ms — `cilium-tpu trace <id>`.
    Orphans (parent outside the ring) render as extra roots so a
    partially-evicted trace still shows."""
    if not spans:
        return "(no spans)\n"
    spans = sorted(spans, key=lambda s: s.get("ts", 0.0))
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent_id", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    lines: List[str] = []

    def walk(span: dict, depth: int) -> None:
        pad = "  " * depth
        attrs = span.get("attrs") or {}
        extra = (
            " " + " ".join(f"{k}={v}" for k, v in attrs.items())
            if attrs
            else ""
        )
        status = span.get("status", "ok")
        mark = "" if status == "ok" else f" [{status}]"
        lines.append(
            f"{pad}{span['name']} ({span.get('site', '')}) "
            f"{span.get('duration_ms', 0.0):.3f}ms{mark}{extra}"
        )
        for ev in span.get("events") or []:
            ev = dict(ev)
            nm = ev.pop("name", "event")
            off = ev.pop("offset_ms", 0.0)
            kv = " ".join(f"{k}={v}" for k, v in ev.items())
            lines.append(f"{pad}  @{off:.3f}ms {nm} {kv}".rstrip())
        for child in children.get(span["span_id"], ()):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return "\n".join(lines) + "\n"
