"""Container-runtime integration: workload events → endpoint labels.

The behavioral port of /root/reference/pkg/workloads/docker.go: the
runtime's event stream (start/die) drives per-container serialized
queues (enqueueByContainerID); a start event fetches the container's
labels, filters them into identity-relevant vs informational sets
(retrieveDockerLabels → filterLabels), and calls the endpoint's
UpdateLabels path — identity re-allocation plus policy regeneration
(handleCreateWorkload, docker.go:391-479); a delete tears the
endpoint down.

There is no container runtime in this environment; `FakeRuntime` is
the in-proc stand-in implementing the inspect+events contract the
docker client consumes.  The daemon paths the handlers drive are
real: identity allocation, ipcache publication, regeneration.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu.labels import Label, Labels

EVENT_START = "start"
EVENT_DELETE = "delete"

# label keys the reference strips from the identity-relevant set
# (filterLabels: io.kubernetes.* bookkeeping labels are
# informational, not security-relevant)
_INFO_PREFIXES = ("io.kubernetes.",)


@dataclass
class Workload:
    """One container: id, labels, network address."""

    container_id: str
    labels: Dict[str, str]
    ipv4: Optional[str] = None
    endpoint_id: Optional[int] = None
    running: bool = True


@dataclass(frozen=True)
class WorkloadEvent:
    container_id: str
    event_type: str  # EVENT_START | EVENT_DELETE


class FakeRuntime:
    """The inspect+events surface of the docker client."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._containers: Dict[str, Workload] = {}
        self._listeners: List[Callable[[WorkloadEvent], None]] = []

    def start_container(self, workload: Workload) -> None:
        with self._lock:
            self._containers[workload.container_id] = workload
            listeners = list(self._listeners)
        for listener in listeners:
            listener(
                WorkloadEvent(workload.container_id, EVENT_START)
            )

    def stop_container(self, container_id: str) -> None:
        with self._lock:
            workload = self._containers.pop(container_id, None)
            listeners = list(self._listeners)
        if workload is not None:
            for listener in listeners:
                listener(WorkloadEvent(container_id, EVENT_DELETE))

    def inspect(self, container_id: str) -> Optional[Workload]:
        with self._lock:
            return self._containers.get(container_id)

    def enable_event_listener(
        self, listener: Callable[[WorkloadEvent], None]
    ) -> None:
        with self._lock:
            self._listeners.append(listener)


def filter_labels(
    raw: Dict[str, str]
) -> Tuple[Labels, Dict[str, str]]:
    """retrieveDockerLabels' split: identity-relevant container labels
    (source `container`, like the reference's docker label source)
    vs informational ones."""
    identity = {}
    info = {}
    for k, v in raw.items():
        if k.startswith(_INFO_PREFIXES):
            info[k] = v
        else:
            identity[k] = v
    return (
        Labels({k: Label(k, v, "container") for k, v in identity.items()}),
        info,
    )


class WorkloadWatcher:
    """EnableEventListener + processEvents (docker.go:264,330): one
    serialized queue per container id, start → create/update the
    endpoint from the container's labels, delete → tear it down."""

    def __init__(self, daemon, runtime: FakeRuntime) -> None:
        self.daemon = daemon
        self.runtime = runtime
        self._queues: Dict[str, "queue.Queue"] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._by_container: Dict[str, int] = {}
        self._next_ep_id = 10_000

    def start(self) -> None:
        self.runtime.enable_event_listener(self._enqueue)

    # -- per-container serialized queues (enqueueByContainerID) -----------

    def _enqueue(self, event: WorkloadEvent) -> None:
        with self._lock:
            q = self._queues.get(event.container_id)
            if q is None:
                q = queue.Queue()
                self._queues[event.container_id] = q
                thread = threading.Thread(
                    target=self._drain_loop,
                    args=(q,),
                    name=f"workload-{event.container_id[:8]}",
                    daemon=True,
                )
                self._threads[event.container_id] = thread
                thread.start()
        q.put(event)

    def _drain_loop(self, q: "queue.Queue") -> None:
        while True:
            event = q.get()
            if event is None:
                return
            try:
                self._process(event)
            except Exception:
                pass  # docker.go logs and keeps the listener alive

    def drain(self) -> None:
        done = []
        with self._lock:
            queues = list(self._queues.values())
        for q in queues:
            marker = threading.Event()
            q.put(marker)
            done.append(marker)
        for marker in done:
            marker.wait(timeout=10.0)

    # -- handlers ----------------------------------------------------------

    def _process(self, event) -> None:
        if isinstance(event, threading.Event):  # drain marker
            event.set()
            return
        if event.event_type == EVENT_START:
            self._handle_start(event.container_id)
        elif event.event_type == EVENT_DELETE:
            self._handle_delete(event.container_id)

    def _handle_start(self, container_id: str) -> None:
        """handleCreateWorkload (docker.go:391): inspect, filter
        labels, create or relabel the endpoint."""
        workload = self.runtime.inspect(container_id)
        if workload is None or not workload.running:
            return  # IgnoreRunningWorkloads / raced a stop
        identity_labels, _info = filter_labels(workload.labels)
        ep_id = self._by_container.get(container_id)
        if ep_id is None:
            with self._lock:
                ep_id = (
                    workload.endpoint_id
                    if workload.endpoint_id is not None
                    else self._next_ep_id
                )
                self._next_ep_id = max(
                    self._next_ep_id + 1, ep_id + 1
                )
            self.daemon.create_endpoint(
                ep_id,
                identity_labels,
                ipv4=workload.ipv4,
                name=container_id,
            )
            self._by_container[container_id] = ep_id
        else:
            # UpdateLabels (docker.go:479): re-allocate the identity
            # from the new label set and regenerate
            self.daemon.update_endpoint_labels(ep_id, identity_labels)

    def _handle_delete(self, container_id: str) -> None:
        ep_id = self._by_container.pop(container_id, None)
        if ep_id is not None:
            self.daemon.delete_endpoint(ep_id)
