"""XDP-style CIDR prefilter.

Port of /root/reference/pkg/policy/prefilter.go (+ daemon/prefilter.go,
bpf/bpf_xdp.c): a deny-by-CIDR stage that drops flows BEFORE the
policy engine runs — the reference compiles CIDR4_*_MAPs consulted by
XDP.

TPU-first lowering: deny lists are usually SMALL, and a random gather
costs ~7 ns/query on v5e while an [B, P] broadcast compare is nearly
free — so up to MAX_BROADCAST prefixes compile to (base, mask) range
arrays checked with one vectorized compare (zero gathers in the fused
step).  Larger sets fall back to the DIR-24-8 structure shared with
the ipcache (two gathers)."""

from __future__ import annotations

import ipaddress
import threading
from dataclasses import dataclass
from typing import List, Set, Tuple, Union

import numpy as np

from cilium_tpu.ipcache.lpm import LPMTables, build_lpm

# marker identity for "listed in the prefilter" (any nonzero works:
# lpm misses resolve to 0)
_LISTED = 1

MAX_BROADCAST = 128


@dataclass
class PrefilterRanges:
    """Broadcast-compare prefilter: drop iff any (ip & mask) == base.
    Arrays padded to a pow2 ≤ MAX_BROADCAST (padding rows have
    mask == 0, base == 1 — unmatchable)."""

    base: np.ndarray  # u32 [P]
    mask: np.ndarray  # u32 [P]

    def tree_flatten(self):
        return ((self.base, self.mask), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        import jax

        jax.tree_util.register_pytree_node(
            PrefilterRanges,
            lambda t: t.tree_flatten(),
            lambda aux, ch: PrefilterRanges.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass


_register_pytree()


def build_prefilter(
    cidrs,
) -> "Union[PrefilterRanges, LPMTables]":
    """Lower a prefilter CIDR set (iterable of v4 cidr strings, or a
    {cidr: marker} dict) to the broadcast form when small, DIR-24-8
    otherwise."""
    cidr_list = sorted(cidrs)
    v4 = []
    for c in cidr_list:
        net = ipaddress.ip_network(c, strict=False)
        if net.version != 4:
            continue
        v4.append(
            (int(net.network_address), int(net.netmask))
        )
    if len(v4) > MAX_BROADCAST:
        return build_lpm({c: _LISTED for c in cidr_list})
    p = 8
    while p < len(v4):
        p *= 2
    base = np.ones(p, dtype=np.uint32)  # base 1 & mask 0 never matches
    mask = np.zeros(p, dtype=np.uint32)
    for i, (b, m) in enumerate(v4):
        base[i] = b
        mask[i] = m
    return PrefilterRanges(base=base, mask=mask)


def prefilter_drop(tables, src_ips):
    """bool [B]: True = drop before policy (XDP_DROP).  Dispatches on
    the compiled form (the form is static pytree structure, so each
    jit cache entry sees exactly one branch)."""
    import jax.numpy as jnp

    if isinstance(tables, PrefilterRanges):
        ips = src_ips.astype(jnp.uint32)
        return jnp.any(
            (ips[:, None] & jnp.asarray(tables.mask)[None, :])
            == jnp.asarray(tables.base)[None, :],
            axis=1,
        )
    from cilium_tpu.ipcache.lpm import _lookup_kernel

    return _lookup_kernel(tables, src_ips) != 0


class PreFilter:
    """prefilter.go PreFilter: insert/delete CIDRs, compile to device
    tables, per-batch drop mask."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cidrs: Set[str] = set()
        self._revision = 0
        self._tables = (0, build_prefilter(set()))

    def insert(self, cidrs: List[str]) -> int:
        with self._lock:
            self._cidrs.update(cidrs)
            self._revision += 1
            return self._revision

    def delete(self, cidrs: List[str]) -> int:
        with self._lock:
            for c in cidrs:
                self._cidrs.discard(c)
            self._revision += 1
            return self._revision

    def dump(self) -> List[str]:
        with self._lock:
            return sorted(self._cidrs)

    def tables(self):
        with self._lock:
            version, tables = self._tables
            if version != self._revision:
                tables = build_prefilter(self._cidrs)
                self._tables = (self._revision, tables)
            return tables


def prefilter_batch(tables, src_ips):
    """bool [B]: True = drop before policy (XDP_DROP)."""
    return prefilter_drop(tables, src_ips)
