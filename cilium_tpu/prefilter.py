"""XDP-style CIDR prefilter.

Port of /root/reference/pkg/policy/prefilter.go (+ daemon/prefilter.go,
bpf/bpf_xdp.c): a deny-by-CIDR stage that drops flows BEFORE the
policy engine runs — the reference compiles CIDR4_*_MAPs consulted by
XDP; here the prefix set lowers onto the same DIR-24-8 structure and
the engine applies the drop mask ahead of the verdict lattice.
"""

from __future__ import annotations

import threading
from typing import List, Set, Tuple

from cilium_tpu.ipcache.lpm import LPMTables, build_lpm

# marker identity for "listed in the prefilter" (any nonzero works:
# lpm misses resolve to 0)
_LISTED = 1


class PreFilter:
    """prefilter.go PreFilter: insert/delete CIDRs, compile to device
    tables, per-batch drop mask."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cidrs: Set[str] = set()
        self._revision = 0
        self._tables: Tuple[int, LPMTables] = (0, build_lpm({}))

    def insert(self, cidrs: List[str]) -> int:
        with self._lock:
            self._cidrs.update(cidrs)
            self._revision += 1
            return self._revision

    def delete(self, cidrs: List[str]) -> int:
        with self._lock:
            for c in cidrs:
                self._cidrs.discard(c)
            self._revision += 1
            return self._revision

    def dump(self) -> List[str]:
        with self._lock:
            return sorted(self._cidrs)

    def tables(self) -> LPMTables:
        with self._lock:
            version, tables = self._tables
            if version != self._revision:
                tables = build_lpm({c: _LISTED for c in self._cidrs})
                self._tables = (self._revision, tables)
            return tables


def prefilter_batch(tables: LPMTables, src_ips):
    """bool [B]: True = drop before policy (XDP_DROP)."""
    from cilium_tpu.ipcache.lpm import _lookup_kernel

    return _lookup_kernel(tables, src_ips) != 0
