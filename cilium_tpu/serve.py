"""Continuous serving plane: streaming admission, SLO-aware dynamic
batching, and multi-tenant fair dispatch over the async/mesh pipeline.

Every dispatch path before this module is request→one batch→reply:
the headline verdicts/s only materializes when a caller hands the
daemon perfectly sized batches, but real traffic from millions of
users arrives as a stream of SMALL flows.  This is the steady-state
ingest pipeline the ROADMAP names — the continuous-batching insight
of PagedAttention/vLLM (arXiv:2309.06180) and the t5x partitioned
serving loop (arXiv:2203.17189) applied to policy verdicts:

  * **Streaming admission.**  `ServingPlane.submit()` decodes a
    flow-record buffer, runs the daemon's unknown-endpoint filter and
    XDP prefilter (shared `Daemon._prefilter_records` — prefiltered
    drops surface immediately, with the submitting tenant on the
    record), and queues the remainder on the tenant's ingest queue.
    A tenant whose backlog would exceed its bound is SHED, not
    queued: every shed flow carries the canonical Overload drop
    reason exactly once — a flow record naming the tenant, the
    shared shed_flows_total counter, and the per-tenant
    serve_shed_flows_total counter (backpressure is attribution,
    never buffering — the AdmissionGate contract).

  * **SLO-aware dynamic batching.**  One serve loop coalesces queued
    flows into device batches of ONE padded jit class (`batch_size`,
    by default the PR 6 autotuner's choice for the published
    tables): the batch grows while the oldest queued flow's deadline
    still allows a dispatch (an EWMA of recent batch walls estimates
    the cost), and dispatches early — partially filled — the moment
    it doesn't.  serve_batch_fill_pct / serve_queue_delay_seconds /
    serve_deadline_dispatch_total expose the trade.

  * **Multi-tenant fair dispatch.**  Batch composition is deficit
    round robin over the tenant queues (weights from
    `PATCH /config {"tenant_weights": ...}`): each round adds
    weight×quantum to a tenant's deficit and takes that many flows,
    so a noisy tenant flooding 10× cannot starve a compliant one —
    with equal weights each backlogged tenant holds ~half of every
    coalesced batch, and the flood sheds against ITS OWN backlog
    bound.

  * **The existing hot path end to end.**  Coalesced batches ride
    engine.publish.AsyncBatchDispatcher (the host pack of batch N+1
    overlaps device compute of batch N), dispatch through
    `Daemon._dispatch_or_degrade` — the breaker/retry/watchdog
    guard, the verdict-memoization plane, and the ChipFailoverRouter
    when a mesh is attached (the PR 8 remainder: the production
    dispatch loop now routes through the per-chip failure domain) —
    and results demux back to per-submission replies in stream
    order.  The monitor/flow/metrics folds per batch are the same
    calls the one-shot path makes, so verdict, counter, telemetry
    and flow surfaces are bit-identical to `process_flows` on the
    same tuples.

Simulation boundary: on this container the "device" is XLA's CPU
backend — absolute serving_p99_ms / sustained_verdicts_per_sec are
only meaningful on real hardware (the driver's bench box); what the
tier-1 suite pins here is the semantics — bit-identity, fairness
shares, exactly-once shed accounting, zero lost/duplicated
submissions across faults.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu import option, tracing
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics

log = get_logger("serve")


def quantile_ms(latencies_s, p: float) -> float:
    """The ONE sorted-list latency quantile this plane and its
    harnesses share (serveprof asserts the plane's p99 against the
    harness's — they must be the same computation)."""
    lats = sorted(latencies_s)
    if not lats:
        return 0.0
    return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0


def tenant_seed(seed: int, name: str) -> int:
    """Stable per-tenant RNG seed (hash() is randomized per process;
    a storm failure must reproduce under the same --seed)."""
    import zlib

    return seed + (zlib.crc32(name.encode()) & 0xFFFF)


class ServeResult:
    """Per-submission reply handle: verdict columns in the
    submission's own stream order, filled as its spans drain.
    ``shed_mask`` marks flows shed at dispatch time (admission
    gate); ``shed`` marks a whole submission refused at the tenant
    backlog bound.  ``wait()`` blocks until every flow is accounted
    (served or shed)."""

    def __init__(self, n: int, tenant: str) -> None:
        self.n = n
        self.tenant = tenant
        self.allowed = np.zeros(n, bool)
        self.match_kind = np.zeros(n, np.int32)
        self.proxy_port = np.zeros(n, np.int32)
        self.cache_hit = np.zeros(n, bool)
        self.shed_mask = np.zeros(n, bool)
        self.shed = False
        self.degraded_batches = 0
        self.batches = 0
        self.prefiltered = 0
        self.dropped_unknown = 0
        self.queue_delay_s = 0.0  # max span wait in this submission
        self.latency_s: Optional[float] = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> "ServeResult":
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"submission of {self.n} flows not served within "
                f"{timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self

    def verdict_columns(self) -> Dict[str, np.ndarray]:
        return {
            "allowed": self.allowed,
            "match_kind": self.match_kind,
            "proxy_port": self.proxy_port,
        }


class _Submission:
    __slots__ = (
        "rec", "tenant", "n", "cursor", "served", "t_enqueue",
        "deadline", "result",
    )

    def __init__(self, rec, tenant, deadline, result) -> None:
        self.rec = rec
        self.tenant = tenant
        self.n = len(rec["ep_id"])
        self.cursor = 0  # flows handed to a batch plan
        self.served = 0  # flows accounted at drain (served or shed)
        self.t_enqueue = time.monotonic()
        self.deadline = deadline
        self.result = result


class _Tenant:
    __slots__ = (
        "name", "weight", "queue", "backlog", "deficit",
        "admitted", "shed", "dispatched", "cache_hits", "served",
        "slo_class",
    )

    def __init__(self, name: str, weight: float = 1.0) -> None:
        self.name = name
        self.weight = float(weight)
        # named SLO class (PATCH /config {"tenant_slo": ...}); None
        # = the plane's default deadline/weight/shed behavior
        self.slo_class: Optional[str] = None
        self.queue: deque = deque()
        self.backlog = 0  # flows queued, not yet planned
        self.deficit = 0.0
        self.admitted = 0
        self.shed = 0
        self.dispatched = 0
        # verdict-cache hits among this tenant's SERVED flows (the
        # cross-tenant memo plane's per-tenant observability)
        self.cache_hits = 0
        self.served = 0


class ServingPlane:
    """The shared ingest queue + serve loop in front of a Daemon.

    One background thread owns batch composition and dispatch; any
    number of submitters feed it concurrently (the REST route's
    thread-per-connection model maps straight onto `submit`).
    """

    def __init__(
        self,
        daemon,
        *,
        batch_size: Optional[int] = None,
        slo_ms: float = 25.0,
        max_tenant_backlog: int = 1 << 16,
        async_depth: Optional[int] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        quantum: Optional[int] = None,
        fused: bool = False,
        slo_classes: Optional[Dict[str, Dict]] = None,
        tenant_slo: Optional[Dict[str, str]] = None,
    ) -> None:
        self.daemon = daemon
        # fused serving: coalesced batches carry the RAW 5-tuple
        # columns (saddr/daddr/sport ride the staged batch) and
        # dispatch through the attached ChipFailoverRouter's fused
        # datapath plane (router.dispatch_flows) — the FULL pipeline
        # (prefilter + LB/DNAT + CT + ipcache + lattice) served over
        # the partitioned N+1 tables, replica gathers and all.
        # Requires daemon.attach_mesh_router + router.attach_datapath.
        self.fused = bool(fused)
        self.batch_size = int(
            batch_size
            if batch_size is not None
            else self._autotuned_batch_size()
        )
        self.slo_s = float(slo_ms) / 1000.0
        self.max_tenant_backlog = int(max_tenant_backlog)
        self.async_depth = (
            daemon.dispatch_async_depth
            if async_depth is None
            else int(async_depth)
        )
        # DRR quantum (flows per round per unit weight): small
        # enough that one round never hands a single tenant the
        # whole batch, large enough to amortize the loop
        self.quantum = int(
            quantum
            if quantum is not None
            else max(64, self.batch_size // 8)
        )
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: Dict[str, _Tenant] = {}
        self._weights = dict(tenant_weights or {})
        # named SLO classes: {name: {"deadline_ms", "shed_priority",
        # "weight"}} + the tenant -> class assignment.  A class
        # bundles the deadline the EWMA batch-wall model protects
        # (per-class early dispatch), the DRR weight, and the shed
        # priority (HIGHER numbers shed FIRST under admission-gate
        # pressure).
        self._slo_classes: Dict[str, Dict] = dict(slo_classes or {})
        self._tenant_slo: Dict[str, str] = dict(tenant_slo or {})
        self._stop = False
        self._drain_on_stop = True
        self._thread: Optional[threading.Thread] = None
        # snapshot cache: endpoint-axis LUTs per published version
        self._lut_version = None
        self._luts = None
        # EWMA of recent coalesced-batch walls (pack→drain), the
        # dispatch-cost estimate behind "grow while the deadline
        # allows"; seeded pessimistically at slo/4 so the first
        # batches lean early rather than blow the SLO
        self._batch_wall_ewma: Optional[float] = None
        # rolling submission latencies → serving_p99_ms gauge; the
        # plane keeps its OWN window (the registry histogram is
        # process-global and may mix planes)
        self._completions = 0
        self._latency_window: deque = deque(maxlen=512)
        # stats
        self.batches = 0
        self.flows_served = 0
        self.early_dispatches = 0
        self.fill_sum = 0.0
        self.degraded_batches = 0
        # per-batch tenant composition ({tenant: flows}, newest
        # last): the fairness gate's evidence — batches where two
        # tenants were both backlogged must show the DRR shares
        self.batch_mix: deque = deque(maxlen=1024)
        # the loop's AsyncBatchDispatcher, exposed for the perf
        # plane (overlap aggregates + "anything in flight?" for the
        # ingest-stall detector)
        self._dispatcher = None
        # batch-boundary barriers (run_at_batch_boundary): callables
        # the serve loop runs BETWEEN dispatches, after draining the
        # in-flight overlap batch — the quiesce seam a reshard
        # cutover flips epochs through.  Admission never pauses;
        # queued flows simply land on whichever epoch is live when
        # their batch composes.
        self._barriers: deque = deque()

    # -- construction helpers -------------------------------------------------

    def _autotuned_batch_size(self) -> int:
        """Default device-batch jit class: the PR 6 autotuner's
        cached choice for the published tables' shape class when one
        exists, else a serving-friendly 4096."""
        try:
            from cilium_tpu.engine import autotune

            _, tables, _ = self.daemon.endpoint_manager.published()
            if tables is not None:
                hit = autotune.cached_choice(
                    autotune.shape_class_key(tables)
                )
                if hit is not None and hit.params.get("batch"):
                    return int(hit.params["batch"])
        except Exception:  # pragma: no cover - defensive default
            pass
        return 1 << 12

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "ServingPlane":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="serve-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the serve loop.  With `drain` (default) every queued
        flow is dispatched first; without, queued flows are shed
        (Overload, exactly once each) so no submission ever hangs."""
        with self._cond:
            self._stop = True
            self._drain_on_stop = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    def set_batch_size(self, batch_size: int) -> None:
        """Live batch-class swap (the online re-tune's batch knob):
        applies to batches COMPOSED from now on — an in-flight batch
        keeps the pad size snapshotted into its meta at stage time,
        so a swap never races the pack of a batch planned under the
        old class."""
        with self._cond:
            self.batch_size = int(batch_size)
            self.quantum = max(64, self.batch_size // 8)
            self._cond.notify_all()

    def set_tenant_weights(self, weights: Dict[str, float]) -> None:
        with self._cond:
            self._weights.update(
                {k: float(v) for k, v in weights.items()}
            )
            for name, t in self._tenants.items():
                t.weight = self._resolve_weight(name)

    def set_slo_classes(
        self,
        classes: Dict[str, Dict],
        tenant_slo: Optional[Dict[str, str]] = None,
    ) -> None:
        """Live-apply the named SLO class bundles + tenant
        assignments (PATCH /config): weights re-resolve immediately;
        deadlines apply to submissions from now on."""
        with self._cond:
            self._slo_classes = dict(classes)
            if tenant_slo is not None:
                self._tenant_slo = dict(tenant_slo)
            for name, t in self._tenants.items():
                t.slo_class = self._tenant_slo.get(name)
                t.weight = self._resolve_weight(name)

    def _class_of(self, tenant: str) -> Optional[Dict]:
        cls = self._tenant_slo.get(tenant)
        return self._slo_classes.get(cls) if cls else None

    def _resolve_weight(self, name: str) -> float:
        cls = self._class_of(name)
        if cls is not None and cls.get("weight") is not None:
            return float(cls["weight"])
        return float(self._weights.get(name, 1.0))

    def _shed_priority(self, tenant: str) -> int:
        cls = self._class_of(tenant)
        if cls is None:
            return 0
        return int(cls.get("shed_priority", 0))

    # -- admission ------------------------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name, self._resolve_weight(name))
            t.slo_class = self._tenant_slo.get(name)
            self._tenants[name] = t
        return t

    def submit(
        self,
        buf: Optional[bytes] = None,
        rec: Optional[dict] = None,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        wait: bool = False,
        timeout: Optional[float] = 60.0,
    ) -> ServeResult:
        """Submit one flow-record buffer (or pre-decoded SoA) for a
        tenant.  Non-blocking by default: returns a ServeResult
        handle whose columns fill as the stream serves; `wait=True`
        blocks until the submission completes.  A malformed buffer
        raises ValueError (HTTP 400 at the REST seam); a tenant past
        its backlog bound gets the whole submission shed with
        exactly-once Overload accounting."""
        from cilium_tpu.native import decode_flow_records

        if rec is None:
            rec = decode_flow_records(buf)
        n_raw = len(rec["ep_id"])
        # filter against the submit-time snapshot (the same guards
        # process_flows applies before batching)
        version, _, index, _ = (
            self.daemon.endpoint_manager.published_with_states()
        )
        if index is None:
            index = {}
        local_ident_lut, _ = self._luts_for(version, index)
        known = np.isin(
            rec["ep_id"], np.fromiter(index, dtype=np.int64)
        )
        n_unknown = int((~known).sum())
        if n_unknown:
            rec = {k: v[known] for k, v in rec.items()}
        rec, n_prefiltered = self.daemon._prefilter_records(
            rec, index, local_ident_lut, tenant=tenant,
            trace_id=tracing.current_trace_id(),
        )
        n = len(rec["ep_id"])
        result = ServeResult(n, tenant)
        result.dropped_unknown = n_unknown
        result.prefiltered = n_prefiltered
        if deadline_ms is None:
            # the tenant's named SLO class (when assigned) carries
            # the default per-flow deadline the dynamic batcher's
            # EWMA batch-wall model protects
            cls = self._class_of(tenant)
            deadline_s = (
                float(cls["deadline_ms"]) / 1000.0
                if cls is not None and cls.get("deadline_ms")
                else self.slo_s
            )
        else:
            deadline_s = float(deadline_ms) / 1000.0
        deadline = time.monotonic() + deadline_s
        sub = _Submission(rec, tenant, deadline, result)
        if n == 0:
            result.latency_s = 0.0
            result._event.set()
            return result
        with self._cond:
            if self._stop:
                raise RuntimeError("serving plane is stopped")
            t = self._tenant(tenant)
            if t.backlog + n > self.max_tenant_backlog:
                # backpressure: shed the WHOLE submission, exactly
                # once per flow, against THIS tenant only
                t.shed += n
            else:
                t.queue.append(sub)
                t.backlog += n
                t.admitted += n
                metrics.serve_admitted_flows_total.inc(
                    tenant, value=n
                )
                metrics.serve_queue_depth.set(
                    tenant, value=t.backlog
                )
                self._cond.notify_all()
                sub = None  # queued — not shed below
        if sub is not None:
            self._shed_flows(sub.rec, tenant, 0, n)
            result.shed = True
            result.shed_mask[:] = True
            result.latency_s = 0.0
            result._event.set()
            if wait:
                return result.wait(timeout)
            return result
        if wait:
            return result.wait(timeout)
        return result

    def _shed_flows(
        self, rec, tenant, start, end, gate_counted: bool = False
    ) -> None:
        """Exactly-once Overload accounting for [start, end) of a
        submission's record SoA: the canonical drop counter, the
        shared + per-tenant shed counters, and one flow record per
        flow naming the tenant (capped at ring capacity — the rest
        charge the eviction counter, the capture_batch drop-storm
        rule).  `gate_counted` marks sheds the AdmissionGate's own
        reserve() refusal already charged to shed_total."""
        from cilium_tpu.flow.store import (
            VERDICT_DROPPED,
            FlowRecord,
        )
        from cilium_tpu.monitor.events import (
            DROP_OVERLOAD,
            drop_reason_name,
        )
        from cilium_tpu.replay import _ep_index_of

        n = end - start
        if n <= 0:
            return
        reason = drop_reason_name(DROP_OVERLOAD)
        dirs = rec["direction"][start:end]
        for dirv, dname in ((0, "INGRESS"), (1, "EGRESS")):
            count = int((dirs == dirv).sum())
            if count:
                metrics.drop_count.inc(reason, dname, value=count)
        metrics.shed_flows_total.inc(value=n)
        metrics.serve_shed_flows_total.inc(tenant, value=n)
        if not gate_counted:
            self.daemon.admission.charge_shed(n)
        tracing.add_event(
            "admission.shed", flows=n, tenant=tenant
        )
        store = self.daemon.flow_store
        build = min(n, store.capacity)
        truncated = n - build
        version, _, index, _ = (
            self.daemon.endpoint_manager.published_with_states()
        )
        local_ident_lut, _ = self._luts_for(version, index or {})
        sl = slice(end - build, end)
        ep_idx = _ep_index_of(
            {"ep_id": rec["ep_id"][sl]}, dict(index or {})
        )
        peer = rec["identity"][sl].astype(np.int64)
        local = local_ident_lut[ep_idx]
        dirs = rec["direction"][sl]
        src = np.where(dirs == 0, peer, local)
        dst = np.where(dirs == 0, local, peer)
        ts = time.time()
        records = [
            FlowRecord(
                ts=ts,
                chip=0,
                ep_id=int(rec["ep_id"][sl][i]),
                src_identity=int(src[i]),
                dst_identity=int(dst[i]),
                dport=int(rec["dport"][sl][i]),
                proto=int(rec["proto"][sl][i]),
                direction=int(dirs[i]),
                verdict=VERDICT_DROPPED,
                match_kind=0,
                drop_reason=reason,
                tenant=tenant,
            )
            for i in range(build)
        ]
        store.extend(records)
        store.charge_evicted(truncated)
        metrics.flow_records_captured_total.inc(
            VERDICT_DROPPED, value=n
        )
        metrics.flow_store_evicted.set(value=store.evicted)

    # -- batch composition (SLO-aware + DRR) ----------------------------------

    def _backlog(self) -> int:
        return sum(t.backlog for t in self._tenants.values())

    def _dispatch_estimate(self) -> float:
        return (
            self._batch_wall_ewma
            if self._batch_wall_ewma is not None
            else self.slo_s / 4.0
        )

    def _head_deadline(self) -> float:
        return self._head_deadline_info()[0]

    def _head_deadline_info(self):
        """(deadline, tenant) of the oldest queued flow — the
        submission whose SLO forces an early dispatch; its tenant's
        class labels serve_deadline_dispatch_total."""
        best = (float("inf"), None)
        for t in self._tenants.values():
            if t.queue and t.queue[0].deadline < best[0]:
                best = (t.queue[0].deadline, t.name)
        return best

    def _next_plan(self):
        """Block until a batch should dispatch.  Returns (spans,
        mix, early, early_class) or None at stop-with-empty-queue.
        `spans` is a list of (submission, sub_start, sub_end)
        totaling <= batch_size flows, composed by deficit round
        robin; `early_class` names the SLO class whose deadline
        forced an early dispatch ("default" for unclassed)."""
        with self._cond:
            while True:
                if self._barriers:
                    # a batch-boundary barrier is queued: hand the
                    # loop an empty plan so it reaches the boundary
                    # (flush + run barriers) even when the tenant
                    # queues are idle
                    return [], {}, False, None
                backlog = self._backlog()
                if backlog == 0:
                    if self._stop:
                        return None
                    self._cond.wait(timeout=0.05)
                    continue
                if self._stop or backlog >= self.batch_size:
                    # full batch (or draining): dispatch now
                    return self._compose_locked() + (False, None)
                now = time.monotonic()
                head, head_tenant = self._head_deadline_info()
                latest_start = head - self._dispatch_estimate()
                if now >= latest_start:
                    # SLO-forced early dispatch: growing further
                    # would blow the oldest flow's deadline
                    early_class = (
                        self._tenant_slo.get(head_tenant)
                        or "default"
                        if head_tenant is not None
                        else "default"
                    )
                    return self._compose_locked() + (
                        True, early_class,
                    )
                t_wait = time.monotonic()
                self._cond.wait(
                    timeout=max(
                        0.0005, min(latest_start - now, 0.05)
                    )
                )
                # ingest-starvation accumulator: this wait holds a
                # NONEMPTY queue (the coalescing-grow branch); when
                # nothing is in flight the device sat idle for it —
                # the line-rate-ingest symptom the perf plane counts
                perf = getattr(self.daemon, "perf", None)
                d = self._dispatcher
                if (
                    perf is not None
                    and d is not None
                    and not d._pending
                ):
                    perf.note_stall(time.monotonic() - t_wait)

    def _compose_locked(self):
        """Deficit round robin over the tenant queues: each round
        credits weight×quantum flows, each tenant drains whole or
        partial submissions against its deficit — one noisy tenant
        cannot hold more than its share of a contended batch, and
        flows WITHIN a submission stay in order.  Returns (spans,
        mix) where mix records, per tenant, the flows taken and the
        backlog LEFT BEHIND — the fairness gate's evidence that a
        small share meant a small offer, not starvation."""
        spans: List[Tuple[_Submission, int, int]] = []
        remaining = self.batch_size
        while remaining > 0:
            active = [
                t for t in self._tenants.values() if t.backlog > 0
            ]
            if not active:
                break
            for t in sorted(active, key=lambda x: x.name):
                t.deficit += t.weight * self.quantum
                while t.queue and t.deficit >= 1 and remaining > 0:
                    sub = t.queue[0]
                    take = min(
                        sub.n - sub.cursor,
                        remaining,
                        int(t.deficit),
                    )
                    if take <= 0:
                        break
                    spans.append(
                        (sub, sub.cursor, sub.cursor + take)
                    )
                    sub.cursor += take
                    t.backlog -= take
                    t.deficit -= take
                    t.dispatched += take
                    remaining -= take
                    if sub.cursor == sub.n:
                        t.queue.popleft()
                if not t.queue:
                    # classic DRR: an idle queue keeps no credit
                    t.deficit = 0.0
                metrics.serve_queue_depth.set(
                    t.name, value=t.backlog
                )
        mix: Dict[str, Dict[str, int]] = {}
        for sub, s, e in spans:
            row = mix.setdefault(
                sub.tenant, {"flows": 0, "left": 0}
            )
            row["flows"] += e - s
        for name, row in mix.items():
            row["left"] = self._tenants[name].backlog
        return spans, mix

    # -- the serve loop -------------------------------------------------------

    def _loop(self) -> None:
        from cilium_tpu.engine.publish import AsyncBatchDispatcher

        dispatcher = AsyncBatchDispatcher(
            pack_fn=self._pack,
            dispatch_fn=self._dispatch,
            depth=self.async_depth,
        )
        self._dispatcher = dispatcher
        try:
            while True:
                if self._barriers:
                    # batch boundary: the previous dispatch returned
                    # and the overlap batch drains on ITS epoch
                    # before the barrier runs — in-flight buffers
                    # are never swapped out from under a batch
                    for done in dispatcher.flush():
                        self._complete(*done)
                    while self._barriers:
                        self._run_barrier(self._barriers.popleft())
                plan = self._next_plan()
                if plan is None:
                    break
                spans, mix, early, early_class = plan
                if not spans:
                    continue
                if not self._drain_on_stop and self._stop:
                    # shed instead of dispatching the leftover
                    for sub, s, e in spans:
                        self._shed_span(sub, s, e)
                    continue
                meta = self._stage(spans, mix, early, early_class)
                if meta is None:
                    continue  # whole plan shed at the gate
                for done in dispatcher.submit(
                    (meta,), meta=meta
                ):
                    self._complete(*done)
                # overlap pays only under sustained load: when the
                # queue went idle there is no batch N+1 to pack, so
                # drain the in-flight batch NOW instead of holding
                # its replies hostage to the next arrival
                with self._cond:
                    idle = self._backlog() == 0
                if idle:
                    for done in dispatcher.flush():
                        self._complete(*done)
            for done in dispatcher.flush():
                self._complete(*done)
            # a barrier that raced stop still runs (the stream is
            # quiesced by definition here) so its submitter never
            # hangs on a dead loop
            while self._barriers:
                self._run_barrier(self._barriers.popleft())
        except Exception as loop_exc:  # last-resort guard: nothing
            # may hang — in-flight batches release their admission
            # units and every pending reply errors out instead of
            # blocking its submitter until the REST timeout
            log.exception("serve loop died")
            failed = set()
            for meta2, _res, _exc in dispatcher.flush():
                self.daemon.admission.release(meta2["valid"])
                so = meta2.get("shadow_out")
                if so is not None:  # in-flight shadow sample:
                    # refuse, exactly once
                    self.daemon.shadow.refuse(so.shadow_ticket)
                for sub, _s, _e in meta2["spans"]:
                    failed.add(id(sub))
                    sub.result.error = RuntimeError(
                        f"serve loop died: {loop_exc}"
                    )
                    sub.result._event.set()
            with self._cond:
                self._stop = True  # submit() must refuse from now on
                for t in self._tenants.values():
                    while t.queue:
                        sub = t.queue.popleft()
                        t.backlog -= sub.n - sub.cursor
                        if id(sub) not in failed:
                            sub.result.error = RuntimeError(
                                f"serve loop died: {loop_exc}"
                            )
                            sub.result._event.set()

    @staticmethod
    def _run_barrier(b: dict) -> None:
        try:
            b["result"] = b["fn"]()
        except BaseException as exc:  # surfaced to the submitter
            b["error"] = exc
        finally:
            b["event"].set()

    def run_at_batch_boundary(self, fn, timeout_s: float = 30.0):
        """Run `fn` on the serve loop BETWEEN batches: after the
        in-flight overlap batch drains on its own epoch, before the
        next plan composes.  The epoch-flip seam for a live reshard
        cutover — admission keeps accepting throughout (queued flows
        land on whichever epoch is live when their batch composes);
        nothing is drained except the one overlapped batch that was
        already dispatched.  Returns fn()'s result, re-raising its
        exception.  Called with no loop running (not started, or
        stopped), runs inline — the stream is trivially quiesced."""
        thread = self._thread
        if (
            thread is None
            or not thread.is_alive()
            or threading.current_thread() is thread
        ):
            return fn()
        box = {
            "fn": fn, "event": threading.Event(),
            "result": None, "error": None,
        }
        with self._cond:
            if self._stop and self._backlog() == 0:
                # loop may already be past its final flush
                return fn()
            self._barriers.append(box)
            self._cond.notify_all()
        if not box["event"].wait(timeout=timeout_s):
            raise TimeoutError(
                f"batch-boundary barrier not reached within "
                f"{timeout_s}s"
            )
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def _stage(self, spans, mix, early, early_class=None):
        """Concatenate a plan's record slices into one host batch
        dict + bookkeeping meta.  Applies the AdmissionGate with
        SHED-PRIORITY ordering: when the gate refuses the full plan,
        whole tenants shed in DESCENDING shed priority (the SLO
        class bundle; higher numbers shed first, unclassed tenants
        are priority 0) until the remainder fits — so under gate
        pressure a gold-class tenant's flows survive the overload a
        noisy tenant created.  Every shed flow gets exactly-once
        Overload accounting; replies complete with shed_mask set."""
        keep = list(spans)
        shed_spans: List[Tuple[_Submission, int, int]] = []
        while keep:
            n_keep = sum(e - s for _sub, s, e in keep)
            if self.daemon.admission.reserve(n_keep, charge=False):
                break
            with self._lock:
                worst = max(
                    self._shed_priority(sub.tenant)
                    for sub, _s, _e in keep
                )
                shed_now = [
                    sp for sp in keep
                    if self._shed_priority(sp[0].tenant) == worst
                ]
            shed_spans.extend(shed_now)
            keep = [sp for sp in keep if sp not in shed_now]
        for sub, s, e in shed_spans:
            self._shed_span(sub, s, e)
        if not keep:
            return None
        spans = keep
        if shed_spans:
            # the fairness evidence must describe the batch that
            # actually dispatched
            for name in {sub.tenant for sub, _s, _e in shed_spans}:
                row = mix.get(name)
                if row is not None:
                    row["flows"] = sum(
                        e - s
                        for sub, s, e in spans
                        if sub.tenant == name
                    )
        fields = (
            "ep_id", "identity", "dport", "proto",
            "direction", "is_fragment",
        )
        if self.fused:
            # the fused pipeline consumes the raw 5-tuple: the
            # address/sport columns every decoded record already
            # carries ride the staged batch
            fields = fields + ("saddr", "daddr", "sport")
        cols = {
            f: np.concatenate(
                [sub.rec[f][s:e] for sub, s, e in spans]
            )
            for f in fields
        }
        valid = len(cols["ep_id"])
        tenants_col = np.concatenate(
            [
                np.full(e - s, sub.tenant, dtype=object)
                for sub, s, e in spans
            ]
        )
        if early:
            metrics.serve_deadline_dispatch_total.inc(
                early_class or "default"
            )
            self.early_dispatches += 1
        return {
            "spans": spans,
            "mix": mix,
            "cols": cols,
            "tenants": tenants_col,
            "valid": valid,
            "early": early,
            "t_plan": time.monotonic(),
            # the jit/pad class THIS batch dispatches under,
            # snapshotted so a live set_batch_size() swap never
            # races an in-flight batch's pack/redispatch (the max
            # guard covers a shrink landing mid-compose)
            "pad_b": max(self.batch_size, valid),
        }

    def _luts_for(self, version, index):
        with self._lock:
            if self._lut_version != version:
                self._luts = self.daemon._flow_luts(index)
                self._lut_version = version
            return self._luts

    def _pack(self, meta):
        """Host half (overlaps the previous batch's device
        compute): resolve the serving snapshot, translate endpoint
        ids, pad to the jit class, stage the TupleBatch."""
        from cilium_tpu.engine.verdict import TupleBatch
        from cilium_tpu.replay import _ep_index_of

        cols = meta["cols"]
        valid = meta["valid"]
        snap = self.daemon._resolve_serving_tables()
        version, tables, index, host_states = snap
        ep_idx = _ep_index_of(cols, dict(index))
        meta["snap"] = snap
        meta["ep_idx"] = ep_idx
        # endpoints deleted while the flows were QUEUED: the
        # submit-time filter passed them, but this snapshot no
        # longer knows them — _ep_index_of maps them to axis 0,
        # which would evaluate them under (and attribute them to)
        # whatever endpoint sits there.  Mask them: excluded from
        # every fold, reported as dropped_unknown on the reply —
        # the one-shot path's single-snapshot discipline, applied
        # across the queueing gap.
        stale = ~np.isin(
            cols["ep_id"], np.fromiter(index, dtype=np.int64)
        )
        meta["stale"] = stale if stale.any() else None
        if self.fused:
            # the fused router path packs/pads internally (its
            # batch re-split owns the padding); nothing to stage
            return (meta, tables, None)
        b = meta["pad_b"]

        def pad(a, fill=0):
            out = np.full(b, fill, dtype=a.dtype)
            out[:valid] = a
            return out

        batch = TupleBatch.from_numpy(
            ep_index=pad(ep_idx),
            identity=pad(cols["identity"]),
            dport=pad(cols["dport"].astype(np.int32)),
            proto=pad(cols["proto"].astype(np.int32)),
            direction=pad(cols["direction"].astype(np.int32)),
            is_fragment=pad(
                cols["is_fragment"].astype(bool), fill=False
            ),
        )
        return (meta, tables, batch)

    def _dispatch(self, meta, tables, batch):
        """Device half: the daemon's guarded dispatch — breaker +
        retry + watchdog, the memo plane, and the mesh router when
        one is attached (non-blocking enqueue on the single-chip
        path; the drain reads the columns one batch behind).  In
        fused mode the batch goes through the router's FULL fused
        pipeline instead (dispatch_flows: prefilter + LB/DNAT + CT +
        ipcache + lattice over the partitioned N+1 tables)."""
        cols = meta["cols"]
        ep_idx = meta["ep_idx"]
        host_states = meta["snap"][3]
        valid = meta["valid"]
        if self.fused:
            router = self.daemon.mesh_router
            if router is None or router.dp_store is None:
                raise RuntimeError(
                    "fused serving requires an attached mesh "
                    "router with a published datapath epoch "
                    "(attach_mesh_router + attach_datapath)"
                )
            res = router.dispatch_flows(
                ep_index=ep_idx,
                saddr=cols["saddr"],
                daddr=cols["daddr"],
                sport=cols["sport"].astype(np.int32),
                dport=cols["dport"].astype(np.int32),
                proto=cols["proto"].astype(np.int32),
                direction=cols["direction"].astype(np.int32),
                is_fragment=cols["is_fragment"].astype(bool),
            )
            meta["degraded"] = res.degraded
            meta["fused_result"] = res
            return (
                res.verdicts.allowed,
                res.verdicts.match_kind,
                res.verdicts.proxy_port,
                None,
                None,
            )

        def host_args():
            return (
                host_states,
                ep_idx,
                cols["identity"],
                cols["dport"],
                cols["proto"],
                cols["direction"],
                cols["is_fragment"].astype(bool),
            )

        def host_cols():
            return (
                ep_idx,
                cols["identity"],
                cols["dport"],
                cols["proto"],
                cols["direction"],
                cols["is_fragment"].astype(bool),
            )

        out, degraded = self.daemon._dispatch_or_degrade(
            tables, batch, host_args, meta["pad_b"],
            host_cols=host_cols,
        )
        meta["degraded"] = degraded
        # the (tables, batch) pair rides the meta so a drain-time
        # memo overflow refusal can re-dispatch THIS batch uncached
        meta["tables"] = tables
        meta["batch"] = batch
        # a shadow-sampled batch carries its ticket + lazy shadow
        # columns to the drain (cilium_tpu.shadow): folded or
        # refused exactly once in _complete
        meta["shadow_out"] = (
            out
            if getattr(out, "shadow_ticket", None) is not None
            else None
        )
        return (
            out.allowed,
            out.match_kind,
            out.proxy_port,
            getattr(out, "cache_hit", None),
            getattr(out, "cache_stats", None),
        )

    def _shed_span(
        self, sub, s, e, gate_counted: bool = False
    ) -> None:
        """Dispatch-time shed of one span (gate refusal / no-drain
        stop): exactly-once Overload accounting + reply completion
        bookkeeping."""
        self._shed_flows(
            sub.rec, sub.tenant, s, e, gate_counted=gate_counted
        )
        with self._lock:
            t = self._tenants.get(sub.tenant)
            if t is not None:
                t.shed += e - s
                t.dispatched -= e - s  # never reached the device
        sub.result.shed_mask[s:e] = True
        self._span_accounted(sub, e - s)

    def _span_accounted(self, sub, n) -> None:
        sub.served += n
        if sub.served >= sub.n:
            r = sub.result
            now = time.monotonic()
            r.latency_s = now - sub.t_enqueue
            metrics.serve_latency_seconds.observe(r.latency_s)
            with self._lock:
                self._latency_window.append(r.latency_s)
            self._completions += 1
            if self._completions % 32 == 0:
                metrics.serving_p99_ms.set(
                    value=self._window_p99_ms()
                )
            # SLO-class compliance ledger: a submission HITS its
            # deadline only when the reply landed in time with
            # nothing shed — shed flows failed their service even
            # though the reply completed early
            perf_plane = getattr(self.daemon, "perf", None)
            if perf_plane is not None:
                cls = self._tenant_slo.get(sub.tenant)
                bundle = (
                    self._slo_classes.get(cls) if cls else None
                ) or {}
                perf_plane.note_deadline(
                    sub.tenant,
                    cls,
                    hit=(
                        now <= sub.deadline
                        and not bool(r.shed_mask.any())
                        and r.error is None
                    ),
                    objective=float(
                        bundle.get("objective", 0.99)
                    ),
                )
            r._event.set()

    def _complete(self, meta, result, exc) -> None:
        """Drain one coalesced batch: failover on a drain-time
        device death, then the SAME per-batch fold the one-shot path
        runs (monitor events, flow records, metrics), then demux to
        the submissions in stream order."""
        from types import SimpleNamespace

        from cilium_tpu.flow import (
            allow_sample_for_level,
            capture_batch,
        )
        from cilium_tpu.monitor import verdicts_to_events

        cols = meta["cols"]
        spans = meta["spans"]
        valid = meta["valid"]
        ep_idx = meta.get("ep_idx")
        degraded = bool(meta.get("degraded"))
        shadow_out = meta.get("shadow_out")
        shadow_refuse = False
        t_fold0 = time.monotonic()  # the perf plane's fold phase
        try:
            if exc is not None and self.fused:
                # fused mode has no bit-identical host fold (the
                # lattice fold computes a DIFFERENT function than
                # the full pipeline) — error the replies instead of
                # silently serving lattice verdicts as fused ones
                for sub, _s, _e in spans:
                    if not sub.result.done:
                        sub.result.error = exc
                        sub.result._event.set()
                return
            if exc is not None:
                # pack/enqueue/drain failure: the in-flight batch
                # serves from the bit-identical host fold under the
                # breaker, same as the one-shot drain path.  Its
                # shadow columns (if sampled) came from the dead
                # dispatch — refuse the sample cleanly.
                shadow_refuse = True
                from cilium_tpu.engine.hostpath import (
                    lattice_fold_host,
                )
                from cilium_tpu.replay import _ep_index_of

                if self.daemon.verdict_cache is not None:
                    self.daemon.verdict_cache.flush(
                        reason="drain-failure"
                    )
                self.daemon.dispatch_breaker.record_failure(
                    str(exc)
                )
                log.warning(
                    "serve drain failed; serving in-flight batch "
                    "from host path",
                    extra={"fields": {"error": str(exc)}},
                )
                snap = meta.get("snap")
                if snap is None:
                    snap = self.daemon._resolve_serving_tables()
                    meta["snap"] = snap
                host_states = snap[3]
                if ep_idx is None:
                    ep_idx = _ep_index_of(cols, dict(snap[2]))
                    meta["ep_idx"] = ep_idx
                    stale_now = ~np.isin(
                        cols["ep_id"],
                        np.fromiter(snap[2], dtype=np.int64),
                    )
                    meta["stale"] = (
                        stale_now if stale_now.any() else None
                    )
                with tracing.tracer.span(
                    "engine.hostpath", site="engine.hostpath",
                    attrs={"failover": True, "drain": True},
                ):
                    host_out = lattice_fold_host(
                        host_states, ep_idx, cols["identity"],
                        cols["dport"], cols["proto"],
                        cols["direction"],
                        is_fragment=cols["is_fragment"].astype(bool),
                    )
                degraded = True
                self.daemon.degraded_batches += 1
                metrics.degraded_batches_total.inc()
                v = SimpleNamespace(
                    allowed=np.asarray(host_out.allowed)[:valid],
                    match_kind=np.asarray(
                        host_out.match_kind
                    )[:valid],
                    proxy_port=np.asarray(
                        host_out.proxy_port
                    )[:valid],
                    cache_hit=np.zeros(valid, bool),
                )
            else:
                (allowed, match_kind, proxy_port, cache_hit,
                 cache_stats) = result
                v = SimpleNamespace(
                    allowed=np.asarray(allowed)[:valid],
                    match_kind=np.asarray(match_kind)[:valid],
                    proxy_port=np.asarray(proxy_port)[:valid],
                    cache_hit=(
                        np.zeros(valid, bool)
                        if cache_hit is None
                        else np.asarray(cache_hit)[:valid]
                    ),
                )
                # deferred memo fold — THE shared drain seam
                # (Daemon._fold_memo_drain), applied to the
                # COALESCED multi-tenant batch: overflow refusal
                # re-dispatches uncached, hit/miss accounting lands
                # once corrected to the valid prefix
                if cache_stats is not None:

                    def _redispatch():
                        def _ha():
                            return (
                                meta["snap"][3],
                                ep_idx,
                                cols["identity"],
                                cols["dport"],
                                cols["proto"],
                                cols["direction"],
                                cols["is_fragment"].astype(bool),
                            )

                        return self.daemon._dispatch_or_degrade(
                            meta["tables"], meta["batch"], _ha,
                            meta["pad_b"], use_memo=False,
                            shadow_sample=False,
                        )

                    (
                        v, deg2, overflowed,
                    ) = self.daemon._fold_memo_drain(
                        cache_stats, v, valid,
                        int(np.asarray(allowed).shape[0]),
                        _redispatch,
                    )
                    degraded = degraded or deg2
                    shadow_refuse = shadow_refuse or overflowed
            # -- the shared fold (monitor + flow + metrics) -----------
            snap = meta["snap"]
            version, _, index, _ = snap
            local_ident_lut, rev_lut = self._luts_for(
                version, index
            )
            # flows whose endpoint vanished while queued are masked
            # out of every fold (their axis-0 evaluation is
            # meaningless) and reported as dropped_unknown below
            stale = meta.get("stale")
            k = slice(None) if stale is None else ~stale
            opts = option.Config.opts
            verdicts_to_events(
                self.daemon.monitor,
                SimpleNamespace(
                    allowed=v.allowed[k],
                    match_kind=v.match_kind[k],
                    proxy_port=v.proxy_port[k],
                ),
                ep_ids=rev_lut[ep_idx[k]],
                identities=cols["identity"][k],
                dports=cols["dport"][k],
                protos=cols["proto"][k],
                directions=cols["direction"][k],
                verdict_eps=(
                    self.daemon.verdict_notification_endpoints()
                ),
                emit_drops=opts.is_enabled(
                    option.DROP_NOTIFICATION
                ),
                emit_trace=(
                    opts.is_enabled(option.TRACE_NOTIFICATION)
                    and opts.level(option.MONITOR_AGGREGATION)
                    == option.MONITOR_AGG_NONE
                ),
            )
            # full-length identity orientation computed once: the
            # shadow fold consumes the whole valid batch, the
            # capture below slices the stale-masked view `k` off it
            dirs_full = cols["direction"]
            peer_full = cols["identity"].astype(np.int64)
            local_full = local_ident_lut[ep_idx]
            src_full = np.where(dirs_full == 0, peer_full, local_full)
            dst_full = np.where(dirs_full == 0, local_full, peer_full)
            dirs = dirs_full[k]
            # shadow verdict-diff fold (cilium_tpu.shadow), exactly
            # once per sampled coalesced batch; a batch holding
            # vanished-endpoint rows refuses (their axis-0 verdicts
            # would be a meaningless diff)
            diff_col = None
            if shadow_out is not None:
                diff_full = self.daemon._fold_shadow_drain(
                    shadow_out, v, valid,
                    ep_ids=rev_lut[ep_idx],
                    src_identities=src_full,
                    dst_identities=dst_full,
                    dports=cols["dport"],
                    protos=cols["proto"],
                    directions=dirs_full,
                    tenant=meta["tenants"],
                    trace_id="",
                    refuse=shadow_refuse or stale is not None,
                )
                if diff_full is not None:
                    diff_col = diff_full[k]
            capture_batch(
                self.daemon.flow_store,
                ep_ids=rev_lut[ep_idx[k]],
                src_identities=src_full[k],
                dst_identities=dst_full[k],
                dports=cols["dport"][k],
                protos=cols["proto"][k],
                directions=dirs,
                allowed=v.allowed[k],
                match_kind=v.match_kind[k],
                proxy_port=v.proxy_port[k],
                cache_hit=v.cache_hit[k],
                diff_status=diff_col,
                allow_sample=allow_sample_for_level(
                    opts.level(option.MONITOR_AGGREGATION)
                ),
                metrics_registry=metrics,
                tenant=meta["tenants"][k],
            )
            # -- bookkeeping ------------------------------------------
            now = time.monotonic()
            wall = now - meta["t_plan"]
            self._batch_wall_ewma = (
                wall
                if self._batch_wall_ewma is None
                else 0.8 * self._batch_wall_ewma + 0.2 * wall
            )
            self.batches += 1
            self.flows_served += valid
            fill = 100.0 * valid / meta["pad_b"]
            self.fill_sum += fill
            if degraded:
                self.degraded_batches += 1
            metrics.serve_batches_total.inc()
            metrics.serve_batch_fill_pct.set(value=fill)
            self.batch_mix.append(meta["mix"])
            # feed the perf plane: the dispatcher's per-batch phase
            # stamps (meta["perf"], written by the overlap
            # bookkeeping) + this fold's own wall — one call per
            # batch, windows + bounded-cadence gauge export inside
            perf_plane = getattr(self.daemon, "perf", None)
            if perf_plane is not None:
                pp = meta.get("perf") or {}
                perf_plane.observe_batch(
                    pack_s=pp.get("pack_s", 0.0),
                    dispatch_s=pp.get("enqueue_s", 0.0),
                    drain_s=pp.get("drain_s", 0.0),
                    fold_s=now - t_fold0,
                    wall_s=wall,
                    fill_pct=fill,
                    valid=valid,
                )
                # the online re-tune controller rides the serve
                # loop at a bounded cadence (no-op unless the
                # daemon enabled it)
                if self.batches % 64 == 0:
                    self.daemon.maybe_online_retune()
            # -- demux to per-submission replies ----------------------
            # per-tenant verdict-cache hits: the cross-tenant memo
            # plane's observability — batch_mix rows carry each
            # tenant's hit count beside its DRR share (one lock
            # acquisition for the whole batch, not one per span)
            off = 0
            mix = meta["mix"]
            tenant_stats: Dict[str, list] = {}
            for sub, s, e in spans:
                seg_hits = int(
                    v.cache_hit[off : off + (e - s)].sum()
                )
                row = mix.get(sub.tenant)
                if row is not None:
                    row["cache_hits"] = (
                        row.get("cache_hits", 0) + seg_hits
                    )
                agg = tenant_stats.setdefault(sub.tenant, [0, 0])
                agg[0] += seg_hits
                agg[1] += e - s
                off += e - s
            with self._lock:
                for name, (hits, served) in tenant_stats.items():
                    t = self._tenants.get(name)
                    if t is not None:
                        t.cache_hits += hits
                        t.served += served
            off = 0
            for sub, s, e in spans:
                n = e - s
                r = sub.result
                seg = slice(off, off + n)
                if stale is None or not stale[seg].any():
                    r.allowed[s:e] = v.allowed[seg]
                    r.match_kind[s:e] = v.match_kind[seg]
                    r.proxy_port[s:e] = v.proxy_port[seg]
                    r.cache_hit[s:e] = v.cache_hit[seg]
                else:
                    live = ~stale[seg]
                    r.allowed[s:e] = np.where(
                        live, v.allowed[seg], False
                    )
                    r.match_kind[s:e] = np.where(
                        live, v.match_kind[seg], 0
                    )
                    r.proxy_port[s:e] = np.where(
                        live, v.proxy_port[seg], 0
                    )
                    r.cache_hit[s:e] = np.where(
                        live, v.cache_hit[seg], False
                    )
                    r.dropped_unknown += int(stale[seg].sum())
                r.batches += 1
                if degraded:
                    r.degraded_batches += 1
                delay = meta["t_plan"] - sub.t_enqueue
                r.queue_delay_s = max(r.queue_delay_s, delay)
                metrics.serve_queue_delay_seconds.observe(delay)
                if perf_plane is not None:
                    perf_plane.observe_queue_delay(delay)
                off += n
                self._span_accounted(sub, n)
        except Exception as exc2:
            # a fold/demux failure must not leave submitters
            # blocked on replies that will never fill; an
            # unresolved shadow ticket refuses (idempotent — a
            # ticket that already folded stays folded)
            if shadow_out is not None:
                self.daemon.shadow.refuse(shadow_out.shadow_ticket)
            for sub, _s, _e in spans:
                if not sub.result.done:
                    sub.result.error = exc2
                    sub.result._event.set()
            raise
        finally:
            self.daemon.admission.release(valid)

    # -- introspection --------------------------------------------------------

    def reset_window(self) -> None:
        """Zero the rolling serving_p99_ms latency window (the
        /debug/profile?reset=1 seam applied to the serving plane):
        bench segments and before/after experiments must not bleed
        one load shape's tail into the next segment's p99.  The
        daemon's perf-plane windows (phase/fill/queue-delay/stall)
        reset alongside — one seam, every window."""
        with self._lock:
            self._latency_window.clear()
        metrics.serving_p99_ms.set(value=0.0)
        perf_plane = getattr(self.daemon, "perf", None)
        if perf_plane is not None:
            perf_plane.reset()

    def snapshot(self) -> Dict:
        with self._lock:
            tenants = {
                t.name: {
                    "weight": t.weight,
                    "slo_class": t.slo_class,
                    "backlog": t.backlog,
                    "admitted": t.admitted,
                    "dispatched": t.dispatched,
                    "shed": t.shed,
                    "cache_hits": t.cache_hits,
                    "cache_hit_rate": (
                        t.cache_hits / t.served if t.served else 0.0
                    ),
                }
                for t in self._tenants.values()
            }
        return {
            "batch_size": self.batch_size,
            "slo_classes": dict(self._slo_classes),
            "slo_ms": self.slo_s * 1000.0,
            "batches": self.batches,
            "flows_served": self.flows_served,
            "early_dispatches": self.early_dispatches,
            "degraded_batches": self.degraded_batches,
            "avg_batch_fill_pct": (
                self.fill_sum / self.batches if self.batches else 0.0
            ),
            "batch_wall_ewma_ms": (
                (self._batch_wall_ewma or 0.0) * 1000.0
            ),
            "serving_p99_ms": self._window_p99_ms(),
            "tenants": tenants,
        }

    def _window_p99_ms(self) -> float:
        with self._lock:
            lats = list(self._latency_window)
        return quantile_ms(lats, 0.99)


# ---------------------------------------------------------------------------
# sustained-QPS serving bench (open-loop arrivals)
# ---------------------------------------------------------------------------


def run_serve_bench(
    daemon,
    *,
    seconds: float = 5.0,
    qps: float = 200.0,
    flows_per_submit: int = 64,
    tenants: Optional[Dict[str, float]] = None,
    batch_size: int = 1 << 12,
    slo_ms: float = 50.0,
    make_records,
    seed: int = 7,
    poisson: bool = True,
) -> Dict:
    """Open-loop arrival driver over a ServingPlane: `tenants` maps
    tenant name → its share of the offered `qps` (submissions per
    second, each of `flows_per_submit` flows).  Arrivals are Poisson
    (exponential gaps) or uniform; the clock never waits for replies
    — open loop, so queue delay is real.  `make_records(rng, n)`
    returns a decoded record SoA of n flows.

    Returns the serving metrics the bench emits:
    sustained_verdicts_per_sec, serving_p99_ms, queue-delay and
    batch-fill aggregates, and per-tenant admitted/shed counts."""
    rng = np.random.default_rng(seed)
    plane = daemon.serving_plane(
        batch_size=batch_size, slo_ms=slo_ms
    )
    # segment isolation: a previous bench segment's latency tail
    # must not bleed into THIS run's serving_p99_ms
    plane.reset_window()
    shares = tenants or {"default": 1.0}
    total_share = sum(shares.values())
    results: List[ServeResult] = []
    res_lock = threading.Lock()
    stop_at = time.monotonic() + seconds

    def arrivals(name, share):
        trng = np.random.default_rng(tenant_seed(seed, name))
        rate = qps * share / total_share
        if rate <= 0:
            return
        t_next = time.monotonic()
        while time.monotonic() < stop_at:
            rec = make_records(trng, flows_per_submit)
            r = plane.submit(rec=rec, tenant=name)
            with res_lock:
                results.append(r)
            gap = (
                trng.exponential(1.0 / rate)
                if poisson
                else 1.0 / rate
            )
            t_next += gap
            sleep = t_next - time.monotonic()
            if sleep > 0:
                time.sleep(sleep)
            else:
                t_next = time.monotonic()  # open loop: never bunch

    threads = [
        threading.Thread(
            target=arrivals, args=(name, share), daemon=True
        )
        for name, share in shares.items()
    ]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for r in results:
        if not r.done:
            try:
                r.wait(timeout=60.0)
            except Exception:
                pass
    wall = time.monotonic() - t0
    # SERVED submissions only: a whole-submission shed completes at
    # latency ~0, which would bias the saturation p99 low exactly
    # when the metric matters
    lat = [
        r.latency_s
        for r in results
        if r.latency_s is not None and not r.shed
    ]
    served = sum(
        int((~r.shed_mask).sum()) for r in results if not r.shed
    )
    shed = sum(
        (r.n if r.shed else int(r.shed_mask.sum()))
        for r in results
    )

    def q(p):
        return quantile_ms(lat, p)

    snap = plane.snapshot()
    metrics.serving_p99_ms.set(value=q(0.99))
    return {
        "submissions": len(results),
        "offered_qps": qps,
        "wall_s": wall,
        "sustained_verdicts_per_sec": served / wall if wall else 0.0,
        "serving_p50_ms": q(0.50),
        "serving_p99_ms": q(0.99),
        "served_flows": served,
        "shed_flows": shed,
        "avg_batch_fill_pct": snap["avg_batch_fill_pct"],
        "batches": snap["batches"],
        "early_dispatches": snap["early_dispatches"],
        "degraded_batches": snap["degraded_batches"],
        "tenants": snap["tenants"],
    }


# ---------------------------------------------------------------------------
# self-contained demo world (serve-bench / serveprof / tenant storm)
# ---------------------------------------------------------------------------


def build_demo_daemon():
    """Two-endpoint world with an L4 + L3 policy — the canonical
    replay world, built self-contained so `cilium-tpu serve-bench`
    and tools/serveprof.py need no running agent.  Returns
    (daemon, client endpoint)."""
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.labels import Label, LabelArray, Labels
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        Rule,
    )

    def k8s_labels(**kv):
        return Labels(
            {k: Label(k, v, "k8s") for k, v in kv.items()}
        )

    def es(**kv):
        return EndpointSelector(
            match_labels={f"k8s.{k}": v for k, v in kv.items()}
        )

    d = Daemon()
    d.create_endpoint(
        10, k8s_labels(app="server"), ipv4="10.0.0.10",
        name="server-0",
    )
    client = d.create_endpoint(
        11, k8s_labels(app="client"), ipv4="10.0.0.11",
        name="client-0",
    )
    d.policy_add(
        [
            Rule(
                endpoint_selector=es(app="server"),
                ingress=[
                    IngressRule(
                        from_endpoints=[es(app="client")],
                        to_ports=[
                            PortRule(
                                ports=[
                                    PortProtocol(
                                        port="80", protocol="TCP"
                                    )
                                ]
                            )
                        ],
                    )
                ],
                labels=LabelArray.parse("serve-bench-rule"),
            )
        ]
    )
    d.policy_trigger.close(wait=True)
    return d, client


def demo_record_maker(client_identity: int):
    """`make_records(rng, n)` for run_serve_bench over the demo
    world: a mixed allowed/denied stream against endpoint 10."""

    def make_records(rng, n):
        return {
            "ep_id": np.full(n, 10, np.uint32),
            "identity": rng.choice(
                [client_identity, 999999], size=n
            ).astype(np.uint32),
            "saddr": np.zeros(n, np.uint32),
            "daddr": np.zeros(n, np.uint32),
            "sport": np.full(n, 40000, np.uint16),
            "dport": rng.choice([80, 443], size=n).astype(
                np.uint16
            ),
            "proto": np.full(n, 6, np.uint8),
            "direction": np.zeros(n, np.uint8),
            "is_fragment": np.zeros(n, np.uint8),
        }

    return make_records
