"""Kubernetes intent translation.

Re-design of /root/reference/pkg/k8s: NetworkPolicy (networking/v1)
and CiliumNetworkPolicy objects — as plain JSON dicts, since the
framework has no kube client dependency — translate into api.Rule
lists; Service/Endpoints rewrite ToServices egress rules into
ToCIDRSet (RuleTranslator).
"""

from cilium_tpu.k8s.network_policy import (
    parse_cilium_network_policy,
    parse_network_policy,
)
from cilium_tpu.k8s.rule_translate import RuleTranslator

__all__ = [
    "parse_network_policy",
    "parse_cilium_network_policy",
    "RuleTranslator",
]
