"""ToServices → ToCIDRSet rewriting.

Behavioral port of /root/reference/pkg/k8s/rule_translate.go
(RuleTranslator rule_translate.go:44, TranslateEgress :56): when a
k8s Service's Endpoints change, egress rules naming that service get
their generated ToCIDRSet repopulated with the endpoints' backend IPs
(marked Generated so depopulation removes only what translation
added).  Repository.translate_rules drives this over all rules
(pkg/policy/repository TranslateRules).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set

from cilium_tpu.policy.api.rule import CIDRRule, EgressRule, Rule, Service


@dataclass
class K8sServiceInfo:
    """loadbalancer.K8sServiceNamespace + its endpoints."""

    name: str
    namespace: str
    backend_ips: Set[str] = field(default_factory=set)
    labels: Dict[str, str] = field(default_factory=dict)


class RuleTranslator:
    """policy.Translator implementation (rule_translate.go:41)."""

    def __init__(self, service: K8sServiceInfo, revert: bool = False):
        self.service = service
        self.revert = revert

    # Translator protocol: Repository.translate_rules calls this per
    # rule (repository.go TranslateRules).
    def translate(self, rule: Rule) -> None:
        for egress in rule.egress:
            self.translate_egress(egress)

    def translate_egress(self, egress: EgressRule) -> None:
        self._depopulate(egress)
        if not self.revert:
            self._populate(egress)

    def _service_matches(self, service: Service) -> bool:
        """rule_translate.go:96 serviceMatches."""
        if service.k8s_service_selector is not None:
            # {"selector": {matchLabels...}, "namespace": str}
            spec = service.k8s_service_selector
            from cilium_tpu.labels import Label, LabelArray
            from cilium_tpu.policy.api.selector import EndpointSelector

            selector = EndpointSelector.from_dict(
                spec.get("selector") or {}
            )
            arr = LabelArray(
                [
                    Label(k, v, "k8s")
                    for k, v in sorted(self.service.labels.items())
                ]
            )
            if not selector.matches(arr):
                return False
            return spec.get("namespace", "") in (
                "", self.service.namespace,
            )
        if service.k8s_service is not None:
            return (
                service.k8s_service.service_name == self.service.name
                and service.k8s_service.namespace
                in ("", self.service.namespace)
            )
        return False

    def _populate(self, egress: EgressRule) -> None:
        """generateToCidrFromEndpoint (rule_translate.go:113): one /32
        generated CIDRRule per backend IP, skipping those already
        covered."""
        if not any(self._service_matches(s) for s in egress.to_services):
            return
        import ipaddress

        for ip in sorted(self.service.backend_ips):
            addr = ipaddress.ip_address(ip)
            plen = 32 if addr.version == 4 else 128
            cidr = f"{ip}/{plen}"
            if any(c.cidr == cidr for c in egress.to_cidr_set):
                continue
            egress.to_cidr_set.append(CIDRRule(cidr=cidr, generated=True))

    def _depopulate(self, egress: EgressRule) -> None:
        """deleteToCidrFromEndpoint: remove only Generated entries for
        this service's backends."""
        if not any(self._service_matches(s) for s in egress.to_services):
            return
        backends = {
            f"{ip}/32" for ip in self.service.backend_ips
        } | {f"{ip}/128" for ip in self.service.backend_ips}
        egress.to_cidr_set = [
            c
            for c in egress.to_cidr_set
            if not (c.generated and c.cidr in backends)
        ]
