"""k8s watch loop: informer stores + serialized per-resource queues.

The machinery of /root/reference/daemon/k8s_watcher.go:453-671 —
controllers subscribing to NetworkPolicy / CiliumNetworkPolicy /
Service / Endpoints streams, with each resource kind draining its
events IN ORDER through its own serialized queue
(k8sUtils.ResourceEventHandlerFactory's funcSerializer) and an
initial-sync gate (blockWaitGroupToSyncResources) before the daemon
is considered ready.

There is no kube-apiserver in this environment; `FakeAPIServer` is
the in-proc stand-in implementing the list+watch contract the
reference's informers consume (replay current objects as ADDED, then
stream).  The event handlers are the real daemon paths:

  * (C)NP add/update → parse → Daemon.policy_add with the policy's
    derived labels (replacing the prior revision of the same policy);
    delete → Daemon.policy_delete by labels;
  * Service/Endpoints → ServiceManager upsert (the LB frontend) AND
    live ToServices→ToCIDRSet retranslation via RuleTranslator
    (k8s_watcher.go updateK8sServiceV1 →
    pkg/k8s/rule_translate.go:44), followed by a policy trigger so
    endpoints regenerate against the rewritten rules.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu.k8s.network_policy import (
    get_policy_labels,
    parse_cilium_network_policy,
    parse_network_policy,
)
from cilium_tpu.k8s.rule_translate import K8sServiceInfo, RuleTranslator
from cilium_tpu.lb.service import L3n4Addr


@dataclass(frozen=True)
class K8sEvent:
    kind: str  # resource kind, e.g. "Service"
    action: str  # added | modified | deleted
    obj: dict
    old: Optional[dict] = None


class FakeAPIServer:
    """List+watch over {kind → (namespace, name) → object}."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._objects: Dict[str, Dict[Tuple[str, str], dict]] = {}
        self._watchers: List[Tuple[str, Callable[[K8sEvent], None]]] = []

    @staticmethod
    def _key(obj: dict) -> Tuple[str, str]:
        meta = obj.get("metadata", {})
        return meta.get("namespace", "default"), meta.get("name", "")

    def upsert(self, kind: str, obj: dict) -> None:
        with self._lock:
            store = self._objects.setdefault(kind, {})
            key = self._key(obj)
            old = store.get(key)
            store[key] = obj
            action = "modified" if old is not None else "added"
            watchers = [w for k, w in self._watchers if k == kind]
        for w in watchers:
            w(K8sEvent(kind, action, obj, old))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            store = self._objects.setdefault(kind, {})
            obj = store.pop((namespace, name), None)
            watchers = [w for k, w in self._watchers if k == kind]
        if obj is not None:
            for w in watchers:
                w(K8sEvent(kind, "deleted", obj))

    def watch(
        self, kind: str, handler: Callable[[K8sEvent], None]
    ) -> None:
        """Replay current objects as `added`, then stream (the
        informer ListAndWatch contract)."""
        with self._lock:
            current = list(self._objects.get(kind, {}).values())
            self._watchers.append((kind, handler))
        for obj in current:
            handler(K8sEvent(kind, "added", obj))


class _SerializedQueue:
    """Per-resource ordered event execution (the reference's
    funcSerializer: handlers for one resource kind never run
    concurrently or out of order)."""

    def __init__(self, name: str) -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name=f"k8s-{name}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                pass  # the reference logs and keeps the loop alive

    def enqueue(self, fn: Callable[[], None]) -> None:
        self._q.put(fn)

    def drain(self) -> None:
        """Block until everything enqueued so far has executed."""
        done = threading.Event()
        self._q.put(done.set)
        done.wait(timeout=10.0)

    def close(self) -> None:
        self._q.put(None)


class K8sWatcher:
    """EnableK8sWatcher (k8s_watcher.go:453): wires the resource
    streams into the daemon with per-kind serialized queues."""

    KINDS = (
        "NetworkPolicy",
        "CiliumNetworkPolicy",
        "Service",
        "Endpoints",
    )

    def __init__(self, daemon, apiserver: FakeAPIServer, services=None):
        self.daemon = daemon
        self.apiserver = apiserver
        self.services = services  # lb.ServiceManager (optional)
        self._svc_info: Dict[Tuple[str, str], K8sServiceInfo] = {}
        self._svc_frontends: Dict[Tuple[str, str], L3n4Addr] = {}
        self._queues = {k: _SerializedQueue(k) for k in self.KINDS}
        self._synced = {k: threading.Event() for k in self.KINDS}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        handlers = {
            "NetworkPolicy": self._on_np,
            "CiliumNetworkPolicy": self._on_cnp,
            "Service": self._on_service,
            "Endpoints": self._on_endpoints,
        }
        for kind in self.KINDS:
            self.apiserver.watch(
                kind,
                lambda ev, k=kind: self._queues[k].enqueue(
                    lambda: handlers[ev.kind](ev)
                ),
            )
            # blockWaitGroupToSyncResources: the replayed backlog is
            # queued; the sync gate trips once it has drained
            self._queues[kind].enqueue(self._synced[kind].set)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return all(e.wait(timeout) for e in self._synced.values())

    def drain(self) -> None:
        for q in self._queues.values():
            q.drain()

    def close(self) -> None:
        for q in self._queues.values():
            q.close()

    # -- policy resources ----------------------------------------------------

    def _policy_upsert(self, rules, labels) -> None:
        if not rules:
            return
        # replaceWithLabels: a re-add of the same policy replaces its
        # previous revision (daemon PolicyAdd ReplaceWithLabels)
        self.daemon.policy_delete(labels)
        self.daemon.policy_add(rules)

    def _on_np(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        labels = get_policy_labels(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            "NetworkPolicy",
        )
        if ev.action == "deleted":
            self.daemon.policy_delete(labels)
            return
        self._policy_upsert(parse_network_policy(ev.obj), labels)

    def _on_cnp(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        labels = get_policy_labels(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            "CiliumNetworkPolicy",
        )
        if ev.action == "deleted":
            self.daemon.policy_delete(labels)
            return
        self._policy_upsert(parse_cilium_network_policy(ev.obj), labels)

    # -- service resources ---------------------------------------------------

    def _info_for(self, namespace: str, name: str) -> K8sServiceInfo:
        key = (namespace, name)
        if key not in self._svc_info:
            self._svc_info[key] = K8sServiceInfo(
                name=name, namespace=namespace
            )
        return self._svc_info[key]

    def _on_service(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        key = (namespace, name)
        if ev.action == "deleted":
            frontend = self._svc_frontends.pop(key, None)
            if frontend is not None and self.services is not None:
                self.services.delete(frontend)
            info = self._svc_info.pop(key, None)
            if info is not None:
                self._retranslate(info, revert=True)
            return
        spec = ev.obj.get("spec", {})
        info = self._info_for(namespace, name)
        info.labels = dict(spec.get("selector") or {})
        cluster_ip = spec.get("clusterIP")
        ports = spec.get("ports") or []
        if cluster_ip and ports and self.services is not None:
            port = int(ports[0].get("port", 0))
            proto = 6 if ports[0].get("protocol", "TCP") == "TCP" else 17
            frontend = L3n4Addr(cluster_ip, port, proto)
            self._svc_frontends[key] = frontend
            self._sync_lb(key)

    def _on_endpoints(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        info = self._info_for(namespace, name)
        old_ips = set(info.backend_ips)
        if ev.action == "deleted":
            info.backend_ips = set()
        else:
            ips = set()
            for subset in ev.obj.get("subsets") or []:
                for addr in subset.get("addresses") or []:
                    if addr.get("ip"):
                        ips.add(addr["ip"])
            info.backend_ips = ips
        self._sync_lb((namespace, name))
        # live ToServices → ToCIDRSet retranslation + regeneration
        # (k8s_watcher.go updateK8sEndpointV1 → TranslateRules):
        # depopulate against the OLD endpoint set, populate the new —
        # the reference translator carries both (rule_translate.go
        # RuleTranslator{OldEndpoint, NewEndpoint})
        stale = old_ips - info.backend_ips
        if stale:
            self._retranslate(
                K8sServiceInfo(
                    name=name,
                    namespace=namespace,
                    backend_ips=stale,
                    labels=dict(info.labels),
                ),
                revert=True,
            )
        self._retranslate(info, revert=False)

    def _sync_lb(self, key: Tuple[str, str]) -> None:
        if self.services is None:
            return
        frontend = self._svc_frontends.get(key)
        info = self._svc_info.get(key)
        if frontend is None or info is None:
            return
        backends = [
            L3n4Addr(ip, frontend.port, frontend.protocol)
            for ip in sorted(info.backend_ips)
        ]
        self.services.upsert(frontend, backends)

    def _retranslate(self, info: K8sServiceInfo, revert: bool) -> None:
        with self.daemon.lock:
            self.daemon.repo.translate_rules(
                RuleTranslator(info, revert=revert)
            )
        self.daemon.trigger_policy_updates(
            f"service {info.namespace}/{info.name} endpoints", full=True
        )
