"""k8s watch loop: informer stores + serialized per-resource queues.

The machinery of /root/reference/daemon/k8s_watcher.go:453-671 —
controllers subscribing to NetworkPolicy / CiliumNetworkPolicy /
Service / Endpoints streams, with each resource kind draining its
events IN ORDER through its own serialized queue
(k8sUtils.ResourceEventHandlerFactory's funcSerializer) and an
initial-sync gate (blockWaitGroupToSyncResources) before the daemon
is considered ready.

There is no kube-apiserver in this environment; `FakeAPIServer` is
the in-proc stand-in implementing the list+watch contract the
reference's informers consume (replay current objects as ADDED, then
stream).  The event handlers are the real daemon paths:

  * (C)NP add/update → parse → Daemon.policy_add with the policy's
    derived labels (replacing the prior revision of the same policy);
    delete → Daemon.policy_delete by labels;
  * Service/Endpoints → ServiceManager upsert (the LB frontend) AND
    live ToServices→ToCIDRSet retranslation via RuleTranslator
    (k8s_watcher.go updateK8sServiceV1 →
    pkg/k8s/rule_translate.go:44), followed by a policy trigger so
    endpoints regenerate against the rewritten rules.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu.k8s.network_policy import (
    get_policy_labels,
    parse_cilium_network_policy,
    parse_network_policy,
)
from cilium_tpu.k8s.rule_translate import K8sServiceInfo, RuleTranslator
from cilium_tpu.lb.service import L3n4Addr


@dataclass(frozen=True)
class K8sEvent:
    kind: str  # resource kind, e.g. "Service"
    action: str  # added | modified | deleted
    obj: dict
    old: Optional[dict] = None


class FakeAPIServer:
    """List+watch over {kind → (namespace, name) → object}."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._objects: Dict[str, Dict[Tuple[str, str], dict]] = {}
        self._watchers: List[Tuple[str, Callable[[K8sEvent], None]]] = []

    @staticmethod
    def _key(obj: dict) -> Tuple[str, str]:
        meta = obj.get("metadata", {})
        return meta.get("namespace", "default"), meta.get("name", "")

    def upsert(self, kind: str, obj: dict) -> None:
        with self._lock:
            store = self._objects.setdefault(kind, {})
            key = self._key(obj)
            old = store.get(key)
            store[key] = obj
            action = "modified" if old is not None else "added"
            watchers = [w for k, w in self._watchers if k == kind]
        for w in watchers:
            w(K8sEvent(kind, action, obj, old))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            store = self._objects.setdefault(kind, {})
            obj = store.pop((namespace, name), None)
            watchers = [w for k, w in self._watchers if k == kind]
        if obj is not None:
            for w in watchers:
                w(K8sEvent(kind, "deleted", obj))

    def watch(
        self, kind: str, handler: Callable[[K8sEvent], None]
    ) -> None:
        """Replay current objects as `added`, then stream (the
        informer ListAndWatch contract)."""
        with self._lock:
            current = list(self._objects.get(kind, {}).values())
            self._watchers.append((kind, handler))
        for obj in current:
            handler(K8sEvent(kind, "added", obj))


class _SerializedQueue:
    """Per-resource ordered event execution (the reference's
    funcSerializer: handlers for one resource kind never run
    concurrently or out of order)."""

    def __init__(self, name: str) -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name=f"k8s-{name}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                pass  # the reference logs and keeps the loop alive

    def enqueue(self, fn: Callable[[], None]) -> None:
        self._q.put(fn)

    def drain(self) -> None:
        """Block until everything enqueued so far has executed."""
        done = threading.Event()
        self._q.put(done.set)
        done.wait(timeout=10.0)

    def close(self) -> None:
        self._q.put(None)


class K8sWatcher:
    """EnableK8sWatcher (k8s_watcher.go:453): wires the resource
    streams into the daemon with per-kind serialized queues.

    Resource kinds beyond policy/services (k8s_watcher.go:72-79):

      * Pod — pod label changes reach the endpoint as an identity
        re-allocation (EndpointUpdateLabels), including the
        namespace's labels under the io.cilium.k8s.namespace.labels
        key space;
      * Namespace — namespace label changes re-derive EVERY tracked
        pod's endpoint labels in that namespace;
      * Node — remote nodes' pod CIDRs + internal IPs feed the
        tunnel/overlay map (the k8s twin of the kvstore NodeWatcher);
      * Ingress — single-service ingresses become an external LB
        frontend on the host address at the backend service's port
        (addIngressV1beta1's loadbalancer sync)."""

    KINDS = (
        "NetworkPolicy",
        "CiliumNetworkPolicy",
        "Service",
        "Endpoints",
        "Pod",
        "Namespace",
        "Node",
        "Ingress",
    )

    def __init__(self, daemon, apiserver: FakeAPIServer, services=None,
                 host_ip: str = "192.168.0.1"):
        self.daemon = daemon
        self.apiserver = apiserver
        self.services = services  # lb.ServiceManager (optional)
        self.host_ip = host_ip  # ingress frontend host (HostV4Addr)
        self._svc_info: Dict[Tuple[str, str], K8sServiceInfo] = {}
        self._svc_frontends: Dict[Tuple[str, str], L3n4Addr] = {}
        # pod bookkeeping for namespace-label rederivation
        self._ns_labels: Dict[str, Dict[str, str]] = {}
        self._pod_eps: Dict[Tuple[str, str], int] = {}
        self._pod_labels: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._ingress_frontends: Dict[Tuple[str, str], L3n4Addr] = {}
        # ingress key → (namespace, serviceName, raw servicePort)
        # — the port may be a NAME (k8s IntOrString), resolved at
        # sync time against the service's port list
        self._ingress_spec: Dict[Tuple[str, str], tuple] = {}
        # (namespace, service) → {port name → port number}
        self._svc_ports: Dict[Tuple[str, str], Dict[str, int]] = {}
        self._queues = {k: _SerializedQueue(k) for k in self.KINDS}
        self._synced = {k: threading.Event() for k in self.KINDS}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        handlers = {
            "NetworkPolicy": self._on_np,
            "CiliumNetworkPolicy": self._on_cnp,
            "Service": self._on_service,
            "Endpoints": self._on_endpoints,
            "Pod": self._on_pod,
            "Namespace": self._on_namespace,
            "Node": self._on_node,
            "Ingress": self._on_ingress,
        }
        for kind in self.KINDS:
            self.apiserver.watch(
                kind,
                lambda ev, k=kind: self._queues[k].enqueue(
                    lambda: handlers[ev.kind](ev)
                ),
            )
            # blockWaitGroupToSyncResources: the replayed backlog is
            # queued; the sync gate trips once it has drained
            self._queues[kind].enqueue(self._synced[kind].set)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return all(e.wait(timeout) for e in self._synced.values())

    def drain(self) -> None:
        for q in self._queues.values():
            q.drain()

    def close(self) -> None:
        for q in self._queues.values():
            q.close()

    # -- policy resources ----------------------------------------------------

    def _policy_upsert(self, rules, labels) -> None:
        if not rules:
            return
        # replaceWithLabels: a re-add of the same policy replaces its
        # previous revision (daemon PolicyAdd ReplaceWithLabels)
        self.daemon.policy_delete(labels)
        self.daemon.policy_add(rules)

    def _on_np(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        labels = get_policy_labels(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            "NetworkPolicy",
        )
        if ev.action == "deleted":
            self.daemon.policy_delete(labels)
            return
        self._policy_upsert(parse_network_policy(ev.obj), labels)

    def _on_cnp(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        labels = get_policy_labels(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            "CiliumNetworkPolicy",
        )
        if ev.action == "deleted":
            self.daemon.policy_delete(labels)
            return
        self._policy_upsert(parse_cilium_network_policy(ev.obj), labels)

    # -- service resources ---------------------------------------------------

    def _info_for(self, namespace: str, name: str) -> K8sServiceInfo:
        key = (namespace, name)
        if key not in self._svc_info:
            self._svc_info[key] = K8sServiceInfo(
                name=name, namespace=namespace
            )
        return self._svc_info[key]

    def _on_service(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        key = (namespace, name)
        if ev.action == "deleted":
            frontend = self._svc_frontends.pop(key, None)
            if frontend is not None and self.services is not None:
                self.services.delete(frontend)
            info = self._svc_info.pop(key, None)
            if self.services is not None:
                # dependent ingress frontends drop to empty backends.
                # _svc_ports must still hold this service's entry:
                # a NAMED servicePort resolves through it, and popping
                # first would resolve port 0 and leave the stale
                # external frontend (old port, old backends) installed
                self._sync_ingresses_for(namespace, name)
            self._svc_ports.pop(key, None)
            if info is not None:
                self._retranslate(info, revert=True)
            return
        spec = ev.obj.get("spec", {})
        info = self._info_for(namespace, name)
        info.labels = dict(spec.get("selector") or {})
        cluster_ip = spec.get("clusterIP")
        ports = spec.get("ports") or []
        self._svc_ports[key] = {
            str(p.get("name", "")): int(p.get("port", 0))
            for p in ports
            if p.get("name")
        }
        if self.services is not None:
            self._sync_ingresses_for(namespace, name)
        if cluster_ip and ports and self.services is not None:
            port = int(ports[0].get("port", 0))
            proto = 6 if ports[0].get("protocol", "TCP") == "TCP" else 17
            frontend = L3n4Addr(cluster_ip, port, proto)
            self._svc_frontends[key] = frontend
            self._sync_lb(key)

    def _on_endpoints(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        info = self._info_for(namespace, name)
        old_ips = set(info.backend_ips)
        if ev.action == "deleted":
            info.backend_ips = set()
        else:
            ips = set()
            for subset in ev.obj.get("subsets") or []:
                for addr in subset.get("addresses") or []:
                    if addr.get("ip"):
                        ips.add(addr["ip"])
            info.backend_ips = ips
        self._sync_lb((namespace, name))
        self._sync_ingresses_for(namespace, name)
        # live ToServices → ToCIDRSet retranslation + regeneration
        # (k8s_watcher.go updateK8sEndpointV1 → TranslateRules):
        # depopulate against the OLD endpoint set, populate the new —
        # the reference translator carries both (rule_translate.go
        # RuleTranslator{OldEndpoint, NewEndpoint})
        stale = old_ips - info.backend_ips
        if stale:
            self._retranslate(
                K8sServiceInfo(
                    name=name,
                    namespace=namespace,
                    backend_ips=stale,
                    labels=dict(info.labels),
                ),
                revert=True,
            )
        self._retranslate(info, revert=False)

    def _sync_lb(self, key: Tuple[str, str]) -> None:
        if self.services is None:
            return
        frontend = self._svc_frontends.get(key)
        info = self._svc_info.get(key)
        if frontend is None or info is None:
            return
        backends = [
            L3n4Addr(ip, frontend.port, frontend.protocol)
            for ip in sorted(info.backend_ips)
        ]
        self.services.upsert(frontend, backends)

    def _retranslate(self, info: K8sServiceInfo, revert: bool) -> None:
        with self.daemon.lock:
            self.daemon.repo.translate_rules(
                RuleTranslator(info, revert=revert)
            )
        self.daemon.trigger_policy_updates(
            f"service {info.namespace}/{info.name} endpoints", full=True
        )

    # -- pods & namespaces ---------------------------------------------------

    def _derived_pod_labels(self, namespace: str, pod_labels):
        """Pod labels + namespace meta labels, the label view the
        reference derives for an endpoint (k8s.go GetPodLabels +
        network_policy.go:73-80's namespace key space)."""
        from cilium_tpu.k8s.network_policy import (
            POD_NAMESPACE_META_LABELS,
        )
        from cilium_tpu.labels import Label, Labels

        out = {}
        for k, v in (pod_labels or {}).items():
            out[k] = Label(k, str(v), "k8s")
        out["io.kubernetes.pod.namespace"] = Label(
            "io.kubernetes.pod.namespace", namespace, "k8s"
        )
        for k, v in self._ns_labels.get(namespace, {}).items():
            nk = f"{POD_NAMESPACE_META_LABELS}.{k}"
            out[nk] = Label(nk, str(v), "k8s")
        return Labels(out)

    def _apply_pod_labels(self, key: Tuple[str, str]) -> None:
        ep_id = self._pod_eps.get(key)
        if ep_id is None:
            return
        labels = self._derived_pod_labels(
            key[0], self._pod_labels.get(key, {})
        )
        # identity re-allocation + regeneration (EndpointUpdateLabels)
        self.daemon.update_endpoint_labels(ep_id, labels)

    def _on_pod(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        key = (namespace, name)
        if ev.action == "deleted":
            self._pod_eps.pop(key, None)
            self._pod_labels.pop(key, None)
            return  # endpoint teardown is the CNI DEL's job
        pod_ip = (ev.obj.get("status") or {}).get("podIP")
        ep = None
        if pod_ip:
            ep = self.daemon.endpoint_manager.lookup_ip(pod_ip)
        if ep is None:
            ep = self.daemon.endpoint_manager.lookup_name(name)
        if ep is None:
            return  # pod not (yet) a local endpoint
        self._pod_eps[key] = ep.id
        new_labels = dict(meta.get("labels") or {})
        if self._pod_labels.get(key) == new_labels:
            return
        self._pod_labels[key] = new_labels
        self._apply_pod_labels(key)

    def _on_namespace(self, ev: K8sEvent) -> None:
        meta = ev.obj.get("metadata", {})
        name = meta.get("name", "")
        if ev.action == "deleted":
            self._ns_labels.pop(name, None)
        else:
            new = dict(meta.get("labels") or {})
            if self._ns_labels.get(name) == new:
                return
            self._ns_labels[name] = new
        # re-derive every tracked pod endpoint in this namespace
        for key in [k for k in self._pod_eps if k[0] == name]:
            self._apply_pod_labels(key)

    # -- nodes ---------------------------------------------------------------

    def _on_node(self, ev: K8sEvent) -> None:
        from cilium_tpu.kvstore.node import Node

        meta = ev.obj.get("metadata", {})
        name = meta.get("name", "")
        if name == self.daemon.node_name:
            return  # the local node's pod CIDR stays direct
        internal_ip = None
        for addr in (ev.obj.get("status") or {}).get("addresses") or []:
            if addr.get("type") == "InternalIP":
                internal_ip = addr.get("address")
        spec = ev.obj.get("spec") or {}
        node = Node(
            name=name,
            internal_ip=internal_ip,
            ipv4_alloc_cidr=spec.get("podCIDR"),
        )
        kind = "delete" if ev.action == "deleted" else "upsert"
        self.daemon.tunnel_map.on_node(kind, node)

    # -- ingresses -----------------------------------------------------------

    def _on_ingress(self, ev: K8sEvent) -> None:
        """Single-service ingress → external LB frontend on the host
        address at the backend service's port, backed by that
        service's endpoints (addIngressV1beta1 → syncExternalLB)."""
        if self.services is None:
            return
        meta = ev.obj.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        key = (namespace, name)
        if ev.action == "deleted":
            frontend = self._ingress_frontends.pop(key, None)
            self._ingress_spec.pop(key, None)
            if frontend is not None:
                self.services.delete(frontend)
            return
        backend_ref = (ev.obj.get("spec") or {}).get("backend")
        if not backend_ref:
            return  # only Single Service Ingress is supported
        self._ingress_spec[key] = (
            namespace,
            backend_ref.get("serviceName", ""),
            backend_ref.get("servicePort", 0),
        )
        self._sync_ingress(key)

    def _sync_ingress(self, key: Tuple[str, str]) -> None:
        """Refresh one ingress frontend from its backing service
        (syncExternalLB): also called from the Service/Endpoints
        streams, because the queues are independently serialized and
        may arrive in either order — and a NAMED servicePort
        (IntOrString) only resolves once the service is known."""
        spec = self._ingress_spec.get(key)
        if spec is None:
            return
        namespace, svc_name, raw_port = spec
        try:
            port = int(raw_port)
        except (TypeError, ValueError):
            port = self._svc_ports.get((namespace, svc_name), {}).get(
                str(raw_port), 0
            )
        if not port:
            return  # named port not resolvable (yet)
        frontend = L3n4Addr(self.host_ip, port, 6)
        old = self._ingress_frontends.get(key)
        if old is not None and old != frontend:
            self.services.delete(old)
        self._ingress_frontends[key] = frontend
        info = self._svc_info.get((namespace, svc_name))
        backends = [
            L3n4Addr(ip, port, 6)
            for ip in sorted(info.backend_ips if info else [])
        ]
        self.services.upsert(frontend, backends)

    def _sync_ingresses_for(self, namespace: str, name: str) -> None:
        for key, spec in list(self._ingress_spec.items()):
            if spec[:2] == (namespace, name):
                self._sync_ingress(key)
