"""k8s NetworkPolicy / CiliumNetworkPolicy → api.Rule.

Behavioral port of /root/reference/pkg/k8s/network_policy.go
(ParseNetworkPolicy network_policy.go:127) over JSON dicts:
  - pod selectors are namespace-scoped by injecting the
    io.kubernetes.pod.namespace matchLabel (network_policy.go:103,239);
  - namespace selectors prefix keys with the namespace-meta label
    space io.cilium.k8s.namespace.labels (network_policy.go:73-80),
    and an EMPTY namespaceSelector becomes an Exists requirement on
    the pod-namespace label (select all namespaces, :87-89);
  - empty from/to matches everything → reserved:all selector (:164);
  - ipBlock → CIDRRule with excepts (:258);
  - the k8s default-deny convention (podSelector + policyTypes with
    no rules) becomes an empty IngressRule/EgressRule (:215-231);
  - ports: one PortRule per NetworkPolicyPort, TCP default (:264).

CiliumNetworkPolicy (pkg/k8s/apis/cilium.io/v2): spec/specs hold
api.Rule JSON directly; policy labels identify name+namespace+
derived-from for deletion by label (GetPolicyLabels, utils.go:54).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from cilium_tpu import labels as lbl
from cilium_tpu.labels import Label, LabelArray
from cilium_tpu.policy.api import (
    CIDRRule,
    EgressRule,
    EndpointSelector,
    IngressRule,
    PortProtocol,
    PortRule,
    Rule,
)
from cilium_tpu.policy.api.parse import rule_from_dict
from cilium_tpu.policy.api.selector import Requirement, OP_EXISTS

# pkg/k8s/apis/cilium.io/const.go
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"
POD_NAMESPACE_META_LABELS = "io.cilium.k8s.namespace.labels"
POLICY_LABEL_NAME = "io.cilium.k8s.policy.name"
POLICY_LABEL_NAMESPACE = "io.cilium.k8s.policy.namespace"
POLICY_LABEL_DERIVED_FROM = "io.cilium.k8s.policy.derived-from"

K8S_PREFIX = lbl.SOURCE_K8S_KEY_PREFIX


def _es_from_k8s_selector(*selectors: Optional[dict]) -> EndpointSelector:
    """NewESFromK8sLabelSelector: merge selectors, prefix keys with the
    k8s source (selector.go:190)."""
    match_labels: Dict[str, str] = {}
    match_expressions: List[Requirement] = []
    for selector in selectors:
        if not selector:
            continue
        for k, v in (selector.get("matchLabels") or {}).items():
            match_labels[K8S_PREFIX + k] = v
        for e in selector.get("matchExpressions") or []:
            match_expressions.append(
                Requirement(
                    K8S_PREFIX + e["key"],
                    e["operator"],
                    e.get("values") or [],
                )
            )
    return EndpointSelector(
        match_labels=match_labels, match_expressions=match_expressions
    )


def _parse_peer(namespace: str, peer: dict) -> Optional[EndpointSelector]:
    """parseNetworkPolicyPeer (network_policy.go:63)."""
    ns_sel = peer.get("namespaceSelector")
    pod_sel = peer.get("podSelector")
    if ns_sel is not None:
        prefixed = {
            "matchLabels": {
                f"{POD_NAMESPACE_META_LABELS}.{k}": v
                for k, v in (ns_sel.get("matchLabels") or {}).items()
            },
            "matchExpressions": [
                {**e, "key": f"{POD_NAMESPACE_META_LABELS}.{e['key']}"}
                for e in ns_sel.get("matchExpressions") or []
            ],
        }
        if not prefixed["matchLabels"] and not prefixed["matchExpressions"]:
            # empty namespaceSelector = all namespaces (:87)
            prefixed["matchExpressions"] = [
                {"key": POD_NAMESPACE_LABEL, "operator": OP_EXISTS}
            ]
        return _es_from_k8s_selector(prefixed, pod_sel)
    if pod_sel is not None:
        scoped = {
            "matchLabels": {
                **(pod_sel.get("matchLabels") or {}),
                POD_NAMESPACE_LABEL: namespace,
            },
            "matchExpressions": pod_sel.get("matchExpressions") or [],
        }
        return _es_from_k8s_selector(scoped)
    return None


def _parse_ports(ports: List[dict]) -> List[PortRule]:
    """parsePorts (network_policy.go:264): one PortRule per entry."""
    out = []
    for p in ports:
        if p.get("protocol") is None and p.get("port") is None:
            continue
        protocol = str(p.get("protocol") or "TCP").upper()
        port = str(p.get("port") or "")
        out.append(
            PortRule(
                ports=[PortProtocol(port=port, protocol=protocol)]
            )
        )
    return out


def _ip_block_to_cidr_rule(block: dict) -> CIDRRule:
    return CIDRRule(
        cidr=block["cidr"],
        except_cidrs=list(block.get("except") or []),
    )


def _all_selector() -> EndpointSelector:
    return EndpointSelector.from_labels(
        Label(lbl.ID_NAME_ALL, "", lbl.SOURCE_RESERVED)
    )


def get_policy_labels(
    namespace: str, name: str, derived_from: str
) -> LabelArray:
    """utils.go:54 GetPolicyLabels."""
    return LabelArray(
        [
            Label(POLICY_LABEL_NAME, name, "k8s"),
            Label(POLICY_LABEL_NAMESPACE, namespace, "k8s"),
            Label(POLICY_LABEL_DERIVED_FROM, derived_from, "k8s"),
        ]
    )


def parse_network_policy(np: dict) -> List[Rule]:
    """ParseNetworkPolicy (network_policy.go:127) over the JSON form."""
    meta = np.get("metadata") or {}
    namespace = meta.get("namespace") or "default"
    name = meta.get("name") or ""
    spec = np.get("spec") or {}
    policy_types = spec.get("policyTypes") or []

    ingresses: List[IngressRule] = []
    egresses: List[EgressRule] = []

    for i_rule in spec.get("ingress") or []:
        ingress = IngressRule()
        if i_rule.get("ports"):
            ingress.to_ports = _parse_ports(i_rule["ports"])
        if i_rule.get("from"):
            for peer in i_rule["from"]:
                selector = _parse_peer(namespace, peer)
                if selector is not None:
                    ingress.from_endpoints.append(selector)
                if peer.get("ipBlock"):
                    ingress.from_cidr_set.append(
                        _ip_block_to_cidr_rule(peer["ipBlock"])
                    )
        else:
            # empty from = all sources (network_policy.go:160)
            ingress.from_endpoints.append(_all_selector())
        ingresses.append(ingress)

    for e_rule in spec.get("egress") or []:
        egress = EgressRule()
        if e_rule.get("to"):
            for peer in e_rule["to"]:
                if (
                    peer.get("namespaceSelector") is not None
                    or peer.get("podSelector") is not None
                ):
                    selector = _parse_peer(namespace, peer)
                    if selector is not None:
                        egress.to_endpoints.append(selector)
                if peer.get("ipBlock"):
                    egress.to_cidr_set.append(
                        _ip_block_to_cidr_rule(peer["ipBlock"])
                    )
        else:
            egress.to_endpoints.append(_all_selector())
        if e_rule.get("ports"):
            egress.to_ports = _parse_ports(e_rule["ports"])
        elif not e_rule.get("to"):
            # quirk reproduced: the reference appends the wildcard
            # selector AGAIN for portless+peerless egress rules
            # (network_policy.go:201-208)
            egress.to_endpoints.append(_all_selector())
        egresses.append(egress)

    # k8s default-deny convention (network_policy.go:215-231)
    has_ingress_type = "Ingress" in policy_types
    has_egress_type = "Egress" in policy_types
    if not ingresses and (has_ingress_type or not has_egress_type):
        ingresses = [IngressRule()]
    if not egresses and has_egress_type:
        egresses = [EgressRule()]

    pod_selector = dict(spec.get("podSelector") or {})
    pod_selector.setdefault("matchLabels", {})
    pod_selector = {
        "matchLabels": {
            **(pod_selector.get("matchLabels") or {}),
            POD_NAMESPACE_LABEL: namespace,
        },
        "matchExpressions": pod_selector.get("matchExpressions") or [],
    }

    rule = Rule(
        endpoint_selector=_es_from_k8s_selector(pod_selector),
        ingress=ingresses,
        egress=egresses,
        labels=get_policy_labels(namespace, name, "NetworkPolicy"),
    )
    rule.sanitize()
    return [rule]


def parse_cilium_network_policy(cnp: dict) -> List[Rule]:
    """CNP (pkg/k8s/apis/cilium.io/v2): spec / specs are api.Rule
    JSON; rules get the policy identification labels appended."""
    meta = cnp.get("metadata") or {}
    namespace = meta.get("namespace") or "default"
    name = meta.get("name") or ""
    docs = []
    if cnp.get("spec"):
        docs.append(cnp["spec"])
    docs.extend(cnp.get("specs") or [])

    rules = []
    for doc in docs:
        rule = rule_from_dict(doc)
        rule.labels = LabelArray(
            list(rule.labels)
            + list(
                get_policy_labels(namespace, name, "CiliumNetworkPolicy")
            )
        )
        rule.sanitize()
        rules.append(rule)
    return rules
