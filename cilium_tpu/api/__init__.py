"""REST API server + client (the api/v1 unix-socket seam)."""
