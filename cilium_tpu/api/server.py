"""REST API over a unix socket — the api/v1 surface.

The reference agent is driven entirely over a swagger REST API on a
unix socket (/root/reference/api/v1/openapi.yaml, served by
daemon/server; consumed by /root/reference/pkg/client and the cilium
CLI).  This is the matching seam for this framework: a thread-per-
connection HTTP server on a unix socket exposing the daemon's
control surface as JSON, so out-of-process clients (cilium_tpu.cli,
tooling, tests) operate a RUNNING agent instead of a private
in-memory one.

Routes (the api/v1 subset this framework's daemon implements):
  GET    /healthz            agent liveness + datapath health probe
  GET    /status             full agent status (daemon.status())
  GET    /config             daemon option set
  PATCH  /config             mutate runtime options / enforcement mode
  PATCH  /endpoint/{id}/config  per-endpoint options (regen that endpoint)
  GET    /policy             policy repository (revision, rules)
  POST   /policy             add rules (JSON list; ?replace=1)
  DELETE /policy             delete by labels (JSON list of labels)
  POST   /policy/resolve     policy trace (the explain mode)
  POST   /policy/shadow      shadow window lifecycle (arm candidate
                             rules / arm standby / disarm / promote)
  GET    /policy/diff        live verdict-diff of the armed shadow
                             window (?last=N&since-seq=C)
  GET    /endpoint           endpoint list
  GET    /endpoint/{id}      one endpoint
  PUT    /endpoint/{id}      create endpoint (labels[, ipv4, name]; CNI ADD)
  DELETE /endpoint/{id}      delete endpoint (CNI DEL)
  GET    /identity           identity cache
  GET    /ipcache            ipcache dump
  GET    /metrics            metrics registry dump
  GET    /service            service list; POST upserts; DELETE removes
  GET    /ct                 conntrack dump (bpf_ct_list analog)
  POST   /ipam               allocate an address ({ip} to pin one)
  DELETE /ipam/{ip}          release an address
  POST   /monitor            open a monitor session (persistent queue)
  GET    /monitor/{sid}      long-poll events (?timeout=s&max=n)
  DELETE /monitor/{sid}      close the session
  GET    /flows              filtered flow records (Hubble observe;
                             ?follow=1&since-seq=N long-polls)
  GET    /flows/summary      flow aggregations (top drop reasons,
                             denied identity pairs, per-chip counts)
  GET    /debug/profile      thread stacks + cumulative SpanStat
                             phase totals (?reset=1 zeroes after)
  GET    /debug/traces       span-plane query: ?trace-id=, ?min-ms=,
                             ?site=, ?last=N, ?slowest=N

Every request runs under a root `http.request` span; an inbound
`traceparent` header adopts the caller's trace and the reply carries
`traceparent`/X-Trace-Id response headers (cilium_tpu.tracing).
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from typing import Optional

from cilium_tpu import option
from cilium_tpu.labels import LabelArray
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.policy.api import rules_from_json
from cilium_tpu.policy.search import Port, SearchContext


class DaemonAPI:
    """The operations behind the routes — shared by the HTTP server
    and the CLI's in-process fallback, so both speak the same
    contract (pkg/client's methods mirror this)."""

    def __init__(self, daemon) -> None:
        import threading as _threading

        self._monitor_sessions = {}
        # the API server is thread-per-connection: open/poll/close/
        # expire race without this
        self._monitor_lock = _threading.Lock()
        self.daemon = daemon

    def healthz(self) -> dict:
        from cilium_tpu.health import probe_endpoints

        # the resilience rollup first: breaker state and stuck
        # controllers flip health to degraded even when the probe
        # below succeeds (degraded-but-serving is the whole point of
        # the host-path failover)
        health = self.daemon.health()
        try:
            probes = probe_endpoints(self.daemon.endpoint_manager)
            reachable = sum(1 for p in probes if p.reachable)
            out = {
                "status": health["status"],
                "reasons": health["reasons"],
                "breaker": health["breaker"],
                "degraded_batches": health["degraded_batches"],
                "shed_flows": health["shed_flows"],
                "endpoints": len(probes),
                "reachable": reachable,
            }
            if "chips" in health:
                # per-chip breaker states (mesh failover router
                # attached): which ordinal is out, not just
                # "degraded"
                out["chips"] = health["chips"]
            return out
        except Exception as exc:
            return {
                "status": "degraded",
                "reasons": health["reasons"] + [str(exc)],
                "breaker": health["breaker"],
                "detail": str(exc),
            }

    def status(self) -> dict:
        return self.daemon.status()

    def config_patch(self, changes: dict) -> dict:
        return self.daemon.config_patch(changes)

    def endpoint_config_patch(
        self, endpoint_id: int, changes: dict
    ) -> dict:
        return self.daemon.endpoint_config_patch(
            endpoint_id, changes
        )

    def config_get(self) -> dict:
        from cilium_tpu import option

        cfg = option.Config
        return {
            "policy_enforcement": cfg.policy_enforcement,
            "options": dict(getattr(cfg, "opts", {}) or {}),
            # the option LIBRARY: define/description/requires per
            # option (option.go's descriptor table, for `cilium
            # config --list-options`)
            "library": cfg.opts.describe(),
            "ipam_cidr": str(self.daemon.ipam.cidr),
        }

    def debug_profile(self, reset: bool = False) -> dict:
        """The pprof/loadinfo analog (the reference serves
        /debug/pprof and logs loadinfo on slow operations): a
        point-in-time profile of every live thread's stack plus the
        daemon's accumulated regeneration span statistics — enough to
        diagnose a wedged agent over the API, which is what the
        reference's handlers exist for.

        The SpanStat numbers are CUMULATIVE since daemon start (or
        the last reset).  `?reset=1` returns the profile and then
        zeroes the accumulators, so before/after experiments don't
        need a daemon restart — the reply always shows the pre-reset
        totals."""
        import sys as _sys
        import threading as _threading
        import traceback as _traceback

        frames = _sys._current_frames()
        threads = []
        for t in _threading.enumerate():
            frame = frames.get(t.ident)
            threads.append(
                {
                    "name": t.name,
                    "daemon": t.daemon,
                    "stack": (
                        _traceback.format_stack(frame)
                        if frame is not None
                        else []
                    ),
                }
            )
        def _span_dict(spanstats):
            return {
                name: {
                    "success_total_s": s.success_total,
                    "failure_total_s": s.failure_total,
                    "num_success": s.num_success,
                    "num_failure": s.num_failure,
                }
                for name, s in spanstats.items()
            }

        try:
            load1, load5, load15 = __import__("os").getloadavg()
        except OSError:  # pragma: no cover - platform-dependent
            load1 = load5 = load15 = -1.0
        from cilium_tpu.metrics import registry as _metrics

        reply = {
            "threads": threads,
            "num_threads": len(threads),
            "cumulative_since_reset": True,
            "regeneration_spans": _span_dict(self.daemon.regen_spans),
            "datapath_spans": _span_dict(self.daemon.datapath_spans),
            "batch_latency": {
                "p50_s": _metrics.batch_duration.window_quantile(0.5),
                "p99_s": _metrics.batch_duration.window_quantile(0.99),
            },
            "loadavg": [load1, load5, load15],
        }
        if reset:
            self.daemon.reset_profile()
            reply["reset"] = True
        return reply

    def debug_perf(self, params: dict) -> dict:
        """GET /debug/perf: the live performance plane — per-batch
        phase windows (p50/p99/max), batch fill, queue delay, the
        ingest-stall ledger, per-tenant SLO-class compliance, the
        live gather-byte model against the published layout stamp,
        dispatch-overlap bookkeeping, per-chip HBM and the retune
        history.

        Params: since=<cursor> (only retune records newer than the
        cursor — pollers resume where they left off), leaves=1 (the
        per-leaf byte-model breakdown rides along)."""
        since = params.get("since")
        return self.daemon.perf_snapshot(
            since=None if since is None else int(since),
            leaves=params.get("leaves") in ("1", "true"),
        )

    def traces_get(self, params: dict) -> dict:
        """GET /debug/traces: the span-plane query surface.

        Params: trace-id=<32 hex> (one trace, oldest-first),
        min-ms=<float> (only spans at least that long),
        site=<instrumentation site>, last=<N> (newest N spans,
        default 1024), slowest=<N> (trace-level ranking by root
        duration instead of a span list)."""
        params = dict(params)
        tracer = self.daemon.tracer
        slowest_raw = params.pop("slowest", None)
        if slowest_raw is not None:
            return {
                "traces": tracer.slowest_traces(int(slowest_raw)),
                "dropped": tracer.dropped,
                "finished_total": tracer.finished_total,
            }
        trace_id = params.pop("trace-id", None)
        min_ms_raw = params.pop("min-ms", None)
        site = params.pop("site", None)
        last_raw = params.pop("last", None)
        if params:
            raise ValueError(
                f"unknown trace filter {sorted(params)[0]!r}"
            )
        spans = tracer.query(
            trace_id=trace_id,
            min_duration_ms=(
                float(min_ms_raw) if min_ms_raw is not None else None
            ),
            site=site,
            last=int(last_raw) if last_raw is not None else 1024,
        )
        return {
            "spans": [s.to_dict() for s in spans],
            "matched": len(spans),
            "dropped": tracer.dropped,
            "finished_total": tracer.finished_total,
            "sample_rate": tracer.sample_rate,
        }

    def policy_get(self) -> dict:
        repo = self.daemon.repo
        return {
            "revision": repo.get_revision(),
            "count": repo.num_rules(),
            "rules": [str(rule) for rule in repo.rules],
        }

    def policy_add(self, rules_json: str, replace: bool) -> dict:
        rules = rules_from_json(rules_json)
        revision = self.daemon.policy_add(rules, replace=replace)
        return {"revision": revision, "count": len(rules)}

    def policy_delete(self, labels: list) -> dict:
        revision, deleted = self.daemon.policy_delete(
            LabelArray.parse(*labels)
        )
        return {"revision": revision, "deleted": deleted}

    def trace_tuple(self, body: dict) -> dict:
        """POST /policy/trace-tuple: the single-tuple datapath
        explain (policy.trace.trace_tuple) over the REST contract."""
        direction = body.get("direction", "ingress")
        if isinstance(direction, str):
            try:
                direction = {"ingress": 0, "egress": 1}[
                    direction.lower()
                ]
            except KeyError:
                raise ValueError(
                    f"direction must be ingress or egress, "
                    f"got {direction!r}"
                )
        elif direction not in (0, 1):
            raise ValueError(
                f"direction must be 0 or 1, got {direction!r}"
            )
        return self.daemon.trace_tuple(
            ep_id=int(body["ep_id"]),
            saddr=body["saddr"],
            daddr=body["daddr"],
            dport=int(body["dport"]),
            proto=int(body.get("proto", 6)),
            direction=direction,
            sport=int(body.get("sport", 0)),
            is_fragment=bool(body.get("is_fragment", False)),
        )

    # -- shadow policy rollout (cilium_tpu.shadow) ----------------------------

    def policy_shadow(self, body: dict) -> dict:
        """POST /policy/shadow: the shadow window lifecycle.

        {"action": "arm", "rules": [...]} compiles the candidate
        rules into a shadow world (omit rules for standby mode — the
        previous publish); optional "sample_rate" (default 1.0) and
        "seed" drive the batch sampler.  {"action": "disarm"} closes
        the window; {"action": "promote"} installs a candidate
        through the normal policy path and zeroes the window
        counters."""
        action = body.get("action")
        shadow = self.daemon.shadow
        if action == "arm":
            rules = body.get("rules")
            rules_json = (
                json.dumps(rules) if rules is not None else None
            )
            return shadow.arm(
                rules_json=rules_json,
                sample_rate=float(body.get("sample_rate", 1.0)),
                seed=int(body.get("seed", 0)),
            )
        if action == "disarm":
            return shadow.disarm()
        if action == "promote":
            return shadow.promote()
        raise ValueError(
            f"action must be arm, disarm or promote, got {action!r}"
        )

    def policy_diff(self, params: dict) -> dict:
        """GET /policy/diff: the armed window's verdict-diff surface
        — status + summary (per-column/per-direction change counts,
        allow→deny vs deny→allow split, top re-verdicted identity
        pairs) + the newest diff records.  Params: last=N (default
        256), since-seq=<cursor> (follow-style reader)."""
        params = dict(params)
        last_raw = params.pop("last", None)
        since_raw = params.pop("since-seq", None)
        if params:
            raise ValueError(
                f"unknown diff param {sorted(params)[0]!r}"
            )
        return self.daemon.shadow.diff(
            last=int(last_raw) if last_raw is not None else 256,
            since_seq=(
                int(since_raw) if since_raw is not None else None
            ),
        )

    def policy_resolve(self, body: dict) -> dict:
        ctx = SearchContext(
            from_labels=LabelArray.parse_select(
                *body.get("from", [])
            ),
            to_labels=LabelArray.parse_select(*body.get("to", [])),
            dports=[
                Port(int(p["port"]), p.get("protocol", "TCP"))
                for p in body.get("dports", [])
            ],
        )
        verdict, log = self.daemon.policy_resolve(ctx)
        return {"verdict": str(verdict), "trace": log}

    def endpoint_list(self) -> list:
        return [
            {
                "id": ep.id,
                "name": ep.name,
                "ipv4": ep.ipv4,
                "state": ep.state,
                "identity": (
                    ep.security_identity.id
                    if ep.security_identity
                    else None
                ),
                "policy_revision": ep.policy_revision,
            }
            for ep in self.daemon.endpoint_manager.endpoints()
        ]

    def endpoint_create(self, endpoint_id: int, body: dict) -> dict:
        from cilium_tpu.labels import labels_from_json

        labels = labels_from_json(body.get("labels", []))
        endpoint = self.daemon.create_endpoint(
            endpoint_id,
            labels,
            ipv4=body.get("ipv4"),
            name=body.get("name", ""),
            ip_reserved=bool(body.get("ip_reserved")),
        )
        return {
            "id": endpoint.id,
            "ipv4": endpoint.ipv4,
            "identity": (
                endpoint.security_identity.id
                if endpoint.security_identity
                else None
            ),
            "state": endpoint.state,
        }

    def endpoint_delete(
        self, endpoint_id: int, expected_name: Optional[str] = None
    ) -> dict:
        return {
            "deleted": self.daemon.delete_endpoint(
                endpoint_id, expected_name
            )
        }

    def endpoint_get(self, endpoint_id: int) -> Optional[dict]:
        for entry in self.endpoint_list():
            if entry["id"] == endpoint_id:
                return entry
        return None

    def service_list(self) -> list:
        # snapshot under the daemon lock: the server is thread-per-
        # connection and POST/DELETE mutate these dicts concurrently
        with self.daemon.lock:
            services = [
                (svc, list(svc.backends))
                for svc in self.daemon.services.by_id.values()
            ]
        return [
            {
                "id": svc.id,
                "frontend": {
                    "ip": svc.frontend.ip,
                    "port": svc.frontend.port,
                    "protocol": svc.frontend.protocol,
                },
                "backends": [
                    {
                        "ip": b.addr.ip,
                        "port": b.addr.port,
                        "protocol": b.addr.protocol,
                    }
                    for b in _backends
                ],
            }
            for svc, _backends in services
        ]

    def service_upsert(self, body: dict) -> dict:
        from cilium_tpu.lb.service import L3n4Addr

        fe = body["frontend"]
        frontend = L3n4Addr(
            fe["ip"], int(fe["port"]), int(fe.get("protocol", 6))
        )
        backends = [
            L3n4Addr(
                b["ip"], int(b["port"]), int(b.get("protocol", 6))
            )
            for b in body.get("backends", [])
        ]
        svc = self.daemon.service_upsert(frontend, backends)
        return {"id": svc.id}

    def service_delete(self, body: dict) -> dict:
        from cilium_tpu.lb.service import L3n4Addr

        fe = body["frontend"]
        frontend = L3n4Addr(
            fe["ip"], int(fe["port"]), int(fe.get("protocol", 6))
        )
        return {"deleted": self.daemon.service_delete(frontend)}

    def ct_list(self, limit: int = 4096) -> dict:
        import ipaddress as _ipaddress

        # daemon.ct is the IPv4 conntrack map (a v6 map is a separate
        # CTMap compiled by engine/datapath6); the family comes from
        # WHICH map is dumped, never from address magnitude — a v6
        # address numerically below 2^32 (e.g. ::1) must not render
        # as a dotted quad
        def _fmt(addr: int) -> str:
            try:
                return str(_ipaddress.IPv4Address(addr))
            except ValueError:
                return str(addr)

        entries = []
        # snapshot: the ct-gc controller thread deletes from this
        # dict concurrently
        snapshot = list(self.daemon.ct.entries.items())
        for key, entry in snapshot:
            if len(entries) >= limit:
                break
            entries.append(
                {
                    "daddr": _fmt(key.daddr),
                    "saddr": _fmt(key.saddr),
                    "dport": key.dport,
                    "sport": key.sport,
                    "proto": key.nexthdr,
                    "flags": key.flags,
                    "lifetime": entry.lifetime,
                    "rx_packets": entry.rx_packets,
                    "tx_packets": entry.tx_packets,
                    "rev_nat": entry.rev_nat_index,
                }
            )
        return {
            "count": len(snapshot),
            "entries": entries,
        }

    def ipam_allocate(self, ip: Optional[str] = None) -> dict:
        got = self.daemon.ipam.allocate(ip)
        return {"ip": got}

    def ipam_release(self, ip: str) -> dict:
        return {"released": self.daemon.ipam.release(ip)}

    def identity_list(self) -> dict:
        return {
            str(num_id): [str(label) for label in labels]
            for num_id, labels in self.daemon.identity_cache().items()
        }

    def ipcache_dump(self) -> dict:
        return dict(self.daemon.lpm_builder.mappings)

    # -- monitor sessions (the `cilium monitor` stream, re-shaped for
    # HTTP: the reference's monitor unix socket pushes; REST clients
    # long-poll a per-session persistent queue so no events are lost
    # between polls; idle sessions expire) ------------------------------

    MONITOR_SESSION_IDLE_S = 60.0

    def monitor_open(self) -> dict:
        import time as _time
        import uuid

        # expire on OPEN too: sessions abandoned before their first
        # poll must not leak bus subscribers forever
        self._expire_monitor_sessions()
        sid = uuid.uuid4().hex[:12]
        q = self.daemon.monitor.subscribe_queue()
        with self._monitor_lock:
            # [queue, [last-active], delivery state]: `seq` numbers
            # each delivered batch; the batch stays in `pending`
            # until the client's NEXT poll acknowledges it (ack=seq),
            # so a reply lost to a client hang-up mid-write is
            # re-delivered instead of silently dropped.  `lock`
            # serializes polls per session: two concurrent polls
            # would each drain events and overwrite the single
            # `pending` slot, silently dropping one delivered-but-
            # unacked batch
            self._monitor_sessions[sid] = (
                q,
                [_time.monotonic()],
                {
                    "seq": 0,
                    "pending": None,
                    "lock": threading.Lock(),
                },
            )
        return {"session": sid}

    def monitor_poll(
        self, sid: str, timeout: float = 5.0, max_events: int = 1024,
        ack: Optional[int] = None,
    ) -> Optional[dict]:
        import dataclasses
        import time as _time

        self._expire_monitor_sessions()
        with self._monitor_lock:
            entry = self._monitor_sessions.get(sid)
            if entry is None:
                return None
            q, last, state = entry
            last[0] = _time.monotonic()
            poll_lock = state.setdefault("lock", threading.Lock())
        # Serialize polls per session OUTSIDE the registry lock: a
        # second concurrent poll waits for the first to finish (its
        # blocking wait is bounded by the 30 s timeout clamp below)
        # instead of racing it for the single pending slot; a poller
        # that cannot get the lock within that bound reports busy
        # rather than corrupting the ack protocol.  Clamp garbage
        # timeouts (negative, NaN) to 0 — Lock.acquire raises on
        # them, and a bad query param must not become a 500.
        timeout = min(timeout, 30.0)
        if not timeout > 0:
            timeout = 0.0
        if not poll_lock.acquire(timeout=timeout + 5.0):
            return {"events": [], "lost": 0, "busy": True}
        try:
            with self._monitor_lock:
                entry = self._monitor_sessions.get(sid)
                if entry is None:  # expired while waiting
                    return None
                q, last, state = entry
                last[0] = _time.monotonic()
                if state["pending"] is not None:
                    if ack is None or ack == state["seq"]:
                        # ack'd — or a legacy client that never acks
                        # (implicit ack keeps old pollers moving;
                        # only ack-aware clients get the re-delivery
                        # guarantee)
                        state["pending"] = None
                    else:
                        # the previous reply never reached the client
                        # (hang-up mid-write): re-deliver the same
                        # batch under the same seq
                        return dict(state["pending"])
            deadline = _time.monotonic() + min(timeout, 30.0)
            max_events = max(1, max_events)
            events = []
            while not events:
                # blocking wakeup from MonitorBus.publish — no spin
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                if not self.daemon.monitor.wait_for_events(
                    q, remaining
                ):
                    break
                with self._monitor_lock:
                    while q and len(events) < max_events:
                        ev = q.popleft()
                        events.append(
                            {
                                "event": type(ev).__name__,
                                **dataclasses.asdict(ev),
                            }
                        )
            reply = {
                "events": events,
                # THIS session's drops since the LAST poll, not the
                # bus-global count (one abandoned subscriber must not
                # inflate everyone's loss report, and a one-time
                # overflow must not read as ongoing loss forever)
                "lost": self.daemon.monitor.queue_drops(
                    q, reset=True
                ),
            }
            with self._monitor_lock:
                entry = self._monitor_sessions.get(sid)
                if entry is not None and events:
                    state = entry[2]
                    state["seq"] += 1
                    reply["seq"] = state["seq"]
                    state["pending"] = dict(reply)
            return reply
        finally:
            poll_lock.release()

    def monitor_close(self, sid: str) -> dict:
        with self._monitor_lock:
            entry = self._monitor_sessions.pop(sid, None)
        if entry is not None:
            self.daemon.monitor.unsubscribe_queue(entry[0])
        return {"closed": entry is not None}

    def _expire_monitor_sessions(self) -> None:
        import time as _time

        now = _time.monotonic()
        with self._monitor_lock:
            expired = [
                (sid, entry[0])
                for sid, entry in self._monitor_sessions.items()
                if now - entry[1][0] > self.MONITOR_SESSION_IDLE_S
            ]
            for sid, _ in expired:
                self._monitor_sessions.pop(sid, None)
        for _, q in expired:
            self.daemon.monitor.unsubscribe_queue(q)

    # -- fault injection (the chaos framework's REST surface) ----------------

    def fault_list(self) -> dict:
        from cilium_tpu import faultinject

        return {
            "sites": list(faultinject.SITES),
            "armed": faultinject.armed(),
        }

    def fault_arm(self, body: dict) -> dict:
        from cilium_tpu import faultinject

        site = body.get("site")
        if not site:
            raise ValueError("site required")
        faultinject.arm(site, body.get("spec", "raise"))
        return {"armed": faultinject.armed()}

    def fault_disarm(self, site: Optional[str] = None) -> dict:
        from cilium_tpu import faultinject

        if site:
            disarmed = 1 if faultinject.disarm(site) else 0
        else:
            disarmed = faultinject.disarm_all()
        return {
            "disarmed": disarmed,
            "armed": faultinject.armed(),
        }

    def process_flows(self, buf: bytes, tenant: str = "") -> dict:
        """POST /datapath/flows: run a binary flow-record buffer
        through the serving plane (the audit-path ingress over REST).
        Malformed buffers raise ValueError → HTTP 400 at the route;
        the stream itself completes even under dispatch faults
        (host-path failover).  ``tenant`` stamps the batch's flow
        records with the submitting tenant/namespace."""
        from cilium_tpu import tracing

        stats = self.daemon.process_flows(buf, tenant=tenant)
        return {
            "total": stats.total,
            "allowed": stats.allowed,
            "denied": stats.denied,
            "dropped": stats.dropped,
            "shed": stats.shed,
            "batches": stats.batches,
            "degraded_batches": stats.degraded_batches,
            "seconds": stats.seconds,
            # the span-plane join key of THIS request (also in the
            # traceparent/X-Trace-Id response headers)
            "trace_id": tracing.current_trace_id(),
        }

    STREAM_WAIT_MAX = 60.0

    def process_flows_stream(
        self,
        buf: bytes,
        tenant: str = "default",
        deadline_ms: float = None,
    ) -> dict:
        """POST /datapath/flows?stream=1: submit the buffer to the
        CONTINUOUS serving plane (cilium_tpu.serve) instead of
        dispatching it as its own batch — the daemon coalesces
        concurrent submissions into right-sized device batches under
        the latency SLO, with per-tenant fair admission.  Blocks
        until this submission's flows are served (or shed under
        Overload backpressure) and replies with the same counters as
        the one-shot route plus queueing detail."""
        from cilium_tpu import tracing

        r = self.daemon.serving_plane().submit(
            buf,
            tenant=tenant,
            deadline_ms=deadline_ms,
            wait=True,
            timeout=self.STREAM_WAIT_MAX,
        )
        served = int((~r.shed_mask).sum()) if not r.shed else 0
        n_allowed = int(r.allowed[~r.shed_mask].sum())
        shed = r.n - served
        return {
            "total": served,
            "allowed": n_allowed,
            "denied": served - n_allowed,
            "dropped": r.dropped_unknown,
            "prefiltered": r.prefiltered,
            "shed": shed,
            "tenant": tenant,
            "batches": r.batches,
            "degraded_batches": r.degraded_batches,
            "queue_delay_ms": r.queue_delay_s * 1000.0,
            "seconds": r.latency_s,
            "trace_id": tracing.current_trace_id(),
        }

    # -- flow observability (the Hubble observe surface over REST) -----------

    FLOW_FOLLOW_TIMEOUT_MAX = 30.0

    def flows_get(self, params: dict) -> dict:
        """GET /flows: filtered read of the flow-record ring.

        Hubble-like filter params: verdict=FORWARDED|DROPPED,
        drop-reason=<canonical name>, identity=<id> (either side),
        ep=<endpoint id>, port=<dport>, proto=tcp|udp|<n>,
        direction=ingress|egress, since=<unix s | 30s/5m/1h>,
        chip=<ordinal>.  Pagination: last=N (newest N matches,
        default 1024).  Follow mode: follow=1&since-seq=<cursor>
        long-polls (timeout=s, clamped) until a MATCHING record newer
        than the cursor lands — poll again with the reply's
        `last_seq` as the next cursor, the MonitorBus long-poll
        contract over flows."""
        from cilium_tpu.flow import FlowFilter

        params = dict(params)
        follow = str(params.pop("follow", "")).lower() in (
            "1", "true", "yes", "on",
        )
        last_raw = params.pop("last", None)
        last = int(last_raw) if last_raw is not None else 1024
        timeout = min(
            float(params.pop("timeout", 5.0)),
            self.FLOW_FOLLOW_TIMEOUT_MAX,
        )
        since_seq_raw = params.pop("since-seq", None)
        since_seq = (
            int(since_seq_raw) if since_seq_raw is not None else None
        )
        flt = FlowFilter.from_params(params)
        store = self.daemon.flow_store
        if follow:
            cursor = (
                since_seq if since_seq is not None else store.last_seq
            )
            records = store.wait_for_flows(cursor, timeout, flt)
            if last:
                # follow keeps the OLDEST N of a burst: the reply's
                # last_seq then resumes exactly after the delivered
                # tail, so the trimmed remainder arrives on the next
                # poll instead of being skipped forever (one-shot
                # mode trims newest — there is no cursor to protect)
                records = records[:last]
            # a timed-out poll reports the UNCHANGED cursor: records
            # landing between the timeout and this reply must be
            # seen by the client's next poll, not skipped
            last_seq = records[-1].seq if records else cursor
        else:
            records = store.query(flt, last=last, after_seq=since_seq)
            last_seq = (
                records[-1].seq if records else store.last_seq
            )
        return {
            "flows": [r.to_dict() for r in records],
            "matched": len(records),
            "last_seq": last_seq,
            "captured_total": store.captured_total,
            "evicted": store.evicted,
        }

    def flows_summary(self, top: int = 10) -> dict:
        """GET /flows/summary: ring aggregations — top drop reasons,
        top denied identity pairs, per-chip counts + imbalance."""
        return self.daemon.flow_store.summary(top=top)

    def metrics_dump(self) -> dict:
        return {"text": metrics.expose()}

    def metrics_prometheus(self) -> str:
        """GET /metrics/prometheus: the raw Prometheus text
        exposition (text/plain; version=0.0.4) — what a Prometheus
        scrape job points at; the JSON /metrics route stays for the
        CLI contract."""
        return metrics.expose()


class _Handler(BaseHTTPRequestHandler):
    # quiet the default stderr access log
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _handle_traced(self, inner) -> None:
        """Every request runs under a root `http.request` span: an
        inbound `traceparent` header adopts the caller's trace (so a
        client's id shows on every child span and flow record), and
        the reply echoes the span's context back (`traceparent` +
        X-Trace-Id response headers) — the Dapper propagation seam of
        the REST surface.

        Long-poll routes (monitor polls, /flows follow mode) are NOT
        traced: their duration is the client's idle wait, so their
        spans would dominate `trace --slowest` and churn real batch
        traces out of the bounded ring."""
        from cilium_tpu import tracing

        path, _, query = self.path.partition("?")
        if self.command == "GET" and (
            path.startswith("/monitor/")
            or (path == "/flows" and "follow=1" in query)
        ):
            return inner()
        parent = tracing.parse_traceparent(
            self.headers.get(tracing.TRACEPARENT_HEADER)
        )
        with tracing.tracer.span(
            "http.request",
            site="api.server",
            parent=parent,
            attrs={"method": self.command, "path": path},
        ) as sp:
            self._span = sp
            try:
                inner()
            finally:
                self._span = None

    def _trace_headers(self, code: int) -> None:
        """Emit span-context response headers (sampled spans only)."""
        from cilium_tpu import tracing

        span = getattr(self, "_span", None)
        if span is None or not getattr(span, "trace_id", ""):
            return
        span.attrs["status_code"] = code
        if code >= 500:
            span.status = "error"
        self.send_header(
            tracing.TRACEPARENT_HEADER,
            tracing.format_traceparent(span),
        )
        self.send_header(tracing.TRACE_ID_HEADER, span.trace_id)

    def _reply(self, code: int, body) -> None:
        data = json.dumps(body).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self._trace_headers(code)
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client hung up (or the server is stopping while a
            # long-poll handler is mid-reply): there is nobody to
            # answer, and an exception escaping a handler thread is
            # just teardown noise
            pass

    def _reply_text(self, code: int, text: str,
                    content_type: str = "text/plain") -> None:
        data = text.encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self._trace_headers(code)
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n).decode() if n else ""

    def _body_raw(self) -> bytes:
        """Raw request body (binary routes: flow-record buffers)."""
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def do_GET(self) -> None:  # noqa: N802
        self._handle_traced(self._route_get)

    def do_POST(self) -> None:  # noqa: N802
        self._handle_traced(self._route_post)

    def do_PUT(self) -> None:  # noqa: N802
        self._handle_traced(self._route_put)

    def do_PATCH(self) -> None:  # noqa: N802
        self._handle_traced(self._route_patch)

    def do_DELETE(self) -> None:  # noqa: N802
        self._handle_traced(self._route_delete)

    def _route_get(self) -> None:
        api: DaemonAPI = self.server.api  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                return self._reply(200, api.healthz())
            if path == "/status":
                return self._reply(200, api.status())
            if path == "/config":
                return self._reply(200, api.config_get())
            if path == "/policy":
                return self._reply(200, api.policy_get())
            if path == "/endpoint":
                return self._reply(200, api.endpoint_list())
            if path.startswith("/endpoint/"):
                raw = path.rsplit("/", 1)[1]
                if not raw.isdigit():
                    return self._reply(404, {"error": "not found"})
                got = api.endpoint_get(int(raw))
                if got is None:
                    return self._reply(404, {"error": "not found"})
                return self._reply(200, got)
            if path == "/identity":
                return self._reply(200, api.identity_list())
            if path == "/ipcache":
                return self._reply(200, api.ipcache_dump())
            if path == "/metrics":
                return self._reply(200, api.metrics_dump())
            if path == "/metrics/prometheus":
                return self._reply_text(
                    200,
                    api.metrics_prometheus(),
                    content_type=(
                        "text/plain; version=0.0.4; charset=utf-8"
                    ),
                )
            if path == "/policy/diff":
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                params = {k: v[0] for k, v in qs.items()}
                try:
                    return self._reply(200, api.policy_diff(params))
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/flows":
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                params = {k: v[0] for k, v in qs.items()}
                try:
                    return self._reply(200, api.flows_get(params))
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/flows/summary":
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                try:
                    top = int(qs.get("top", ["10"])[0])
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                return self._reply(200, api.flows_summary(top=top))
            if path == "/debug/profile":
                reset = "reset=1" in (self.path.partition("?")[2] or "")
                return self._reply(200, api.debug_profile(reset=reset))
            if path == "/debug/perf":
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                params = {k: v[0] for k, v in qs.items()}
                try:
                    return self._reply(200, api.debug_perf(params))
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/debug/traces":
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                params = {k: v[0] for k, v in qs.items()}
                try:
                    return self._reply(200, api.traces_get(params))
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/debug/faults":
                return self._reply(200, api.fault_list())
            if path == "/service":
                return self._reply(200, api.service_list())
            if path == "/ct":
                return self._reply(200, api.ct_list())
            if path.startswith("/monitor/"):
                from urllib.parse import parse_qs

                qs = parse_qs(self.path.partition("?")[2])
                sid = path.split("/monitor/", 1)[1]
                try:
                    timeout = float(qs.get("timeout", ["5"])[0])
                    max_events = int(qs.get("max", ["1024"])[0])
                    ack_raw = qs.get("ack", [None])[0]
                    ack = None if ack_raw is None else int(ack_raw)
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                got = api.monitor_poll(
                    sid, timeout=timeout, max_events=max_events,
                    ack=ack,
                )
                if got is None:
                    return self._reply(
                        404, {"error": "unknown monitor session"}
                    )
                return self._reply(200, got)
            return self._reply(404, {"error": f"no route {path}"})
        except Exception as exc:
            return self._reply(500, {"error": str(exc)})

    def _route_post(self) -> None:
        api: DaemonAPI = self.server.api  # type: ignore
        path, _, query = self.path.partition("?")
        try:
            if path == "/policy":
                replace = "replace=1" in query
                return self._reply(
                    200, api.policy_add(self._body(), replace)
                )
            if path == "/policy/resolve":
                return self._reply(
                    200, api.policy_resolve(json.loads(self._body()))
                )
            if path == "/policy/trace-tuple":
                try:
                    body = json.loads(self._body() or "{}")
                except json.JSONDecodeError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                # missing required fields are a 400 (caller error);
                # only an unknown endpoint id is 404
                missing = [
                    k for k in ("ep_id", "saddr", "daddr", "dport")
                    if k not in body
                ]
                if missing:
                    return self._reply(
                        400,
                        {"error": f"missing fields: {missing}"},
                    )
                try:
                    return self._reply(
                        200, api.trace_tuple(body)
                    )
                except KeyError as exc:
                    return self._reply(404, {"error": str(exc)})
            if path == "/policy/shadow":
                try:
                    body = json.loads(self._body() or "{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be an object")
                except (json.JSONDecodeError, ValueError) as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                try:
                    return self._reply(
                        200, api.policy_shadow(body)
                    )
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                except RuntimeError as exc:
                    # lifecycle conflicts (no published tables, no
                    # previous publish, nothing to promote) are the
                    # caller racing the world, not a server fault
                    return self._reply(409, {"error": str(exc)})
            if path == "/monitor":
                return self._reply(201, api.monitor_open())
            if path == "/debug/faults":
                try:
                    body = json.loads(self._body() or "{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be an object")
                    return self._reply(200, api.fault_arm(body))
                except (json.JSONDecodeError, ValueError) as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/datapath/flows":
                # a truncated/corrupt record buffer is the CLIENT's
                # fault: clean 400, never a daemon crash
                from urllib.parse import parse_qs

                qs = parse_qs(query)
                tenant = qs.get("tenant", [""])[0]
                stream = qs.get("stream", ["0"])[0] in (
                    "1", "true", "yes", "on",
                )
                try:
                    if stream:
                        deadline_raw = qs.get(
                            "deadline-ms", [None]
                        )[0]
                        deadline_ms = (
                            float(deadline_raw)
                            if deadline_raw is not None
                            else None
                        )
                        return self._reply(
                            200,
                            api.process_flows_stream(
                                self._body_raw(),
                                tenant=tenant or "default",
                                deadline_ms=deadline_ms,
                            ),
                        )
                    return self._reply(
                        200,
                        api.process_flows(
                            self._body_raw(), tenant=tenant
                        ),
                    )
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if path == "/service":
                try:
                    body = json.loads(self._body() or "{}")
                    if not isinstance(body, dict) or "frontend" not in body:
                        raise ValueError("frontend required")
                except (json.JSONDecodeError, ValueError) as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                return self._reply(200, api.service_upsert(body))
            if path == "/ipam":
                # parse faults are 400; allocation failures (pool
                # exhausted, duplicate pin — IPAMError is a
                # ValueError) are SERVER conditions and must not ride
                # the blanket bad-request catch below
                try:
                    body = json.loads(self._body() or "{}")
                except json.JSONDecodeError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                try:
                    return self._reply(
                        201, api.ipam_allocate(body.get("ip"))
                    )
                except Exception as exc:
                    return self._reply(503, {"error": str(exc)})
            return self._reply(404, {"error": f"no route {path}"})
        except (json.JSONDecodeError, KeyError, ValueError) as exc:
            return self._reply(400, {"error": f"bad request: {exc}"})
        except Exception as exc:
            return self._reply(500, {"error": str(exc)})

    def _route_put(self) -> None:
        from cilium_tpu.daemon import EndpointConflict

        api: DaemonAPI = self.server.api  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path.startswith("/endpoint/"):
                raw = path.rsplit("/", 1)[1]
                if not raw.isdigit():
                    return self._reply(404, {"error": "not found"})
                # parse errors alone are the client's fault — deeper
                # ValueErrors (IPAM exhaustion is one) are SERVER
                # conditions and must not masquerade as 400s
                try:
                    body = json.loads(self._body() or "{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be an object")
                    labels = body.get("labels", [])
                    if not isinstance(labels, list) or any(
                        not isinstance(item, dict)
                        or "key" not in item
                        for item in labels
                    ):
                        raise ValueError("malformed labels")
                    if body.get("ipv4") is not None:
                        import ipaddress as _ipaddress

                        _ipaddress.IPv4Address(body["ipv4"])
                except (
                    json.JSONDecodeError,
                    ValueError,
                    TypeError,
                    AttributeError,
                ) as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                return self._reply(
                    201, api.endpoint_create(int(raw), body)
                )
            return self._reply(404, {"error": f"no route {path}"})
        except EndpointConflict as exc:
            return self._reply(409, {"error": str(exc)})
        except Exception as exc:
            return self._reply(500, {"error": str(exc)})

    def _patch_body(self):
        """Shared config-patch body parsing: JSON object with an
        optional `options` object.  Returns (body, None) or
        (None, error_reply_sent)."""
        try:
            body = json.loads(self._body() or "{}")
            if not isinstance(body, dict) or not isinstance(
                body.get("options", {}), dict
            ):
                raise ValueError("body must be an object")
            return body, False
        except (json.JSONDecodeError, ValueError) as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return None, True

    def _route_patch(self) -> None:
        api: DaemonAPI = self.server.api  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path == "/config":
                body, sent = self._patch_body()
                if sent:
                    return
                try:
                    return self._reply(200, api.config_patch(body))
                except ValueError as exc:
                    # unknown option / enforcement mode is the
                    # client's fault
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            if (
                path.startswith("/endpoint/")
                and path.endswith("/config")
            ):
                raw = path.split("/")[2]
                if not raw.isdigit():
                    return self._reply(404, {"error": "not found"})
                body, sent = self._patch_body()
                if sent:
                    return
                try:
                    return self._reply(
                        200,
                        api.endpoint_config_patch(int(raw), body),
                    )
                except KeyError as exc:
                    return self._reply(404, {"error": str(exc)})
                except ValueError as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
            return self._reply(404, {"error": f"no route {path}"})
        except Exception as exc:
            return self._reply(500, {"error": str(exc)})

    def _route_delete(self) -> None:
        api: DaemonAPI = self.server.api  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path == "/policy":
                labels = json.loads(self._body())
                return self._reply(200, api.policy_delete(labels))
            if path == "/service":
                try:
                    body = json.loads(self._body() or "{}")
                    if not isinstance(body, dict) or "frontend" not in body:
                        raise ValueError("frontend required")
                except (json.JSONDecodeError, ValueError) as exc:
                    return self._reply(
                        400, {"error": f"bad request: {exc}"}
                    )
                return self._reply(200, api.service_delete(body))
            if path == "/debug/faults":
                return self._reply(200, api.fault_disarm())
            if path.startswith("/debug/faults/"):
                site = path.split("/debug/faults/", 1)[1]
                return self._reply(200, api.fault_disarm(site))
            if path.startswith("/monitor/"):
                sid = path.split("/monitor/", 1)[1]
                return self._reply(200, api.monitor_close(sid))
            if path.startswith("/ipam/"):
                ip = path.split("/ipam/", 1)[1]
                return self._reply(200, api.ipam_release(ip))
            if path.startswith("/endpoint/"):
                raw = path.rsplit("/", 1)[1]
                if not raw.isdigit():
                    return self._reply(404, {"error": "not found"})
                name = None
                if "name=" in (self.path.partition("?")[2] or ""):
                    from urllib.parse import parse_qs

                    name = parse_qs(
                        self.path.partition("?")[2]
                    ).get("name", [None])[0]
                from cilium_tpu.daemon import EndpointConflict

                try:
                    return self._reply(
                        200,
                        api.endpoint_delete(int(raw), name),
                    )
                except EndpointConflict as exc:
                    return self._reply(409, {"error": str(exc)})
            return self._reply(404, {"error": f"no route {path}"})
        except (json.JSONDecodeError, ValueError) as exc:
            return self._reply(400, {"error": f"bad request: {exc}"})
        except Exception as exc:
            return self._reply(500, {"error": str(exc)})


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class APIServer:
    """Serve a Daemon's API on a unix socket (the cilium.sock)."""

    def __init__(self, daemon, socket_path: str) -> None:
        if os.path.exists(socket_path):
            # refuse to hijack a LIVE agent's socket; only reclaim a
            # stale one (the previous agent died without cleanup)
            import socket as _socket

            probe = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            try:
                probe.settimeout(0.5)
                probe.connect(socket_path)
                probe.close()
                raise RuntimeError(
                    f"another agent is serving on {socket_path}"
                )
            except (ConnectionRefusedError, FileNotFoundError, OSError):
                pass
            finally:
                probe.close()
            os.unlink(socket_path)
        self.socket_path = socket_path
        self.api = DaemonAPI(daemon)
        self._httpd = _UnixHTTPServer(socket_path, _Handler)
        self._httpd.api = self.api  # type: ignore
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self) -> "APIServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
