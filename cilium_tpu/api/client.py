"""REST API client over the agent's unix socket.

The analog of /root/reference/pkg/client: every CLI command and
external tool drives a RUNNING daemon through this, instead of
constructing a private in-memory one."""

from __future__ import annotations

import http.client
import json
import socket


class APIError(RuntimeError):
    """Non-2xx agent response, with the HTTP status for callers that
    branch on conflict (409) vs server condition (5xx)."""

    def __init__(self, status: int, msg: str) -> None:
        super().__init__(msg)
        self.status = status


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 30.0):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class APIClient:
    """Methods mirror api.server.DaemonAPI — the shared contract."""

    def __init__(self, socket_path: str) -> None:
        self.socket_path = socket_path

    def _request(self, method: str, path: str, body=None,
                 timeout: float = 30.0, headers=None):
        conn = _UnixHTTPConnection(self.socket_path, timeout=timeout)
        try:
            payload = None
            headers = dict(headers or {})
            if isinstance(body, bytes):
                payload = body
                headers["Content-Type"] = "application/octet-stream"
            elif body is not None:
                payload = (
                    body if isinstance(body, str) else json.dumps(body)
                )
                headers["Content-Type"] = "application/json"
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = json.loads(resp.read().decode() or "null")
            if resp.status >= 400:
                raise APIError(
                    resp.status,
                    data.get("error", f"HTTP {resp.status}")
                    if isinstance(data, dict)
                    else f"HTTP {resp.status}",
                )
            return data
        finally:
            conn.close()

    def healthz(self):
        return self._request("GET", "/healthz")

    def status(self):
        return self._request("GET", "/status")

    def config_patch(self, changes: dict):
        return self._request("PATCH", "/config", body=changes)

    def endpoint_config_patch(self, endpoint_id: int, changes: dict):
        return self._request(
            "PATCH", f"/endpoint/{endpoint_id}/config", body=changes
        )

    def config_get(self):
        return self._request("GET", "/config")

    def policy_get(self):
        return self._request("GET", "/policy")

    def policy_add(self, rules_json: str, replace: bool = False):
        path = "/policy?replace=1" if replace else "/policy"
        return self._request("POST", path, body=rules_json)

    def policy_delete(self, labels):
        return self._request("DELETE", "/policy", body=list(labels))

    def policy_resolve(self, body: dict):
        return self._request("POST", "/policy/resolve", body=body)

    def trace_tuple(self, body: dict):
        return self._request("POST", "/policy/trace-tuple", body=body)

    def policy_shadow(self, body: dict):
        """POST /policy/shadow: {"action": "arm"|"disarm"|"promote",
        "rules": [...]?, "sample_rate": f?, "seed": n?}."""
        return self._request("POST", "/policy/shadow", body=body)

    def policy_diff(self, params: dict = None):
        """GET /policy/diff (?last=N&since-seq=C): the armed shadow
        window's verdict-diff status, summary, and records."""
        from urllib.parse import urlencode

        qs = urlencode(dict(params or {}))
        path = f"/policy/diff?{qs}" if qs else "/policy/diff"
        return self._request("GET", path)

    def endpoint_list(self):
        return self._request("GET", "/endpoint")

    def endpoint_create(self, endpoint_id: int, body: dict):
        return self._request(
            "PUT", f"/endpoint/{endpoint_id}", body=body
        )

    def endpoint_delete(self, endpoint_id: int, name=None):
        path = f"/endpoint/{endpoint_id}"
        if name:
            from urllib.parse import quote

            path += f"?name={quote(name)}"
        return self._request("DELETE", path)

    def endpoint_get(self, endpoint_id: int):
        return self._request("GET", f"/endpoint/{endpoint_id}")

    def identity_list(self):
        return self._request("GET", "/identity")

    def ipcache_dump(self):
        return self._request("GET", "/ipcache")

    def service_list(self):
        return self._request("GET", "/service")

    def service_upsert(self, body: dict):
        return self._request("POST", "/service", body=body)

    def service_delete(self, body: dict):
        return self._request("DELETE", "/service", body=body)

    def ct_list(self):
        return self._request("GET", "/ct")

    def ipam_allocate(self, ip=None):
        return self._request(
            "POST", "/ipam", body={} if ip is None else {"ip": ip}
        )

    def ipam_release(self, ip: str):
        return self._request("DELETE", f"/ipam/{ip}")

    def monitor_open(self):
        return self._request("POST", "/monitor")

    def monitor_poll(self, sid: str, timeout: float = 5.0,
                     max_events: int = 1024, ack=None):
        # the HTTP socket budget must outlive the server's long-poll
        # window (clamped to 30 s server-side) or a reply carrying
        # already-dequeued events times out client-side and loses them.
        # `ack` acknowledges the previous reply's seq — an unacked
        # batch (reply lost to a hang-up) is re-delivered.
        qs = f"timeout={timeout}&max={max_events}"
        if ack is not None:
            qs += f"&ack={ack}"
        return self._request(
            "GET",
            f"/monitor/{sid}?{qs}",
            timeout=min(timeout, 30.0) + 15.0,
        )

    def monitor_close(self, sid: str):
        return self._request("DELETE", f"/monitor/{sid}")

    def flows_get(self, params: dict = None):
        """GET /flows with Hubble-like filter params; the HTTP socket
        budget outlives the server's (clamped) follow long-poll
        window, like monitor_poll — the params dict carries the
        long-poll `timeout` itself."""
        from urllib.parse import urlencode

        params = dict(params or {})
        budget = min(float(params.get("timeout", 5.0)), 30.0) + 15.0
        qs = urlencode(params)
        path = f"/flows?{qs}" if qs else "/flows"
        return self._request("GET", path, timeout=budget)

    def flows_summary(self, top: int = 10):
        return self._request("GET", f"/flows/summary?top={top}")

    def metrics_dump(self):
        return self._request("GET", "/metrics")

    # -- fault injection / serving plane -------------------------------------

    def fault_list(self):
        return self._request("GET", "/debug/faults")

    def fault_arm(self, body: dict):
        return self._request("POST", "/debug/faults", body=body)

    def fault_disarm(self, site=None):
        path = (
            f"/debug/faults/{site}" if site else "/debug/faults"
        )
        return self._request("DELETE", path)

    def process_flows(
        self, buf: bytes, traceparent=None, tenant=None,
        stream=False, deadline_ms=None,
    ):
        """POST a binary flow-record buffer through the serving
        plane; malformed buffers surface as APIError(400).
        `traceparent` (a `00-<trace>-<span>-01` string) propagates
        the caller's trace context — the reply's `trace_id` and the
        batch's spans/flow records then carry the caller's ids.
        `stream=True` submits through the CONTINUOUS serving plane
        (`?stream=1`): the daemon coalesces concurrent submissions
        into SLO-bounded device batches with per-tenant fair
        admission; `tenant` names the submitting tenant/namespace
        (stamped on flow records either way) and `deadline_ms`
        overrides the plane's default SLO for this submission."""
        from urllib.parse import urlencode

        headers = (
            {"traceparent": traceparent} if traceparent else None
        )
        params = {}
        if tenant:
            params["tenant"] = tenant
        if stream:
            params["stream"] = 1
            if deadline_ms is not None:
                params["deadline-ms"] = deadline_ms
        qs = urlencode(params)
        path = f"/datapath/flows?{qs}" if qs else "/datapath/flows"
        return self._request(
            "POST", path, body=buf, headers=headers
        )

    # -- span plane (GET /debug/traces, /debug/profile) -----------------------

    def traces_get(self, params: dict = None):
        """GET /debug/traces with the span-plane query params
        (trace-id, min-ms, site, last, slowest)."""
        from urllib.parse import urlencode

        qs = urlencode(dict(params or {}))
        path = f"/debug/traces?{qs}" if qs else "/debug/traces"
        return self._request("GET", path)

    def debug_profile(self, reset: bool = False):
        path = "/debug/profile" + ("?reset=1" if reset else "")
        return self._request("GET", path)

    def debug_perf(self, params: dict = None):
        """GET /debug/perf — the live performance plane snapshot
        (params: since=<retune cursor>, leaves=1)."""
        from urllib.parse import urlencode

        qs = urlencode(dict(params or {}))
        path = f"/debug/perf?{qs}" if qs else "/debug/perf"
        return self._request("GET", path)
