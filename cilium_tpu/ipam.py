"""Host-scope IP allocation for endpoints.

Port of /root/reference/pkg/ipam: IPs come from the node's pod
allocation CIDR (node.ipv4_alloc_cidr), first-free with explicit
reservation support; the network/broadcast and router addresses are
excluded as the reference excludes them.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional, Set


class IPAMError(ValueError):
    pass


class IPAM:
    def __init__(self, alloc_cidr: str) -> None:
        self.cidr = ipaddress.ip_network(alloc_cidr, strict=False)
        self._lock = threading.Lock()
        self._allocated: Set[int] = set()
        # network + broadcast + first host (router) excluded
        base = int(self.cidr.network_address)
        self._reserved = {base, int(self.cidr.broadcast_address), base + 1}

    def allocate(self, ip: Optional[str] = None) -> str:
        with self._lock:
            if ip is not None:
                addr = ipaddress.ip_address(ip)
                v = int(addr)
                if addr not in self.cidr:
                    raise IPAMError(f"{ip} not in {self.cidr}")
                if v in self._allocated or v in self._reserved:
                    raise IPAMError(f"{ip} already allocated")
                self._allocated.add(v)
                return str(addr)
            base = int(self.cidr.network_address)
            for v in range(base, int(self.cidr.broadcast_address) + 1):
                if v not in self._allocated and v not in self._reserved:
                    self._allocated.add(v)
                    return str(ipaddress.ip_address(v))
            raise IPAMError(f"pool {self.cidr} exhausted")

    def release(self, ip: str) -> bool:
        with self._lock:
            v = int(ipaddress.ip_address(ip))
            if v in self._allocated:
                self._allocated.remove(v)
                return True
            return False

    def in_use(self) -> int:
        with self._lock:
            return len(self._allocated)
