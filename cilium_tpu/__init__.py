"""cilium_tpu: a TPU-native policy-evaluation framework.

A ground-up re-design of Cilium's security-policy stack (reference:
/root/reference, v1.2.90) for TPU hardware: a host-side policy compiler
lowers label/identity/CIDR/L4/L7 rules into dense tensors, and a
JAX/XLA/Pallas verdict engine evaluates batched
(src_identity, dst_identity, dport, proto, l7_features) tuples with
allow/deny/redirect verdicts bit-identical to the reference semantics.

Layering (see SURVEY.md):
  labels / identity / policy.api  - the pure rule model ("what is allowed")
  policy                         - repository + resolution (control plane)
  ipcache                        - IP/CIDR -> identity resolution
  compiler                       - rules -> tensors lowering
  engine                         - jitted/Pallas verdict kernels (data plane)
  parallel                       - mesh sharding, multi-chip/multi-host eval
  runtime                        - endpoints, regeneration, kvstore, metrics
  l7                             - HTTP/Kafka/generic L7 matching
"""

__version__ = "0.1.0"
