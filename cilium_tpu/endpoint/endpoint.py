"""The Endpoint object and its policy regeneration pipeline.

Re-design of /root/reference/pkg/endpoint/{endpoint.go,policy.go}:
  - state machine (endpoint.go:227-258, SetStateLocked endpoint.go:1983
    transition matrix reproduced verbatim);
  - regeneratePolicy (policy.go:506): identity snapshot, revision-gated
    skip, ComputePolicyEnforcement (policy.go:643), resolveL4Policy,
    ResolveCIDRPolicy, computeDesiredPolicyMapState;
  - syncPolicyMap (endpoint.go:2572): desired→realized diffing, with
    per-entry counters preserved across updates.

What the reference realizes into a per-endpoint BPF map + compiled C
program, we realize into the endpoint's `realized_map_state`; the
EndpointManager lowers all realized states into one stacked
PolicyTables (manager.py) — the datapath "reload".
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional, Tuple

from cilium_tpu import option
from cilium_tpu.compiler.mapstate import (
    compute_desired_policy_map_state,
    resolve_l4_policy,
)
from cilium_tpu.identity import Identity, IdentityCache
from cilium_tpu.labels import LabelArray
from cilium_tpu.maps.policymap import (
    MapStateArrays,
    PolicyMapState,
    PolicyMapStateEntry,
    diff_map_state,
    sync_map_arrays,
)
from cilium_tpu.policy.l3 import CIDRPolicy
from cilium_tpu.policy.l4 import L4Policy
from cilium_tpu.policy.search import SearchContext

# endpoint.go:227-258
STATE_CREATING = "creating"
STATE_WAITING_FOR_IDENTITY = "waiting-for-identity"
STATE_READY = "ready"
STATE_WAITING_TO_REGENERATE = "waiting-to-regenerate"
STATE_REGENERATING = "regenerating"
STATE_DISCONNECTING = "disconnecting"
STATE_DISCONNECTED = "disconnected"
STATE_RESTORING = "restoring"

# SetStateLocked transition matrix (endpoint.go:1983-2037).
_TRANSITIONS = {
    "": {STATE_WAITING_FOR_IDENTITY, STATE_RESTORING},
    STATE_CREATING: {
        STATE_DISCONNECTING,
        STATE_WAITING_FOR_IDENTITY,
        STATE_RESTORING,
    },
    STATE_WAITING_FOR_IDENTITY: {STATE_READY, STATE_DISCONNECTING},
    STATE_READY: {
        STATE_WAITING_FOR_IDENTITY,
        STATE_DISCONNECTING,
        STATE_WAITING_TO_REGENERATE,
        STATE_RESTORING,
    },
    STATE_DISCONNECTING: {STATE_DISCONNECTED},
    STATE_DISCONNECTED: set(),
    STATE_WAITING_TO_REGENERATE: {
        STATE_WAITING_FOR_IDENTITY,
        STATE_DISCONNECTING,
        STATE_RESTORING,
    },
    STATE_REGENERATING: {
        STATE_WAITING_FOR_IDENTITY,
        STATE_DISCONNECTING,
        STATE_WAITING_TO_REGENERATE,
        STATE_RESTORING,
    },
    STATE_RESTORING: {
        STATE_DISCONNECTING,
        STATE_WAITING_TO_REGENERATE,
        STATE_RESTORING,
    },
}

# BuilderSetStateLocked (endpoint.go:2077): only the builder moves an
# endpoint into/out of regenerating.
_BUILDER_TRANSITIONS = {
    STATE_WAITING_TO_REGENERATE: {STATE_REGENERATING},
    STATE_REGENERATING: {STATE_READY, STATE_WAITING_TO_REGENERATE},
}


_ENDPOINT_NONCE = itertools.count(1)


class Endpoint:
    """pkg/endpoint.Endpoint, reduced to the policy-relevant core."""

    def __init__(
        self,
        endpoint_id: int,
        ipv4: Optional[str] = None,
        name: str = "",
    ) -> None:
        self.id = endpoint_id
        self.ipv4 = ipv4
        self.name = name
        self.state = ""
        self.security_identity: Optional[Identity] = None

        # policy computation state (endpoint.go:265 + policy.go:506)
        self.policy_revision = 0
        self.next_policy_revision = 0
        self.prev_identity_cache: Optional[IdentityCache] = None
        self.prev_universe_version: Optional[int] = None
        self.force_policy_compute = False
        # did the last regeneration change this endpoint's desired
        # policy?  (gates redirect re-resolution per sweep)
        self.last_policy_changed = True
        self.ingress_policy_enabled = False
        self.egress_policy_enabled = False
        self.desired_l4_policy: Optional[L4Policy] = None
        self.l3_policy: Optional[CIDRPolicy] = None
        self.desired_map_state: PolicyMapState = {}
        self.realized_map_state: PolicyMapState = {}
        # bumped whenever realized_map_state content changes; combined
        # with the per-instance nonce it forms the content token the
        # incremental fleet compiler keys cached rows on (the nonce
        # keeps tokens unique across endpoint re-creation with a
        # recycled id)
        self.map_state_revision = 0
        self.instance_nonce = next(_ENDPOINT_NONCE)
        self.realized_redirects: Dict[str, int] = {}

        # per-endpoint runtime options (pkg/endpoint applyOptsLocked;
        # `cilium endpoint config`): overlay on the global option set.
        # Consulted by the monitor fold (per-endpoint
        # PolicyVerdictNotification) and any per-endpoint toggles.
        from cilium_tpu.option import OptionMap

        self.opts = OptionMap()

        self.lock = threading.RLock()
        self.build_lock = threading.Lock()

    # -- state machine -------------------------------------------------------

    @staticmethod
    def _count_state_change(old: str, new: str) -> None:
        # endpoint_state gauge, kept on transitions; the reference
        # deliberately does NOT count the terminal disconnected state
        # (endpoint.go:2065-2069: "the final state, after which the
        # endpoint is gone") — counting it would grow unboundedly as
        # endpoints come and go
        from cilium_tpu.metrics import registry as metrics

        if old:  # the initial "" pseudo-state is not a series
            metrics.endpoint_state_count.dec(old)
        if new != STATE_DISCONNECTED:
            metrics.endpoint_state_count.inc(new)

    def set_state(self, to_state: str, reason: str = "") -> bool:
        """SetStateLocked (endpoint.go:1983): invalid transitions are
        skipped, not raised."""
        with self.lock:
            if to_state in _TRANSITIONS.get(self.state, set()):
                self._count_state_change(self.state, to_state)
                self.state = to_state
                return True
            return False

    def builder_set_state(self, to_state: str, reason: str = "") -> bool:
        """BuilderSetStateLocked (endpoint.go:2077)."""
        with self.lock:
            if to_state in _BUILDER_TRANSITIONS.get(self.state, set()):
                self._count_state_change(self.state, to_state)
                self.state = to_state
                return True
            return False

    # -- identity ------------------------------------------------------------

    def set_identity(self, identity: Identity) -> None:
        with self.lock:
            self.security_identity = identity

    def is_init(self) -> bool:
        """IsInit (reserved:init label present, policy.go:655)."""
        if self.security_identity is None:
            return False
        return any(
            l.source == "reserved" and l.key == "init"
            for l in self.security_identity.label_array
        )

    # -- policy computation (policy.go:506 regeneratePolicy) ----------------

    def compute_policy_enforcement(
        self, repo, rules=None
    ) -> Tuple[bool, bool]:
        """ComputePolicyEnforcement (policy.go:643)."""
        mode = option.Config.policy_enforcement
        if mode == option.ALWAYS_ENFORCE:
            return True, True
        if mode == option.DEFAULT_ENFORCEMENT:
            if self.is_init():
                return True, True
            return repo.get_rules_matching(
                self.security_identity.label_array, rules
            )
        return False, False

    def regenerate_policy(
        self,
        repo,
        identity_cache: IdentityCache,
        selector_cache=None,
        rule_index=None,
        universe_version=None,
        affected_identities=None,
        affected_revision=None,
    ) -> bool:
        """regeneratePolicy (policy.go:506).  Returns whether the
        desired state may have changed (False = revision-gated skip).

        With `universe_version` (the SelectorCache version at snapshot
        time) the identity-snapshot comparison is O(1) instead of a
        full dict compare.  With `affected_identities` (the union of
        changed rules' endpoint-selector matches) an endpoint whose
        identity is unaffected skips recomputation entirely and just
        fast-forwards its revision — the precise form of the
        reference's revision gating (policy.go:540-552): a rule can
        only change an endpoint's policy if its endpoint_selector
        selects it."""
        if self.security_identity is None:
            return False

        if universe_version is not None:
            universe_unchanged = (
                self.prev_universe_version == universe_version
            )
        else:
            # Use the previous snapshot object when contents are equal
            # (policy.go:530-533) so the skip below can compare by "is".
            if (
                self.prev_identity_cache is not None
                and self.prev_identity_cache == identity_cache
            ):
                identity_cache = self.prev_identity_cache
            universe_unchanged = identity_cache is self.prev_identity_cache

        revision = repo.get_revision()
        if (
            not self.force_policy_compute
            and self.next_policy_revision >= revision
            and universe_unchanged
        ):
            return False

        if (
            affected_identities is not None
            and universe_unchanged
            and not self.force_policy_compute
            and self.desired_l4_policy is not None
            and self.security_identity.id not in affected_identities
        ):
            # No changed rule selects this endpoint: the desired state
            # cannot have moved — realize the revision without work.
            # Fast-forward only to the revision snapshotted WITH the
            # pending-selector swap: a rule added concurrently after
            # the swap isn't in `affected_identities` and must not be
            # marked realized here.
            self.next_policy_revision = (
                min(revision, affected_revision)
                if affected_revision is not None
                else revision
            )
            return False

        self.prev_identity_cache = identity_cache
        self.prev_universe_version = universe_version
        rules = (
            rule_index.relevant(self.security_identity.id)
            if rule_index is not None
            else None
        )
        (
            self.ingress_policy_enabled,
            self.egress_policy_enabled,
        ) = self.compute_policy_enforcement(repo, rules)

        ep_labels = self.security_identity.label_array
        self.desired_l4_policy = resolve_l4_policy(
            repo,
            ep_labels,
            self.ingress_policy_enabled,
            self.egress_policy_enabled,
            rules,
        )

        # regenerateL3Policy (policy.go:392)
        new_l3 = repo.resolve_cidr_policy(
            SearchContext(to_labels=ep_labels), rules
        )
        new_l3.validate()
        self.l3_policy = new_l3

        self.desired_map_state = compute_desired_policy_map_state(
            repo,
            identity_cache,
            ep_labels,
            endpoint_id=self.id,
            ingress_enabled=self.ingress_policy_enabled,
            egress_enabled=self.egress_policy_enabled,
            realized_redirects=self.realized_redirects,
            l4_policy=self.desired_l4_policy,
            selector_cache=selector_cache,
            rules=rules,
        )

        self.force_policy_compute = False
        # When computing from a rule_index sublist, the sublist was
        # frozen when the index was built; a rule added concurrently
        # between the build and our get_revision() read is absent from
        # the sublist and must not be marked realized (the next sweep's
        # revision gate would silently skip it).  Cap at the revision
        # snapshotted with the index build.
        if rules is not None and affected_revision is not None:
            self.next_policy_revision = min(revision, affected_revision)
        else:
            self.next_policy_revision = revision
        return True

    # -- realization (endpoint.go:2572 syncPolicyMap) ------------------------

    def sync_policy_map(self) -> Tuple[int, int]:
        """Apply desired→realized delta; preserves counters of entries
        that stay.  Returns (n_added_or_updated, n_deleted)."""
        with self.lock:
            if isinstance(self.desired_map_state, MapStateArrays) or (
                isinstance(self.realized_map_state, MapStateArrays)
            ):
                # vectorized sync: counters carry over for persisting
                # keys into a FRESH instance — counter writers must
                # re-read realized_map_state under self.lock (see
                # replay.sync_counters_to_endpoints) or their
                # increments land in the superseded snapshot
                realized = MapStateArrays.from_dict(
                    self.realized_map_state
                )
                desired = MapStateArrays.from_dict(self.desired_map_state)
                new_realized, n_add, n_del = sync_map_arrays(
                    realized, desired
                )
                if n_add == 0 and n_del == 0:
                    return 0, 0
                self.realized_map_state = new_realized
                self.map_state_revision += 1
                return n_add, n_del
            to_add, to_delete = diff_map_state(
                self.realized_map_state, self.desired_map_state
            )
            if not to_add and not to_delete:
                return 0, 0
            # Copy-on-write: the fleet compiler (and any stale-table
            # consumer) may be iterating the current dict from another
            # thread; publish a fresh dict atomically instead of
            # mutating in place.
            realized = dict(self.realized_map_state)
            for key in to_delete:
                del realized[key]
            for key in to_add:
                old = realized.get(key)
                entry = PolicyMapStateEntry(
                    proxy_port=self.desired_map_state[key].proxy_port,
                    packets=old.packets if old else 0,
                    bytes=old.bytes if old else 0,
                )
                realized[key] = entry
            self.realized_map_state = realized
            # content token for the incremental fleet compiler:
            # rows relower only when this changes
            self.map_state_revision += 1
            return len(to_add), len(to_delete)

    def bump_policy_revision(self) -> None:
        """policy.go:790-804: realized revision catches up after a
        successful regeneration."""
        with self.lock:
            self.policy_revision = self.next_policy_revision
