"""Endpoint lifecycle: state machine, policy regeneration, fleet
table compilation, checkpoint/restore.

Re-design of /root/reference/pkg/endpoint + pkg/endpointmanager: the
regeneration pipeline computes desired PolicyMapState per endpoint
(the control plane, identical semantics) and realizes it as stacked
device tensors for the verdict engine (replacing per-endpoint BPF
compile+load with one fleet lowering + a double-buffered flip).
"""

from cilium_tpu.endpoint.endpoint import (
    STATE_CREATING,
    STATE_DISCONNECTED,
    STATE_DISCONNECTING,
    STATE_READY,
    STATE_REGENERATING,
    STATE_RESTORING,
    STATE_WAITING_FOR_IDENTITY,
    STATE_WAITING_TO_REGENERATE,
    Endpoint,
)
from cilium_tpu.endpoint.manager import EndpointManager

__all__ = [
    "Endpoint",
    "EndpointManager",
    "STATE_CREATING",
    "STATE_WAITING_FOR_IDENTITY",
    "STATE_READY",
    "STATE_WAITING_TO_REGENERATE",
    "STATE_REGENERATING",
    "STATE_DISCONNECTING",
    "STATE_DISCONNECTED",
    "STATE_RESTORING",
]
