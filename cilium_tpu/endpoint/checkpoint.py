"""Endpoint checkpoint / restore.

Re-design of the reference's state-dir persistence: per-endpoint JSON
(the C header file becomes the serialized realized map state — config
IS data here, not generated code) written via the current→next→failed
directory shuffle of pkg/endpoint/policy.go:738-775, and boot-time
restore (daemon/state.go restoreOldEndpoints: re-allocate identities
from labels, mark restoring, regenerate).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

from cilium_tpu.endpoint.endpoint import (
    STATE_RESTORING,
    STATE_WAITING_TO_REGENERATE,
    Endpoint,
)
from cilium_tpu.identity import IdentityAllocator
from cilium_tpu.labels import labels_from_json
from cilium_tpu.maps.policymap import (
    PolicyKey,
    PolicyMapState,
    PolicyMapStateEntry,
)

STATE_FILE = "ep_state.json"

# Checkpoint schema version — the analog of the reference's pinned-map
# schema that bpf/cilium-map-migrate.c migrates on upgrade (init.sh
# runs it before the agent attaches).  History:
#   0: round-1 shape — no version stamp, no realized_redirects, map
#      entries without packets/bytes counters;
#   1: adds the explicit version stamp, realized_redirects, and
#      per-entry packets/bytes;
#   2: adds per-endpoint runtime options ("opts" — `cilium endpoint
#      config` state, which is compiled datapath state in the
#      reference and must survive restarts).
# A checkpoint newer than SCHEMA_VERSION is NOT restored (a downgraded
# agent must not guess at fields it does not know), mirroring
# map-migrate refusing unknown map properties.
SCHEMA_VERSION = 2

# version k → pure doc→doc migration producing version k+1
_MIGRATIONS = {}


def _migration(frm: int):
    def register(fn):
        _MIGRATIONS[frm] = fn
        return fn

    return register


@_migration(0)
def _v0_to_v1(doc: dict) -> dict:
    """Round-1 checkpoints: stamp the version, default the fields
    later rounds added (redirects; per-entry counters)."""
    doc = dict(doc)
    doc["version"] = 1
    doc.setdefault("realized_redirects", {})
    doc["realized_map_state"] = [
        {**{"packets": 0, "bytes": 0}, **item}
        for item in doc.get("realized_map_state", [])
    ]
    return doc


@_migration(1)
def _v1_to_v2(doc: dict) -> dict:
    doc = dict(doc)
    doc["version"] = 2
    doc.setdefault("opts", {})
    return doc


class CheckpointTooNew(ValueError):
    """Checkpoint written by a NEWER framework version."""


def migrate_doc(doc: dict) -> dict:
    """Apply registered migrations until the doc reaches
    SCHEMA_VERSION (missing stamp ⇒ version 0)."""
    version = int(doc.get("version", 0))
    if version > SCHEMA_VERSION:
        raise CheckpointTooNew(
            f"checkpoint version {version} > supported "
            f"{SCHEMA_VERSION}"
        )
    while version < SCHEMA_VERSION:
        fn = _MIGRATIONS.get(version)
        if fn is None:
            raise ValueError(
                f"no migration registered from version {version}"
            )
        doc = fn(doc)
        version = int(doc["version"])
    return doc


def migrate_state_dir(state_dir: str) -> int:
    """Rewrite old-version checkpoints in place (the init.sh
    map-migrate moment: run once at boot, BEFORE restore).  Returns
    the number migrated; too-new or unparseable files are left
    untouched for the operator."""
    migrated = 0
    if not os.path.isdir(state_dir):
        return 0
    for entry in sorted(os.listdir(state_dir)):
        path = os.path.join(state_dir, entry, STATE_FILE)
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (ValueError, json.JSONDecodeError):
            continue
        if int(doc.get("version", 0)) == SCHEMA_VERSION:
            continue
        try:
            doc = migrate_doc(doc)
        except (CheckpointTooNew, ValueError):
            continue
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), prefix=".tmp_migrate"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            migrated += 1
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return migrated


def _map_state_to_json(state: PolicyMapState) -> list:
    return [
        {
            "identity": k.identity,
            "dest_port": k.dest_port,
            "nexthdr": k.nexthdr,
            "dir": k.traffic_direction,
            "proxy_port": v.proxy_port,
            "packets": v.packets,
            "bytes": v.bytes,
        }
        for k, v in state.items()
    ]


def _map_state_from_json(items: list) -> PolicyMapState:
    return {
        PolicyKey(
            item["identity"], item["dest_port"], item["nexthdr"], item["dir"]
        ): PolicyMapStateEntry(
            proxy_port=item["proxy_port"],
            packets=item.get("packets", 0),
            bytes=item.get("bytes", 0),
        )
        for item in items
    }


def save_endpoint(endpoint: Endpoint, state_dir: str) -> str:
    """Write <state_dir>/<ep id>/ep_state.json atomically (write to a
    temp file, rename — the reference's directory-shuffle transaction
    reduced to a file swap)."""
    ep_dir = os.path.join(state_dir, str(endpoint.id))
    os.makedirs(ep_dir, exist_ok=True)
    doc = {
        "version": SCHEMA_VERSION,
        "id": endpoint.id,
        "name": endpoint.name,
        "ipv4": endpoint.ipv4,
        "labels": (
            [
                {"key": l.key, "value": l.value, "source": l.source}
                for l in endpoint.security_identity.labels.values()
            ]
            if endpoint.security_identity
            else []
        ),
        "policy_revision": endpoint.policy_revision,
        "realized_map_state": _map_state_to_json(
            endpoint.realized_map_state
        ),
        "realized_redirects": endpoint.realized_redirects,
        "opts": dict(endpoint.opts),
    }
    fd, tmp = tempfile.mkstemp(dir=ep_dir, prefix=".tmp_state")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(ep_dir, STATE_FILE))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return os.path.join(ep_dir, STATE_FILE)


def restore_endpoints(
    state_dir: str, allocator: Optional[IdentityAllocator] = None
) -> List[Endpoint]:
    """restoreOldEndpoints (daemon/state.go): parse the state dir,
    re-allocate identities from the checkpointed labels (ids may
    change across restarts — the labels are the durable key), mark
    restoring → waiting-to-regenerate.  Unparseable directories are
    skipped, as the reference skips and logs."""
    endpoints: List[Endpoint] = []
    if not os.path.isdir(state_dir):
        return endpoints
    for entry in sorted(os.listdir(state_dir)):
        path = os.path.join(state_dir, entry, STATE_FILE)
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            doc = migrate_doc(doc)
            endpoint = Endpoint(
                endpoint_id=int(doc["id"]),
                ipv4=doc.get("ipv4"),
                name=doc.get("name", ""),
            )
            endpoint.set_state(STATE_RESTORING, "restoring")
            # policy_revision round-trips for observability only; the
            # regeneration gate reads next_policy_revision, which is
            # deliberately NOT restored — a fresh daemon regenerates
            # restored endpoints unconditionally (daemon/state.go
            # regenerateRestoredEndpoints), since the checkpointed
            # revision belongs to the old daemon's repo numbering
            endpoint.policy_revision = doc.get("policy_revision", 0)
            endpoint.realized_map_state = _map_state_from_json(
                doc.get("realized_map_state", [])
            )
            endpoint.realized_redirects = dict(
                doc.get("realized_redirects", {})
            )
            endpoint.opts.update(
                {
                    k: bool(v)
                    for k, v in doc.get("opts", {}).items()
                }
            )
            if allocator is not None and doc.get("labels"):
                ident, _ = allocator.allocate(
                    labels_from_json(doc["labels"])
                )
                endpoint.set_identity(ident)
            endpoint.set_state(
                STATE_WAITING_TO_REGENERATE, "restored"
            )
            endpoints.append(endpoint)
        except (ValueError, KeyError, json.JSONDecodeError):
            # includes CheckpointTooNew (a ValueError): a downgraded
            # agent must not guess at unknown fields
            continue
    return endpoints
