"""Endpoint checkpoint / restore.

Re-design of the reference's state-dir persistence: per-endpoint JSON
(the C header file becomes the serialized realized map state — config
IS data here, not generated code) written via the current→next→failed
directory shuffle of pkg/endpoint/policy.go:738-775, and boot-time
restore (daemon/state.go restoreOldEndpoints: re-allocate identities
from labels, mark restoring, regenerate).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

from cilium_tpu.endpoint.endpoint import (
    STATE_RESTORING,
    STATE_WAITING_TO_REGENERATE,
    Endpoint,
)
from cilium_tpu.identity import IdentityAllocator
from cilium_tpu.labels import Label, Labels
from cilium_tpu.maps.policymap import (
    PolicyKey,
    PolicyMapState,
    PolicyMapStateEntry,
)

STATE_FILE = "ep_state.json"


def _map_state_to_json(state: PolicyMapState) -> list:
    return [
        {
            "identity": k.identity,
            "dest_port": k.dest_port,
            "nexthdr": k.nexthdr,
            "dir": k.traffic_direction,
            "proxy_port": v.proxy_port,
            "packets": v.packets,
            "bytes": v.bytes,
        }
        for k, v in state.items()
    ]


def _map_state_from_json(items: list) -> PolicyMapState:
    return {
        PolicyKey(
            item["identity"], item["dest_port"], item["nexthdr"], item["dir"]
        ): PolicyMapStateEntry(
            proxy_port=item["proxy_port"],
            packets=item.get("packets", 0),
            bytes=item.get("bytes", 0),
        )
        for item in items
    }


def save_endpoint(endpoint: Endpoint, state_dir: str) -> str:
    """Write <state_dir>/<ep id>/ep_state.json atomically (write to a
    temp file, rename — the reference's directory-shuffle transaction
    reduced to a file swap)."""
    ep_dir = os.path.join(state_dir, str(endpoint.id))
    os.makedirs(ep_dir, exist_ok=True)
    doc = {
        "id": endpoint.id,
        "name": endpoint.name,
        "ipv4": endpoint.ipv4,
        "labels": (
            [
                {"key": l.key, "value": l.value, "source": l.source}
                for l in endpoint.security_identity.labels.values()
            ]
            if endpoint.security_identity
            else []
        ),
        "policy_revision": endpoint.policy_revision,
        "realized_map_state": _map_state_to_json(
            endpoint.realized_map_state
        ),
        "realized_redirects": endpoint.realized_redirects,
    }
    fd, tmp = tempfile.mkstemp(dir=ep_dir, prefix=".tmp_state")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(ep_dir, STATE_FILE))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return os.path.join(ep_dir, STATE_FILE)


def restore_endpoints(
    state_dir: str, allocator: Optional[IdentityAllocator] = None
) -> List[Endpoint]:
    """restoreOldEndpoints (daemon/state.go): parse the state dir,
    re-allocate identities from the checkpointed labels (ids may
    change across restarts — the labels are the durable key), mark
    restoring → waiting-to-regenerate.  Unparseable directories are
    skipped, as the reference skips and logs."""
    endpoints: List[Endpoint] = []
    if not os.path.isdir(state_dir):
        return endpoints
    for entry in sorted(os.listdir(state_dir)):
        path = os.path.join(state_dir, entry, STATE_FILE)
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            endpoint = Endpoint(
                endpoint_id=int(doc["id"]),
                ipv4=doc.get("ipv4"),
                name=doc.get("name", ""),
            )
            endpoint.set_state(STATE_RESTORING, "restoring")
            # policy_revision round-trips for observability only; the
            # regeneration gate reads next_policy_revision, which is
            # deliberately NOT restored — a fresh daemon regenerates
            # restored endpoints unconditionally (daemon/state.go
            # regenerateRestoredEndpoints), since the checkpointed
            # revision belongs to the old daemon's repo numbering
            endpoint.policy_revision = doc.get("policy_revision", 0)
            endpoint.realized_map_state = _map_state_from_json(
                doc.get("realized_map_state", [])
            )
            endpoint.realized_redirects = dict(
                doc.get("realized_redirects", {})
            )
            if allocator is not None and doc.get("labels"):
                labels = Labels(
                    {
                        item["key"]: Label(
                            key=item["key"],
                            value=item.get("value", ""),
                            source=item.get("source", "unspec"),
                        )
                        for item in doc["labels"]
                    }
                )
                ident, _ = allocator.allocate(labels)
                endpoint.set_identity(ident)
            endpoint.set_state(
                STATE_WAITING_TO_REGENERATE, "restored"
            )
            endpoints.append(endpoint)
        except (ValueError, KeyError, json.JSONDecodeError):
            continue
    return endpoints
