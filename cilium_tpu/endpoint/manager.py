"""Endpoint registry, parallel build queue, fleet table compilation.

Re-design of /root/reference/pkg/endpointmanager/manager.go (registry,
RegenerateAllEndpoints manager.go:271) and the daemon's builder pool
(daemon/daemon.go:209 QueueEndpointBuild, daemon.go:235
StartEndpointBuilders: builds serialize per endpoint via the build
lock, N run in parallel fleet-wide).

The TPU twist: realization is fleet-wide — after endpoints sync their
realized map states, `compile_fleet` lowers ALL of them into one
stacked PolicyTables (the endpoint axis replaces per-endpoint BPF
programs + the tail-call PROG_ARRAY) and publishes it with a
double-buffered version flip, the device analog of the realized/
backup/pending map shuffle in pkg/datapath/ipcache/listener.go:167.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Callable, Dict, List, Optional, Tuple

from cilium_tpu import logging as logfields
from cilium_tpu.compiler.tables import FleetCompiler, PolicyTables
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.endpoint.endpoint import (
    STATE_READY,
    STATE_REGENERATING,
    STATE_WAITING_TO_REGENERATE,
    Endpoint,
)
from cilium_tpu.identity import IdentityCache

log = get_logger("endpoint-manager")


class EndpointManager:
    """pkg/endpointmanager: lookup by id / name / IP + regeneration."""

    def __init__(self, num_workers: int = 4) -> None:
        self._lock = threading.RLock()
        self.by_id: Dict[int, Endpoint] = {}
        self.by_ip: Dict[str, Endpoint] = {}
        self.by_name: Dict[str, Endpoint] = {}
        self._pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
        # published tables: (version, tables, ep_id → endpoint axis idx)
        self._published: Tuple[int, Optional[PolicyTables], Dict[int, int]] = (
            0,
            None,
            {},
        )
        # incremental lowering: caches identity/slot tables and
        # per-endpoint rows across publishes (delta compilation)
        self._fleet_compiler = FleetCompiler()
        # device-resident table epochs (engine/publish.py): created
        # lazily on the first published_device() call so control-plane
        #-only users never pay a device upload; publishes after that
        # apply delta scatters instead of re-uploading the world
        self._device_store = None
        self._device_lock = threading.RLock()
        # optional store factory (e.g. engine.sharded's
        # make_partitioned_store bound to a mesh): set before the
        # first published_device() call to serve the daemon's
        # dispatch from identity-SHARDED epochs — the same delta
        # publish path then scatters each payload into the owning
        # chip's shard only
        self.device_store_factory = None
        self.last_publish_stats = None
        # optional listener fired after every device-epoch publish
        # with the HOST tables just installed: the daemon wires the
        # attached ChipFailoverRouter here so the router's published
        # tables track regenerates AUTOMATICALLY (no operator
        # publish).  Called outside the manager's main lock but
        # under the device lock; must not call back into
        # published_device.
        self.on_device_publish = None
        # builder failure bookkeeping (endpoint.go's bpf.go:442 retry
        # counter analog): (endpoint_id, reason, repr(exc)) of the
        # most recent failed builds, surfaced via daemon status
        self.build_failures = 0
        self.last_build_failures: List[Tuple[int, str, str]] = []

    # -- registry (manager.go Insert/Lookup*) --------------------------------

    def insert(self, endpoint: Endpoint) -> None:
        with self._lock:
            self.by_id[endpoint.id] = endpoint
            if endpoint.ipv4:
                self.by_ip[endpoint.ipv4] = endpoint
            if endpoint.name:
                self.by_name[endpoint.name] = endpoint

    def remove(self, endpoint: Endpoint) -> None:
        with self._lock:
            self.by_id.pop(endpoint.id, None)
            if endpoint.ipv4:
                self.by_ip.pop(endpoint.ipv4, None)
            if endpoint.name:
                self.by_name.pop(endpoint.name, None)

    def lookup(self, endpoint_id: int) -> Optional[Endpoint]:
        with self._lock:
            return self.by_id.get(endpoint_id)

    def lookup_name(self, name: str) -> Optional[Endpoint]:
        with self._lock:
            return self.by_name.get(name)

    def lookup_ip(self, ipv4: str) -> Optional[Endpoint]:
        with self._lock:
            return self.by_ip.get(ipv4)

    def endpoints(self) -> List[Endpoint]:
        with self._lock:
            return list(self.by_id.values())

    # -- regeneration (manager.go:271 RegenerateAllEndpoints) ---------------

    def regenerate_endpoint(
        self,
        endpoint: Endpoint,
        repo,
        identity_cache: IdentityCache,
        selector_cache=None,
        rule_index=None,
        universe_version=None,
        affected_identities=None,
        affected_revision=None,
    ) -> bool:
        """One build: the regenerate→regenerateBPF tail of §3.2 (CT
        scrub and proxy ACKs are owned by their subsystems; here:
        policy calc + map sync + revision bump).  Serialized per
        endpoint via build_lock (QueueEndpointBuild daemon.go:209)."""
        with endpoint.build_lock:
            if not endpoint.builder_set_state(
                STATE_REGENERATING, "regenerating"
            ):
                # not queued for regeneration (e.g. disconnecting)
                return False
            metrics.endpoint_count_regenerating.inc()
            try:
                changed = endpoint.regenerate_policy(
                    repo,
                    identity_cache,
                    selector_cache=selector_cache,
                    rule_index=rule_index,
                    universe_version=universe_version,
                    affected_identities=affected_identities,
                    affected_revision=affected_revision,
                )
                endpoint.last_policy_changed = bool(changed)
                if changed:
                    endpoint.sync_policy_map()
                endpoint.bump_policy_revision()
                endpoint.builder_set_state(STATE_READY, "regenerated")
                return True
            except Exception:
                # failed builds fall back to waiting-to-regenerate
                # (policy.go:770-775 keeps old state, retries later)
                endpoint.builder_set_state(
                    STATE_WAITING_TO_REGENERATE, "regeneration failed"
                )
                raise
            finally:
                metrics.endpoint_count_regenerating.dec()

    def regenerate_all(
        self,
        repo,
        identity_cache: IdentityCache,
        reason: str = "",
        selector_cache=None,
        rule_index=None,
        universe_version=None,
        affected_identities=None,
        affected_revision=None,
        identity_cache_token=None,
    ) -> int:
        """RegenerateAllEndpoints: mark + rebuild every endpoint (N
        builders in parallel), then publish fresh fleet tables."""
        eps = self.endpoints()
        for endpoint in eps:
            endpoint.set_state(STATE_WAITING_TO_REGENERATE, reason)
        futures = [
            self._pool.submit(
                self.regenerate_endpoint,
                endpoint,
                repo,
                identity_cache,
                selector_cache,
                rule_index,
                universe_version,
                affected_identities,
                affected_revision,
            )
            for endpoint in eps
        ]
        wait(futures)
        n = 0
        failures = []
        for endpoint, f in zip(eps, futures):
            exc = f.exception()
            if exc is None:
                n += 1 if f.result() else 0
            else:
                failures.append((endpoint.id, reason, repr(exc)))
        metrics.endpoint_regenerations.inc("success", value=n)
        if failures:
            # a failed build must be LOUD, not a swallowed pool
            # exception: count it, keep the last batch for status,
            # and log — the endpoint itself already fell back to
            # waiting-to-regenerate inside regenerate_endpoint
            for ep_id, rsn, err in failures:
                metrics.endpoint_regenerations.inc("fail")
                log.error(
                    "endpoint build failed",
                    extra={"fields": {
                        logfields.ENDPOINT_ID: ep_id,
                        "reason": rsn,
                        "error": err,
                    }},
                )
            with self._lock:
                self.build_failures += len(failures)
                self.last_build_failures = failures
        self.publish_tables(
            identity_cache, universe_token=identity_cache_token
        )
        return n

    # -- fleet realization ---------------------------------------------------

    def compile_fleet(
        self, identity_cache: IdentityCache
    ) -> Tuple[PolicyTables, Dict[int, int]]:
        """Lower every endpoint's REALIZED map state into one stacked
        PolicyTables; returns (tables, ep_id → endpoint-axis index).

        Incremental: unchanged endpoints (by map_state_revision) reuse
        their cached rows; identity/slot tables rebuild only when the
        universe or key set changes (SURVEY §7 hard part 4)."""
        return self._fleet_compiler.compile(
            self._capture_entries(), list(identity_cache)
        )

    def _capture_entries(self) -> list:
        """Per-endpoint (id, realized state, cache token) snapshot.
        (state, token) must be read atomically: sync_policy_map
        publishes a fresh dict and bumps the revision under the same
        lock; pairing a new dict with an old token would wrongly
        reuse cached rows."""
        entries = []
        for e in sorted(self.endpoints(), key=lambda ep: ep.id):
            with e.lock:
                entries.append(
                    (
                        e.id,
                        e.realized_map_state,
                        (e.instance_nonce, e.map_state_revision),
                    )
                )
        return entries

    def publish_tables(
        self,
        identity_cache: IdentityCache,
        universe_token=None,
    ) -> int:
        """Double-buffered flip: compile the new version, then swap the
        published pointer atomically (consumers holding the old tables
        keep a consistent snapshot — the ACK-gated versioned flip of
        SURVEY §5).

        `universe_token` is the identity-allocator version stamp of
        `identity_cache` (see FleetCompiler.compile): matching tokens
        skip the O(universe) identity diff inside the compiler.

        The EXACT map states the tables were compiled from are
        published alongside (endpoint-axis order): the daemon's
        degraded host fold evaluates against these, so its verdicts
        stay bit-identical to the device tables no matter what
        regenerations land mid-stream."""
        entries = self._capture_entries()
        tables, index = self._fleet_compiler.compile(
            entries, list(identity_cache), universe_token=universe_token
        )
        states_by_id = {eid: state for eid, state, _ in entries}
        states: list = [None] * (max(index.values(), default=-1) + 1)
        for ep_id, idx in index.items():
            states[idx] = states_by_id.get(ep_id)
        with self._lock:
            # retain the outgoing publish (the world the standby
            # epoch slot still holds after the flip): the shadow
            # plane's standby-arm source.  Valid for exactly one
            # further publish — the compiler's ping-pong reuses the
            # buffers after that, which is why the shadow plane
            # HOST-COPIES these arrays at arm time and closes the
            # window stale the moment the live stamp moves again.
            prev_version, prev_tables, prev_index = self._published
            if prev_tables is not None:
                self._previous_published = (
                    prev_version,
                    prev_tables,
                    prev_index,
                    getattr(self, "_published_states", []),
                )
            version = prev_version + 1
            self._published = (version, tables, index)
            self._published_states = states
            return version

    def published(self) -> Tuple[int, Optional[PolicyTables], Dict[int, int]]:
        with self._lock:
            return self._published

    def published_with_states(self):
        """(version, tables, index, states) read under ONE lock —
        `states` is the per-axis realized-map-state snapshot the
        published tables were compiled from (the host fold's
        substrate)."""
        with self._lock:
            version, tables, index = self._published
            return version, tables, index, getattr(
                self, "_published_states", []
            )

    def published_previous(self):
        """The PREVIOUS publish — (version, tables, index, states) of
        the world the standby epoch slot held before the last flip,
        or None.  One-publish-deep by construction (the compiler's
        ping-pong buffer pair): the shadow plane copies what it needs
        at arm time and stamp-guards everything after."""
        with self._lock:
            return getattr(self, "_previous_published", None)

    # -- device-resident epochs (engine/publish.py) ---------------------------

    def _ensure_device_store(self):
        from cilium_tpu.engine.publish import DeviceTableStore

        with self._device_lock:
            if self._device_store is None:
                factory = self.device_store_factory
                self._device_store = (
                    factory() if factory is not None
                    else DeviceTableStore()
                )
            return self._device_store

    def published_device(self):
        """(version, device-epoch PolicyTables, index): the published
        tables RESIDENT on device.  The first call pays a full upload;
        later calls return the live epoch, and a publish that landed
        since is installed as a delta-scoped scatter into the standby
        epoch (FleetCompiler.delta_for) — in-flight batches finish on
        the previous epoch untouched."""
        with self._lock:
            version, tables, index = self._published
        if tables is None:
            return version, None, index
        return version, self._device_tables(tables), index

    def delta_for(self, base_stamp, tables):
        """TableDelta from `base_stamp` to `tables`
        (FleetCompiler.delta_for passthrough) — lets a SECOND device
        store (the failover router's replica store) compute its own
        delta against ITS standby epoch's stamp instead of reusing
        the manager store's delta, whose base differs."""
        return self._fleet_compiler.delta_for(base_stamp, tables)

    def device_tables_for(self, tables):
        """Device-resident epoch for an EXACT published host snapshot
        (the daemon's serving path reads tables + host states under
        one lock and must dispatch against those same tables);
        installs it into the store when not yet resident."""
        return self._device_tables(tables)

    def _device_tables(self, tables):
        import numpy as np

        store = self._ensure_device_store()
        stamp = int(np.asarray(tables.generation))
        with self._device_lock:
            got = store.get(stamp)
            if got is not None:
                return got
            delta = self._fleet_compiler.delta_for(
                store.spare_stamp(), tables
            )
            dev, stats = store.publish(tables, delta)
            self.last_publish_stats = stats
            metrics.table_publish_total.inc(stats.mode)
            metrics.table_publish_bytes.inc(
                stats.mode, value=stats.bytes_h2d
            )
            metrics.table_publish_seconds.set(value=stats.seconds)
            listener = self.on_device_publish
            if listener is not None:
                try:
                    listener(tables)
                except Exception as exc:  # noqa: BLE001 — a router
                    # sync failure must not take down the publish
                    log.warning(
                        "on_device_publish listener failed",
                        extra={"fields": {"error": str(exc)}},
                    )
            log.info(
                "device table epoch published",
                extra={"fields": {
                    "epoch": stats.epoch,
                    "mode": stats.mode,
                    "bytes_h2d": stats.bytes_h2d,
                    "seconds": round(stats.seconds, 4),
                }},
            )
            return dev

    def build_failure_snapshot(self) -> Tuple[int, List[Tuple[int, str, str]]]:
        """(total count, last batch) read atomically — the two fields
        are updated together under the manager lock."""
        with self._lock:
            return self.build_failures, list(self.last_build_failures)

    def check_tables_current(self, tables) -> None:
        """Raises if `tables` is no longer a valid snapshot: a HOST
        compile more than one publish old (its stacked buffers have
        been reused — FleetCompiler.check_tables_current), or a device
        epoch that is no longer one of the two LIVE epochs (its
        buffers were donated to a newer publish)."""
        import numpy as np

        store = self._device_store
        if store is not None:
            raw = getattr(tables, "generation", None)
            stamp = int(np.asarray(raw)) if raw is not None else 0
            if stamp:
                if store.holds(tables):
                    return
                if (stamp >> 32) == 0 and store.live_stamps():
                    # a device round trip without x64 truncates the
                    # stamp to the publish counter — the store owns
                    # the staleness verdict for such tables
                    store.check_current(tables)
                    return
        self._fleet_compiler.check_tables_current(tables)

    def identity_index(self) -> Tuple[Dict[int, int], int]:
        """Identity index space of the (last-compiled) fleet tables —
        see FleetCompiler.identity_index."""
        return self._fleet_compiler.identity_index()
