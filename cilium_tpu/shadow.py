"""Shadow policy rollout: dual-epoch evaluation with live verdict-diff
canarying.

An operator changing a CiliumNetworkPolicy today learns what it *did*
only after cutover, from flow records.  This plane turns the epoch
double-buffer into a policy-CI surface: while a SHADOW world is armed,
the daemon samples live batches and dispatches them against BOTH
worlds — the second gather rides the same staged batch (the TupleBatch
is already device-resident; only the table gathers repeat) — then
diffs all verdict columns and folds per-column / per-transition change
counters, with every re-verdicted tuple captured as a diff record in a
bounded ring.  `cilium-tpu policy diff --live` shows exactly which
flows a pending change would re-verdict, on device, at line rate,
BEFORE cutover.

Two ways to arm a window:

  * **candidate** (`POST /policy/shadow {"action": "arm", "rules":
    [...]}`): the candidate rules are compiled into a full shadow
    world against the LIVE identity universe and endpoint set — the
    what-if form.  ``promote`` installs the candidate through the
    normal policy path (``policy_add(replace=True)``) and closes the
    window with its counters zeroed.
  * **standby** (no rules): the shadow world is the PREVIOUS publish —
    the world still held by the standby epoch slot after the last
    cutover — so the diff reads "what did my last change re-verdict"
    retroactively.  Nothing to promote in this mode.

Stamp-guard contract (the dual-epoch seam): arming pins the pair
(live generation, shadow generation).  Every sampled dispatch verifies
the batch's tables still carry the pinned live stamp; any publish that
moves the live world closes the window with an explicit ``stale``
status — a diff can never silently span a third world.  A shadow
dispatch already in flight across a concurrent publish either folds
against its pinned stamps (window still open at drain) or is REFUSED
cleanly (``policy_diff_refused_total``) — never half-world-diffed.
Sample accounting is exactly-once: ``policy_diff_sampled_total``
counts only folded samples, each ticket folds or refuses exactly once.

Device-residency cost: the shadow world is placed as ONE extra epoch
(a `device_put` at first sample; a replica-store publish on the routed
path).  The per-batch marginal cost is only the second table gather —
the staged batch, the H2D upload, the event/flow folds are all shared
with the live dispatch.

Simulation boundary: on this 2-CPU container the "device" is XLA's
CPU backend — `shadow_eval_overhead_pct` absolutes read on real
hardware; what the tier-1 suite pins here is the semantics
(bit-identity of the sampled diff to the host oracle's diff of the
two worlds, exactly-once accounting, stamp-guarded staleness).
"""

from __future__ import annotations

import threading
import time
from collections import Counter as _Counter
from collections import deque
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

import numpy as np

from cilium_tpu import tracing
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics

log = get_logger("shadow")

# diff transition codes (the device diff kernel's per-row output)
TRANS_NONE = 0
TRANS_ALLOW_TO_DENY = 1
TRANS_DENY_TO_ALLOW = 2
TRANS_CHANGED = 3  # verdict kept, match_kind/proxy_port moved

TRANS_NAMES = {
    TRANS_NONE: "",
    TRANS_ALLOW_TO_DENY: "allow_to_deny",
    TRANS_DENY_TO_ALLOW: "deny_to_allow",
    TRANS_CHANGED: "changed",
}

_DIRECTION_NAMES = {0: "INGRESS", 1: "EGRESS"}

# the verdict columns the diff covers — every column the lattice
# dispatch returns (engine.verdict.Verdicts)
DIFF_COLUMNS = ("allowed", "proxy_port", "match_kind")


def diff_codes(
    live_allowed,
    live_proxy,
    live_kind,
    shadow_allowed,
    shadow_proxy,
    shadow_kind,
    xp=np,
):
    """The ONE diff definition both the jitted device kernel and the
    host oracle comparisons share (the telemetry_masks pattern):
    per-row changed flags per verdict column plus a transition code.
    ``xp`` is numpy or jax.numpy."""
    ca = live_allowed.astype(xp.int32) != shadow_allowed.astype(
        xp.int32
    )
    cp = live_proxy.astype(xp.int32) != shadow_proxy.astype(xp.int32)
    ck = live_kind.astype(xp.int32) != shadow_kind.astype(xp.int32)
    a2d = ca & (live_allowed.astype(xp.int32) != 0)
    d2a = ca & (live_allowed.astype(xp.int32) == 0)
    trans = xp.where(
        a2d,
        xp.int32(TRANS_ALLOW_TO_DENY),
        xp.where(
            d2a,
            xp.int32(TRANS_DENY_TO_ALLOW),
            xp.where(
                cp | ck,
                xp.int32(TRANS_CHANGED),
                xp.int32(TRANS_NONE),
            ),
        ),
    )
    return (
        ca.astype(xp.uint8),
        cp.astype(xp.uint8),
        ck.astype(xp.uint8),
        trans.astype(xp.uint8),
    )


@dataclass
class DiffRecord:
    """One re-verdicted tuple of an armed shadow window (the changed
    row's old/new verdict pair, Hubble-oriented identities, and the
    drop-reason transition an operator greps for)."""

    ts: float
    ep_id: int
    src_identity: int
    dst_identity: int
    dport: int
    proto: int
    direction: int  # 0=ingress 1=egress
    live_allowed: bool
    shadow_allowed: bool
    live_match_kind: int
    shadow_match_kind: int
    live_proxy_port: int
    shadow_proxy_port: int
    transition: str  # allow_to_deny | deny_to_allow | changed
    live_reason: str = ""  # canonical drop reason ("" = forwarded)
    shadow_reason: str = ""
    tenant: str = ""
    trace_id: str = ""
    seq: int = 0

    def to_dict(self) -> dict:
        d = asdict(self)
        d["direction"] = _DIRECTION_NAMES.get(
            self.direction, str(self.direction)
        )
        return d


def _drop_reason_of(allowed, kind) -> str:
    """Canonical reason name of a denied lattice verdict — the SAME
    classification the flow plane applies (telemetry's policy/frag
    split; the audit path has no prefilter column)."""
    from cilium_tpu.engine.oracle import MATCH_FRAG_DROP
    from cilium_tpu.telemetry import (
        DROP_COLUMN_REASONS,
        TELEM_DROP_FRAG,
        TELEM_DROP_POLICY,
    )

    if allowed:
        return ""
    return DROP_COLUMN_REASONS[
        TELEM_DROP_FRAG if kind == MATCH_FRAG_DROP else TELEM_DROP_POLICY
    ]


def _norm_stamp(gen) -> int:
    """Normalize a table generation to the store-scoped publish
    counter bits (a device round trip without x64 truncates to u32 —
    the engine.publish._norm convention)."""
    return int(np.asarray(gen)) & 0xFFFFFFFF


def compile_candidate_world(daemon, rules):
    """Compile candidate rules into a full shadow world against the
    LIVE identity universe and endpoint set, without touching any
    live daemon state: live rules with same-labeled rules replaced by
    the candidates (the ``policy_add(replace=True)`` semantics a
    later promote applies), lowered per endpoint through the same
    ``compute_desired_policy_map_state`` the real regeneration path
    runs, stacked by a FRESH FleetCompiler.

    Returns (tables, index, states) with ``index`` guaranteed equal
    to the live published index (same endpoint axis — the diff
    dispatches ONE staged batch against both worlds).

    Boundary: candidate rules are resolved against the live identity
    universe — CIDR selectors match only already-allocated prefix
    identities, and L7 redirects not already realized on an endpoint
    surface with proxy_port 0 (the reference defers them to port
    allocation at real cutover).  Both are exactly what an operator
    wants answered pre-cutover: how does THIS world's traffic
    re-verdict."""
    from cilium_tpu.compiler.mapstate import (
        compute_desired_policy_map_state,
        resolve_l4_policy,
    )
    from cilium_tpu.compiler.selectorcache import SelectorCache
    from cilium_tpu.compiler.tables import FleetCompiler
    from cilium_tpu.policy.repository import Repository

    with daemon.lock:
        live_rules = [pr.rule for pr in daemon.repo.rules]
    keep = list(live_rules)
    for cand in rules:
        keep = [
            r for r in keep if not r.labels.contains(cand.labels)
        ]
    repo2 = Repository()
    repo2.add_list(keep + list(rules))
    cache, _ = daemon.identity_allocator.identity_cache_versioned()
    sc = SelectorCache()
    sc.sync(cache)
    entries = []
    eps = sorted(
        daemon.endpoint_manager.endpoints(), key=lambda e: e.id
    )
    for i, ep in enumerate(eps):
        if ep.security_identity is None:
            entries.append((ep.id, {}, ("shadow", i)))
            continue
        ep_labels = ep.security_identity.label_array
        ing, eg = ep.compute_policy_enforcement(repo2)
        l4 = resolve_l4_policy(repo2, ep_labels, ing, eg)
        state = compute_desired_policy_map_state(
            repo2,
            cache,
            ep_labels,
            endpoint_id=ep.id,
            ingress_enabled=ing,
            egress_enabled=eg,
            realized_redirects=dict(ep.realized_redirects),
            l4_policy=l4,
            selector_cache=sc,
        )
        entries.append((ep.id, state, ("shadow", i)))
    tables, index = FleetCompiler().compile(entries, list(cache))
    states_by_id = {eid: st for eid, st, _ in entries}
    states: list = [None] * (max(index.values(), default=-1) + 1)
    for ep_id, idx in index.items():
        states[idx] = states_by_id.get(ep_id)
    return tables, index, states


class ShadowPlane:
    """The daemon's shadow-evaluation + verdict-diff plane: one armed
    window at a time, sampled dual-epoch dispatch, bounded diff ring,
    stamp-guarded lifecycle (arm / disarm / promote / stale)."""

    def __init__(self, daemon, ring_capacity: int = 8192) -> None:
        self.daemon = daemon
        self.ring_capacity = int(ring_capacity)
        self._lock = threading.RLock()
        self._state = "disarmed"  # disarmed | armed | stale
        self._window: Optional[dict] = None
        self._window_id = 0
        self.last_window: Optional[dict] = None
        self._eval = None  # jit-tracked evaluate_batch, lazy
        self._diff_kernel = None  # jitted diff_codes, lazy

    # -- lifecycle ------------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def arm(
        self,
        rules_json: Optional[str] = None,
        sample_rate: float = 1.0,
        seed: int = 0,
        replace: bool = True,
    ) -> dict:
        """Open a diff window.  With ``rules_json`` the shadow world
        is the compiled CANDIDATE (live rules with same-labeled ones
        replaced); without, it is the PREVIOUS publish (standby
        mode).  Re-arming closes any open window first."""
        if not (0.0 < float(sample_rate) <= 1.0):
            raise ValueError(
                f"sample_rate must be in (0, 1], got {sample_rate!r}"
            )
        mgr = self.daemon.endpoint_manager
        version, live_tables, live_index, _ = (
            mgr.published_with_states()
        )
        if live_tables is None:
            raise RuntimeError(
                "no published tables: nothing to shadow against"
            )
        if rules_json is not None:
            from cilium_tpu.policy.api import rules_from_json

            rules = rules_from_json(rules_json)
            for r in rules:
                r.sanitize()
            tables, index, states = compile_candidate_world(
                self.daemon, rules
            )
            mode = "candidate"
        else:
            prev = mgr.published_previous()
            if prev is None:
                raise RuntimeError(
                    "standby shadow needs a previous publish (the "
                    "standby epoch is empty); publish a change "
                    "first or arm a candidate"
                )
            _, tables, index, states = prev
            # own the buffers NOW: the manager's retained previous
            # tables are the compiler's ping-pong pair, valid for
            # exactly one further publish — a lazy device placement
            # at first sample could read buffers a later compile is
            # reusing in place.  One host copy at arm (a
            # control-plane op) makes every later placement —
            # single-chip device_put or routed replica-store
            # publish — read plane-owned memory only.
            import jax as _jax

            tables = _jax.tree.map(
                lambda a: (
                    None if a is None else np.array(a, copy=True)
                ),
                tables,
                is_leaf=lambda x: x is None,
            )
            rules_json = None
            mode = "standby"
        if dict(index) != dict(live_index):
            raise RuntimeError(
                "shadow endpoint axis diverged from the live "
                "publish (endpoint churn during arm); retry"
            )
        with self._lock:
            if self._state == "armed":
                self._close("superseded")
            self._window_id += 1
            self._window = {
                "id": self._window_id,
                "mode": mode,
                "live_gen": _norm_stamp(live_tables.generation),
                "live_version": version,
                "shadow_gen": _norm_stamp(tables.generation),
                "sample_rate": float(sample_rate),
                "tables": tables,
                "states": states,
                "index": dict(index),
                "rules_json": rules_json,
                "armed_at": time.time(),
                "rng": np.random.default_rng(
                    [int(seed), self._window_id]
                ),
                # lazy device placements (single-chip epoch; routed
                # replica-store epoch + evaluator per router)
                "single_dev": None,
                "routed": None,
                # window counters (GET /policy/diff; zeroed per
                # window — the process-global registry counters
                # stay cumulative)
                "sampled": 0,
                "sampled_batches": 0,
                "refused": 0,
                "changed": {c: 0 for c in DIFF_COLUMNS},
                "changed_dir": _Counter(),
                "allow_to_deny": 0,
                "deny_to_allow": 0,
                "ring": deque(maxlen=self.ring_capacity),
                "ring_evicted": 0,
                "next_seq": 1,
                "pairs": _Counter(),
            }
            self._state = "armed"
        log.info(
            "shadow window armed",
            extra={"fields": {
                "mode": mode,
                "live_gen": self._window["live_gen"],
                "shadow_gen": self._window["shadow_gen"],
                "sample_rate": float(sample_rate),
            }},
        )
        return self.status()

    def disarm(self, reason: str = "operator") -> dict:
        with self._lock:
            if self._window is not None:
                self._close(reason)
        return self.status()

    def promote(self) -> dict:
        """Install the candidate as the live policy through the
        normal policy path and close the window with its counters
        zeroed.  Standby windows have nothing to promote (their
        shadow IS the previous world)."""
        from cilium_tpu.policy.api import rules_from_json

        with self._lock:
            w = self._window
            if w is None:
                raise RuntimeError("no armed shadow window")
            if w["mode"] != "candidate" or not w["rules_json"]:
                raise RuntimeError(
                    "nothing to promote: a standby window's shadow "
                    "is the previous epoch, not a candidate"
                )
            rules_json = w["rules_json"]
            summary = self._close("promoted")
        # the policy path outside the plane lock (it regenerates)
        rules = rules_from_json(rules_json)
        revision = self.daemon.policy_add(rules, replace=True)
        summary["promoted_revision"] = revision
        with self._lock:
            self.last_window = summary
        log.info(
            "shadow candidate promoted",
            extra={"fields": {"revision": revision}},
        )
        return {"state": self._state, "promoted": summary}

    def notify_cutover(self) -> bool:
        """A mesh reshard cutover moved the live serving epoch out
        from under an armed window: its pinned dual-epoch pair no
        longer describes the serving layout, so the window closes
        ``stale`` — exactly the moved-live-stamp rule
        (_check_live_stamp_locked), surfaced as its own verb because
        a cutover preserves the table STAMP while replacing the
        layout underneath it.  Returns True when a window closed."""
        with self._lock:
            if self._window is None:
                return False
            self._close("stale")
            return True

    def _close(self, reason: str) -> dict:
        """Close the open window (caller holds the lock): counters
        freeze into ``last_window``, sampling stops, device epochs
        drop (HBM released with the refs)."""
        w = self._window
        summary = self._window_summary(w)
        summary["closed"] = reason
        self.last_window = summary
        self._window = None
        self._state = "stale" if reason == "stale" else "disarmed"
        if reason == "stale":
            metrics.policy_diff_stale_total.inc()
            tracing.add_event(
                "shadow.stale", live_gen=w["live_gen"],
                shadow_gen=w["shadow_gen"],
            )
        log.info(
            "shadow window closed",
            extra={"fields": {
                "reason": reason, "sampled": w["sampled"],
                "changed": dict(w["changed"]),
            }},
        )
        return summary

    def _check_live_stamp_locked(self, gen: Optional[int]) -> bool:
        """True while the window is open and ``gen`` (normalized, or
        None to re-read the published tables) still matches the
        pinned live stamp; a moved live world closes the window
        stale — the disarm-on-stale guard."""
        w = self._window
        if w is None:
            return False
        if gen is None:
            _, tables, _ = self.daemon.endpoint_manager.published()
            if tables is None:
                gen = -1
            else:
                gen = _norm_stamp(tables.generation)
        if gen != w["live_gen"]:
            self._close("stale")
            return False
        return True

    # -- sampling -------------------------------------------------------------

    def sample_ticket(self, tables) -> Optional[dict]:
        """Sampling decision for one batch about to dispatch against
        ``tables`` (the live epoch).  Returns a ticket pinning the
        window + stamp pair, or None (disarmed, stale-closed, or not
        sampled).  The fast disarmed path is one attribute read."""
        if self._state != "armed":
            return None
        gen = _norm_stamp(tables.generation)
        with self._lock:
            if not self._check_live_stamp_locked(gen):
                return None
            w = self._window
            if (
                w["sample_rate"] < 1.0
                and w["rng"].random() >= w["sample_rate"]
            ):
                return None
            return {
                "window": w["id"],
                "live_gen": w["live_gen"],
                "shadow_gen": w["shadow_gen"],
                "done": False,
            }

    # -- the second dispatch --------------------------------------------------

    def _device_diff(self, live_v, shadow_v):
        """The on-device half of the diff: per-row changed flags per
        verdict column + the transition code, jitted (site
        shadow.diff) over the two lazy column sets — no sync here;
        the drain folds the codes one batch behind."""
        import jax

        if self._diff_kernel is None:
            import jax.numpy as jnp

            def kern(la, lp, lk, sa, sp_, sk):
                return diff_codes(la, lp, lk, sa, sp_, sk, xp=jnp)

            self._diff_kernel = tracing.track_jit(
                jax.jit(kern), "shadow.diff"
            )
        return self._diff_kernel(
            live_v.allowed, live_v.proxy_port, live_v.match_kind,
            shadow_v.allowed, shadow_v.proxy_port,
            shadow_v.match_kind,
        )

    def evaluate(self, ticket: dict, batch, live_out):
        """Dispatch the ALREADY-STAGED TupleBatch against the shadow
        epoch (single-chip path) and diff on device.  Returns a dict
        of lazy columns {allowed, proxy_port, match_kind, ca, cp, ck,
        trans} to ride the pending queue to the drain, or None on any
        shadow-side failure (the live batch is never degraded by its
        shadow; the ticket refuses)."""
        import jax

        with self._lock:
            w = self._window
            if w is None or w["id"] != ticket["window"]:
                self._refuse_ticket(ticket)
                return None
            if w["single_dev"] is None:
                w["single_dev"] = jax.device_put(w["tables"])
            dev = w["single_dev"]
        if self._eval is None:
            from cilium_tpu.engine.verdict import evaluate_batch

            self._eval = tracing.track_jit(
                evaluate_batch, "shadow.dispatch"
            )
        try:
            with tracing.tracer.span(
                "shadow.dispatch", site="shadow.dispatch",
                attrs={
                    "rows": int(batch.ep_index.shape[0]),
                    "shadow_gen": ticket["shadow_gen"],
                },
            ):
                sv = self._eval(dev, batch)
                ca, cp, ck, trans = self._device_diff(live_out, sv)
        except Exception as exc:  # noqa: BLE001 — shadow must never
            # take the live stream down
            log.warning(
                "shadow dispatch failed; sample refused",
                extra={"fields": {"error": str(exc)}},
            )
            self._refuse_ticket(ticket)
            return None
        return {
            "allowed": sv.allowed,
            "proxy_port": sv.proxy_port,
            "match_kind": sv.match_kind,
            "ca": ca,
            "cp": cp,
            "ck": ck,
            "trans": trans,
        }

    def routed_args(self, router):
        """(evaluator, augmented device tables) serving the shadow
        world through the ROUTED failover path — the shadow gather
        goes through the same alive-masked replica machinery as the
        live one, on the same re-split batch.  Built lazily once per
        window; reuses the router's evaluator when the shadow
        geometry matches its jit class, else builds a dedicated
        one."""
        from cilium_tpu.engine.sharded import (
            make_failover_evaluator,
            make_replica_store,
        )

        with self._lock:
            w = self._window
            if w is None:
                return None
            routed = w["routed"]
            if routed is not None and routed["router"] is router:
                return routed["ev"], routed["dev"]
            store = make_replica_store(router.mesh, router.table_axis)
            _, _ = store.publish(w["tables"])
            dev_tables = store.current()[1]
            geom = (
                tuple(w["tables"].l4_hash_rows.shape),
                tuple(w["tables"].l3_allow_bits.shape),
            )
            ev = (
                router._ev
                if geom == router._geom
                else make_failover_evaluator(
                    router.mesh, w["tables"],
                    batch_axis=router.batch_axis,
                    table_axis=router.table_axis,
                    collect_telemetry=router.collect_telemetry,
                )
            )
            w["routed"] = {
                "router": router,
                "store": store,
                "ev": ev,
                "dev": dev_tables,
            }
            return ev, dev_tables

    # -- the drain-side fold --------------------------------------------------

    def _refuse_ticket(self, ticket: dict) -> None:
        with self._lock:
            if ticket.get("done"):
                return
            ticket["done"] = True
            metrics.policy_diff_refused_total.inc()
            w = self._window
            if w is not None and w["id"] == ticket["window"]:
                w["refused"] += 1
            elif self.last_window is not None:
                self.last_window["refused"] = (
                    self.last_window.get("refused", 0) + 1
                )

    def refuse(self, ticket: dict) -> None:
        """A sampled batch whose drain failed over (or whose shadow
        columns were dropped): the ticket refuses cleanly, exactly
        once."""
        self._refuse_ticket(ticket)

    def fold(
        self,
        ticket: dict,
        live_v,
        shadow_cols: dict,
        valid: int,
        *,
        ep_ids,
        src_identities,
        dst_identities,
        dports,
        protos,
        directions,
        tenant="",
        trace_id: str = "",
    ) -> Optional[np.ndarray]:
        """Fold one sampled batch's diff into the window, exactly
        once per ticket: the device-diffed codes (sliced to the valid
        prefix by the caller's [:valid] convention) become counter
        increments + diff records.  Returns the per-row transition
        codes (np.uint8 [valid]; 0 = unchanged) for the flow plane's
        diff-status join, or None when the window closed since the
        sample was taken (the in-flight-across-a-publish refusal —
        counted, never half-folded)."""
        trans = np.asarray(shadow_cols["trans"])[:valid]
        ca = np.asarray(shadow_cols["ca"])[:valid]
        cp = np.asarray(shadow_cols["cp"])[:valid]
        ck = np.asarray(shadow_cols["ck"])[:valid]
        with self._lock:
            w = self._window
            if (
                ticket.get("done")
                or w is None
                or w["id"] != ticket["window"]
            ):
                self._refuse_ticket(ticket)
                return None
            ticket["done"] = True
            w["sampled"] += valid
            w["sampled_batches"] += 1
            metrics.policy_diff_sampled_total.inc(value=valid)
            dirs = np.asarray(directions)[:valid]
            for col, flags in (
                ("allowed", ca), ("proxy_port", cp),
                ("match_kind", ck),
            ):
                n = int(flags.sum())
                if not n:
                    continue
                w["changed"][col] += n
                for dirv, dname in _DIRECTION_NAMES.items():
                    c = int((flags.astype(bool) & (dirs == dirv)).sum())
                    if c:
                        w["changed_dir"][(col, dname)] += c
                        metrics.policy_diff_changed_total.inc(
                            col, dname, value=c
                        )
            n_a2d = int((trans == TRANS_ALLOW_TO_DENY).sum())
            n_d2a = int((trans == TRANS_DENY_TO_ALLOW).sum())
            if n_a2d:
                w["allow_to_deny"] += n_a2d
                metrics.policy_diff_flows_allow_to_deny_total.inc(
                    value=n_a2d
                )
            if n_d2a:
                w["deny_to_allow"] += n_d2a
                metrics.policy_diff_flows_deny_to_allow_total.inc(
                    value=n_d2a
                )
            changed_idx = np.nonzero(trans != TRANS_NONE)[0]
            if changed_idx.size:
                self._capture_records_locked(
                    w, changed_idx, trans, live_v, shadow_cols,
                    valid,
                    ep_ids=ep_ids,
                    src_identities=src_identities,
                    dst_identities=dst_identities,
                    dports=dports,
                    protos=protos,
                    directions=dirs,
                    tenant=tenant,
                    trace_id=trace_id,
                )
        return trans

    def _capture_records_locked(
        self, w, changed_idx, trans, live_v, shadow_cols, valid,
        *, ep_ids, src_identities, dst_identities, dports, protos,
        directions, tenant, trace_id,
    ) -> None:
        """Changed rows → DiffRecords in the bounded ring (newest
        kept under a diff storm, excess charged to ring_evicted —
        the capture_batch drop-storm rule) + the identity-pair
        aggregation behind the summary."""
        sa = np.asarray(shadow_cols["allowed"])[:valid]
        sk = np.asarray(shadow_cols["match_kind"])[:valid]
        sp_ = np.asarray(shadow_cols["proxy_port"])[:valid]
        la = np.asarray(live_v.allowed)[:valid]
        lk = np.asarray(live_v.match_kind)[:valid]
        lp = np.asarray(live_v.proxy_port)[:valid]
        # tuple columns converted ONCE (the loop below runs under
        # the plane lock on the drain path — per-row asarray would
        # stall every concurrent sample/fold during a diff storm)
        src_col = np.asarray(src_identities)
        dst_col = np.asarray(dst_identities)
        ep_col = np.asarray(ep_ids)
        dport_col = np.asarray(dports)
        proto_col = np.asarray(protos)
        dir_col = np.asarray(directions)
        tenants = (
            np.asarray(tenant, dtype=object)
            if not isinstance(tenant, str)
            else None
        )
        truncated = max(0, changed_idx.size - self.ring_capacity)
        if truncated:
            w["ring_evicted"] += truncated
            changed_idx = changed_idx[-self.ring_capacity:]
        ts = time.time()
        for i in changed_idx:
            i = int(i)
            src = int(src_col[i])
            dst = int(dst_col[i])
            w["pairs"][(src, dst)] += 1
            if len(w["ring"]) == self.ring_capacity:
                w["ring_evicted"] += 1
            rec = DiffRecord(
                ts=ts,
                ep_id=int(ep_col[i]),
                src_identity=src,
                dst_identity=dst,
                dport=int(dport_col[i]),
                proto=int(proto_col[i]),
                direction=int(dir_col[i]),
                live_allowed=bool(la[i]),
                shadow_allowed=bool(sa[i]),
                live_match_kind=int(lk[i]),
                shadow_match_kind=int(sk[i]),
                live_proxy_port=int(lp[i]),
                shadow_proxy_port=int(sp_[i]),
                transition=TRANS_NAMES[int(trans[i])],
                live_reason=_drop_reason_of(bool(la[i]), int(lk[i])),
                shadow_reason=_drop_reason_of(
                    bool(sa[i]), int(sk[i])
                ),
                tenant=(
                    str(tenants[i]) if tenants is not None
                    else str(tenant)
                ),
                trace_id=trace_id,
                seq=w["next_seq"],
            )
            w["next_seq"] += 1
            w["ring"].append(rec)

    # -- introspection --------------------------------------------------------

    def _window_summary(self, w: dict) -> dict:
        return {
            "mode": w["mode"],
            "live_gen": w["live_gen"],
            "shadow_gen": w["shadow_gen"],
            "sample_rate": w["sample_rate"],
            "armed_at": w["armed_at"],
            "sampled": w["sampled"],
            "sampled_batches": w["sampled_batches"],
            "refused": w["refused"],
            "changed": dict(w["changed"]),
            "changed_by_direction": [
                {"column": col, "direction": d, "count": n}
                for (col, d), n in sorted(w["changed_dir"].items())
            ],
            "allow_to_deny": w["allow_to_deny"],
            "deny_to_allow": w["deny_to_allow"],
            "records": len(w["ring"]),
            "ring_evicted": w["ring_evicted"],
            "top_reverdicted_pairs": [
                {
                    "src_identity": src,
                    "dst_identity": dst,
                    "count": n,
                }
                for (src, dst), n in w["pairs"].most_common(10)
            ],
        }

    def status(self) -> dict:
        """The diff window's state + counters; re-verifies the live
        stamp so a publish flips the reply to ``stale`` immediately
        (not only at the next sampled dispatch)."""
        with self._lock:
            if self._state == "armed":
                self._check_live_stamp_locked(None)
            out = {"state": self._state}
            if self._window is not None:
                out["window"] = self._window_summary(self._window)
            elif self.last_window is not None:
                out["last_window"] = dict(self.last_window)
            return out

    def diff(
        self, last: int = 256, since_seq: Optional[int] = None
    ) -> dict:
        """GET /policy/diff: status + summary + the newest ``last``
        diff records (``since_seq`` cursors a follow-style reader —
        records with seq > cursor only)."""
        out = self.status()
        with self._lock:
            w = self._window
            records: List[DiffRecord] = list(w["ring"]) if w else []
        if since_seq is not None:
            records = [r for r in records if r.seq > since_seq]
        if last is not None and last > 0:
            # last=0 = untrimmed (the follow reader's shape: the
            # since-seq cursor already bounds the window)
            records = records[-last:]
        out["flows"] = [r.to_dict() for r in records]
        out["matched"] = len(records)
        out["last_seq"] = records[-1].seq if records else (
            (self._window or {}).get("next_seq", 1) - 1
            if self._window
            else 0
        )
        return out
