"""The daemon: orchestration of every subsystem.

Re-design of /root/reference/daemon/daemon.go NewDaemon (daemon.go:1084)
and the policy API handlers (daemon/policy.go):

  bootstrap order (≙ §3.1 of SURVEY.md):
    config → repository → endpoint manager (builder pool) → identity
    allocator (kvstore-backed when a store is given) → ipcache (+
    device LPM listener) → kvstore watchers → clustermesh → proxy →
    endpoint restore from the state dir.

  PolicyAdd (daemon/policy.go:167): collect CIDR prefixes → prefix-
  length refcount → AllocateCIDRs (local identities + ipcache) →
  repo.AddList (revision++) → TriggerPolicyUpdates → regenerate all
  endpoints → publish fresh fleet tables.

  PolicyDelete (daemon/policy.go:240): delete by label, release CIDR
  identities, trigger regeneration.

The REST API of the reference (api/v1 swagger over a unix socket)
maps onto this object's methods one-to-one; cilium_tpu.cli drives
them in-process.
"""

from __future__ import annotations

import threading
from collections import Counter as _Counter
from typing import Dict, List, Optional, Tuple

from cilium_tpu import logging as logfields
from cilium_tpu.logging import get_logger

log = get_logger("daemon")

from cilium_tpu import option, tracing
from cilium_tpu.endpoint import Endpoint, EndpointManager
from cilium_tpu.endpoint.checkpoint import restore_endpoints, save_endpoint
from cilium_tpu.identity import IdentityAllocator
from cilium_tpu.ipcache import IPCache
from cilium_tpu.ipcache.cidr import allocate_cidrs, release_cidrs
from cilium_tpu.ipcache.lpm import LPMBuilder
from cilium_tpu.kvstore import IDENTITIES_PATH, KVStore
from cilium_tpu.kvstore.allocator import Allocator, IdentityBackendAdapter
from cilium_tpu.kvstore.clustermesh import ClusterMesh
from cilium_tpu.kvstore.ipsync import IPIdentityWatcher
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.monitor import MonitorBus
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.search import SearchContext
from cilium_tpu.policy.trace import trace_policy
from cilium_tpu.proxy import Proxy
from cilium_tpu.resilience import (
    STATE_CODES,
    AdmissionGate,
    CircuitBreaker,
    DispatchWatchdog,
)
from cilium_tpu.spanstat import SpanStats
from cilium_tpu.utils.controller import ControllerManager
from cilium_tpu.utils.trigger import Trigger


class EndpointConflict(ValueError):
    """Endpoint id already in use by a different workload."""


def get_cidr_prefixes(rules) -> List[str]:
    """policy.GetCIDRPrefixes: every CIDR string the rules reference."""
    out: List[str] = []
    for rule in rules:
        for ingress in rule.ingress:
            out.extend(str(c) for c in ingress.from_cidr)
            out.extend(str(c.cidr) for c in ingress.from_cidr_set)
        for egress in rule.egress:
            out.extend(str(c) for c in egress.to_cidr)
            out.extend(str(c.cidr) for c in egress.to_cidr_set)
    return out


class Daemon:
    def __init__(
        self,
        node_name: str = "node0",
        kvstore: Optional[KVStore] = None,
        state_dir: Optional[str] = None,
        num_workers: int = 4,
        dns_resolver=None,
        ipam_cidr: str = "10.200.0.0/16",
    ) -> None:
        self.node_name = node_name
        self.lock = threading.RLock()
        # host-scope endpoint IP allocation (pkg/ipam; daemon.go
        # ipam.Init) — create_endpoint without an explicit address
        # draws from this pool, the CNI ADD path
        from cilium_tpu.ipam import IPAM

        self.ipam = IPAM(ipam_cidr)

        # policy.NewPolicyRepository (daemon.go:1100)
        self.repo = Repository()
        # builder pool (daemon.go:235)
        self.endpoint_manager = EndpointManager(num_workers=num_workers)
        # identity allocator, kvstore-backed when distributed
        backend = None
        self.kvstore = kvstore
        if kvstore is not None:
            backend = IdentityBackendAdapter(
                Allocator(kvstore, IDENTITIES_PATH, node=node_name)
            )
        self.identity_allocator = IdentityAllocator(backend=backend)
        # ipcache + device LPM listener (§3.5 tail)
        self.ipcache = IPCache()
        self.lpm_builder = LPMBuilder()
        self.ipcache.add_listener(self.lpm_builder)
        if kvstore is not None:
            self._ip_watcher = IPIdentityWatcher(kvstore, self.ipcache)
        self.clustermesh = ClusterMesh(self.ipcache)
        # service model + conntrack, daemon-owned like the reference
        # (daemon/loadbalancer.go service BPF sync; endpointmanager
        # conntrack.go periodic GC).  Consumers assemble
        # DatapathTables with lb=compile_lb(self.services) /
        # ct=compile_ct(self.ct).
        from cilium_tpu.ct.table import CTMap
        from cilium_tpu.lb.service import ServiceManager

        self.services = ServiceManager()
        self.ct = CTMap()
        # tunnel/overlay map fed by node discovery (pkg/maps/tunnel ←
        # linuxNodeHandler NodeUpdate): remote nodes' pod CIDRs map to
        # their node IP; consumers assemble DatapathTables with
        # tunnel=self.tunnel_map.tables() to compile the overlay form
        from cilium_tpu.tunnel import TunnelMap

        self.tunnel_map = TunnelMap()
        if kvstore is not None:
            from cilium_tpu.kvstore.node import NodeWatcher

            def _tunnel_feed(kind, node):
                # the agent's OWN published Node comes back through
                # the watch; the local pod CIDR must stay direct
                # (linuxNodeHandler skips the local node)
                if getattr(node, "name", "") == self.node_name:
                    return
                self.tunnel_map.on_node(kind, node)

            self._node_watcher = NodeWatcher(
                kvstore, on_change=_tunnel_feed
            )
        # indexed selector -> identity-set resolution for the compiler
        from cilium_tpu.compiler.selectorcache import RuleIndex, SelectorCache

        self.selector_cache = SelectorCache()
        self.rule_index = RuleIndex()
        # Serializes whole regeneration sweeps.  The selector cache and
        # rule index are shared, version-keyed caches: a second sweep
        # starting mid-flight would re-sync them to a NEWER identity
        # universe than the first sweep's snapshot, so in-flight builds
        # could resolve selectors against identities absent from the
        # universe their tables are lowered onto (universe/table skew —
        # the reference serializes the equivalent via the trigger +
        # per-endpoint regeneration.Lock, policy.go:540-552).
        self._regen_lock = threading.Lock()
        # endpoint selectors of rules changed since the last sweep;
        # None = a non-policy reason forced a full sweep
        self._pending_rule_selectors: Optional[list] = []
        self.monitor = MonitorBus()
        # Hubble-style flow-record plane (cilium_tpu.flow): a bounded
        # ring of structured per-flow records fed by process_flows —
        # all drops plus head-sampled allows (the
        # MonitorAggregationLevel knob, shared with the monitor
        # fold) — served by GET /flows and `cilium-tpu observe`
        from cilium_tpu.flow import FlowStore

        self.flow_store = FlowStore()
        self.proxy = Proxy(monitor=self.monitor)
        # accumulated per-phase regeneration spans (pkg/spanstat; the
        # reference logs one SpanStat per phase, policy.go:689-699) —
        # served by GET /debug/profile
        self.regen_spans = SpanStats()
        # datapath-loop phase spans (host pack / dispatch / event
        # fold), fed by process_flows — the hot path's SpanStat
        # instrumentation, also served by GET /debug/profile
        self.datapath_spans = SpanStats()
        # XDP-style deny-by-CIDR prefilter (daemon/prefilter.go):
        # daemon-owned so trace_tuple, process_flows and datapath
        # assembly (prefilter.tables()) consult ONE authoritative
        # CIDR set
        from cilium_tpu.prefilter import PreFilter

        self.prefilter = PreFilter()
        self.controllers = ControllerManager()
        # a controller stuck failing on its background thread flips
        # node health to degraded at this many consecutive failures
        # (pkg/controller's failure bookkeeping surfaced, instead of
        # failing silently off the request path)
        self.controller_failure_threshold = 3
        # -- trace plane (cilium_tpu.tracing) --------------------------
        # the process-global tracer (the metrics-registry shape):
        # REST handlers open root spans, every serving-path phase
        # below nests under them via contextvars; `/debug/traces`
        # serves the ring
        self.tracer = tracing.tracer
        self._traced_evaluate = None  # jit-tracked evaluate_batch
        # -- resilience plane (cilium_tpu.resilience) ------------------
        # Device dispatch runs under retry + a circuit breaker; when
        # the breaker opens the serving plane degrades to the
        # bit-identical host lattice fold instead of erroring the
        # stream, and half-open probes restore TPU service.
        self.dispatch_retries = 2
        self.dispatch_retry_base = 0.002
        self.dispatch_breaker = CircuitBreaker(
            name="engine.dispatch",
            failure_threshold=3,
            recovery_timeout=1.0,
            on_transition=self._breaker_event,
        )
        # per-batch dispatch deadline (a wedged XLA launch must fail
        # the batch, not hang the stream); <=0 disables
        self.dispatch_watchdog = DispatchWatchdog(timeout=30.0)
        # double-buffered async dispatch depth: batches in flight
        # beyond the one being drained (process_flows overlaps the
        # host pack of batch N+1 with device compute of batch N);
        # 0 = fully synchronous per-batch serving
        self.dispatch_async_depth = 1
        # sub-word hot planes for the FUSED datapath world
        # (engine.datapath.subword_datapath_tables): opt-in default
        # of datapath_tables() — flip before attach_mesh_router /
        # ServingPlane(fused=True) so every fused epoch ships the
        # compact row layouts (planes whose ranges don't fit keep
        # the wide layout automatically)
        self.datapath_subword = False
        # fused-plane hot-lane overrides the online autotuner sweeps
        # (engine.autotune.retune_candidates): CT bucket-row width
        # for the compact layout, and a plane-scoped ipcache
        # sub-word toggle that applies without the global
        # datapath_subword transform.  Either change moves the
        # datapath layout stamp, so the DatapathStore refuses the
        # next cross-layout delta into exactly one full upload.
        self.datapath_ct_lanes = None
        self.datapath_ip_subword = None
        # device table-publication backoff (monotonic deadline): a
        # failed epoch publish must not be retried per batch
        self._device_publish_retry_at = 0.0
        # per-chip failure domain (engine/failover.py): when a mesh
        # router is attached, its ChipBreakerBank's transitions flow
        # through the same observability planes as the process-wide
        # breaker (cilium_chip_breaker_state{chip} gauge, AgentNotify
        # monitor events, health()/status() degraded reasons) — the
        # mesh refinement of the dispatch breaker above
        self.mesh_router = None
        # when a router is attached with route_dispatch=True (the
        # default), the production dispatch loop (process_flows +
        # the serving plane) sends each batch THROUGH the router's
        # per-chip failure domain instead of the single-chip
        # evaluate_batch — the PR 8 remainder closed.  Routing only
        # engages once the router holds a published epoch.
        self.mesh_route_dispatch = False
        # continuous serving plane (cilium_tpu.serve.ServingPlane):
        # lazy — POST /datapath/flows?stream=1 and `cilium-tpu
        # serve-bench` start it on first use.  Tenant fairness
        # weights live on the daemon so PATCH /config can set them
        # before (or after) the plane spins up.
        self.serving = None
        self.tenant_weights: Dict[str, float] = {}
        # verdict memoization (engine/memo.py): when enabled, the
        # serving dispatch dedups each batch's policy keys in-jit
        # and serves repeats from a device-resident verdict cache,
        # epoch-stamped so any publish flushes it; an overflowing
        # batch (more distinct keys than the compaction capacity)
        # falls back to the uncached program — bit-identity is
        # unconditional either way.  Off by default: PATCH /config
        # {"verdict_cache": true} turns it on.
        self.verdict_cache_enabled = False
        self.verdict_cache = None  # engine.memo.VerdictCache, lazy
        self.verdict_cache_rows = 1 << 12
        # rep/miss compaction capacity as a fraction of the batch
        # (1/4 keeps lattice-gather savings real while Zipf-skewed
        # batches virtually never overflow), floored at 1024 keys
        # (matching autotune.memo_candidates) so tiny batches don't
        # overflow on trivially small key sets
        self.verdict_cache_rep_shift = 2
        # overflow backoff: a workload whose distinct-key count
        # keeps exceeding the compaction capacity pays the memo
        # sort+probe AND the uncached re-dispatch per batch; after
        # `streak_limit` consecutive refusals the memo attempt is
        # skipped, re-probed once every `retry_period` batches
        self.verdict_cache_overflow_streak = 0
        self.verdict_cache_streak_limit = 8
        self.verdict_cache_retry_period = 64
        self._memo_batch_seq = 0
        # shadow policy rollout (cilium_tpu.shadow): dual-epoch
        # sampled evaluation + live verdict-diff canarying.  Armed
        # via POST /policy/shadow; disarmed windows cost one
        # attribute read per batch.
        from cilium_tpu.shadow import ShadowPlane

        self.shadow = ShadowPlane(self)
        # live performance plane (cilium_tpu.perfplane): always-on
        # per-batch phase windows, ingest-stall detector, SLO-class
        # compliance ledger and the retune history behind GET
        # /debug/perf and `cilium-tpu top`.  The serving plane feeds
        # it from the overlap bookkeeping it already keeps.
        from cilium_tpu.perfplane import PerfPlane

        self.perf = PerfPlane()
        # online re-tune (engine.autotune.online_retune): the serving
        # loop polls maybe_online_retune() every 64 batches; off by
        # default so steady-state daemons never swap layouts behind
        # the operator's back.  PATCH /config {"online_retune": true}
        # arms it; config overrides the hysteresis bounds.
        self.online_retune_enabled = False
        self.online_retune_config: Dict = {}
        self._retune_inflight = threading.Lock()
        # fused-tables byte model cache for perf_snapshot: keyed by
        # (generation, layout) so the gatherprof walk runs once per
        # publish, not per /debug/perf poll
        self._perf_model_cache = None
        # per-tenant named SLO classes (serving tier 2): name ->
        # {"deadline_ms", "shed_priority", "weight"} bundles and the
        # tenant -> class assignment, both live via PATCH /config
        # {"slo_classes": ..., "tenant_slo": ...}
        self.slo_classes: Dict[str, Dict] = {}
        self.tenant_slo: Dict[str, str] = {}
        # bounded admission: flows in flight across concurrent
        # process_flows calls; excess batches shed under the
        # canonical Overload drop reason (None = unbounded)
        self.admission = AdmissionGate(limit=None)
        self.degraded_batches = 0
        # CT occupancy watermarks → emergency GC with adaptive backoff
        self.ct_high_watermark = 0.90
        self.ct_low_watermark = 0.75
        self._ct_gc_backoff_base = 0.1
        self._ct_gc_backoff_max = 30.0
        self._ct_gc_backoff = self._ct_gc_backoff_base
        self._ct_gc_not_before = 0.0
        # periodic CT GC (pkg/maps/ctmap GC; endpointmanager
        # conntrack.go loop)
        from cilium_tpu.utils.controller import Controller

        self.controllers.update_controller(
            Controller(
                name="ct-gc",
                do_func=self._ct_gc,
                run_interval=30.0,
            )
        )
        # TriggerPolicyUpdates debouncing (daemon/policy.go:47)
        self.policy_trigger = Trigger(
            self._regenerate_for_reasons, name="policy_update"
        )
        # ToFQDNs poller (daemon.go NewDaemon: d.dnsPoller).  The
        # resolver defaults to the REAL host stack
        # (fqdn.system_resolver ≙ dnspoller.go LookupIPs); tests
        # inject deterministic resolvers.  Polling starts only when
        # ToFQDNs rules are marked, so hermetic runs never touch DNS.
        from cilium_tpu.fqdn import DNSPoller, system_resolver

        self.dns_poller = DNSPoller(
            policy_add=lambda rules: self.policy_add(rules, replace=True),
            resolver=dns_resolver or system_resolver,
        )
        # CIDR prefix-length refcounts (daemon.go createPrefixLengthCounter)
        self.prefix_lengths: _Counter = _Counter()

        self.state_dir = state_dir
        if state_dir:
            from cilium_tpu.ipcache.ipcache import (
                FROM_AGENT_LOCAL,
                IPIdentity,
            )
            from cilium_tpu.kvstore.ipsync import upsert_ip_mapping

            # schema migration FIRST (the init.sh cilium-map-migrate
            # moment): old-version checkpoints rewrite in place, then
            # restore parses only current-version docs
            from cilium_tpu.endpoint.checkpoint import migrate_state_dir

            migrated = migrate_state_dir(state_dir)
            if migrated:
                log.info(
                    "migrated endpoint checkpoints",
                    extra={"fields": {"count": migrated}},
                )
            import ipaddress as _ipaddress

            for endpoint in restore_endpoints(
                state_dir, self.identity_allocator
            ):
                self.endpoint_manager.insert(endpoint)
                # re-reserve the restored IP — a fresh pool would
                # hand the same address to the next CNI ADD
                if endpoint.ipv4 and (
                    _ipaddress.ip_address(endpoint.ipv4)
                    in self.ipam.cidr
                ):
                    try:
                        self.ipam.allocate(endpoint.ipv4)
                    except Exception:
                        log.warning(
                            "restored endpoint IP already reserved",
                            extra={"fields": {
                                logfields.ENDPOINT_ID: endpoint.id,
                                logfields.IP_ADDR: endpoint.ipv4,
                            }},
                        )
                # republish the endpoint's IP mapping — the reference
                # restores the ipcache from the pinned BPF map on
                # restart (daemon restoreOldEndpoints + ipcache
                # restore); without this, restored endpoints' traffic
                # would resolve to WORLD
                if (
                    endpoint.ipv4
                    and endpoint.security_identity is not None
                ):
                    self.ipcache.upsert(
                        endpoint.ipv4,
                        IPIdentity(
                            endpoint.security_identity.id,
                            FROM_AGENT_LOCAL,
                        ),
                    )
                    # and to the cluster: the old daemon's
                    # lease-scoped kvstore key died with its session,
                    # so remote nodes would otherwise resolve this
                    # endpoint to WORLD after our restart
                    if self.kvstore is not None:
                        upsert_ip_mapping(
                            self.kvstore,
                            endpoint.ipv4,
                            endpoint.security_identity.id,
                            node=self.node_name,
                        )
            if self.endpoint_manager.endpoints():
                self.trigger_policy_updates("restore", full=True)

    # -- identity snapshot ---------------------------------------------------

    def identity_cache(self):
        return self.identity_allocator.identity_cache()

    # -- policy API (daemon/policy.go) --------------------------------------

    def policy_add(self, rules, replace: bool = False) -> int:
        """PolicyAdd (daemon/policy.go:167).  Returns the new revision."""
        with self.lock:
            try:
                for rule in rules:
                    rule.sanitize()
            except Exception:
                metrics.policy_import_errors.inc()
                raise
            # MarkToFQDNRules (daemon/policy.go:172); the poll loop
            # spins up lazily on the first ToFQDNs rule, so hermetic
            # runs without such rules never touch DNS
            self.dns_poller.mark_to_fqdn_rules(rules)
            if self.dns_poller.managed and not self.dns_poller.running:
                self.dns_poller.start()
            prefixes = get_cidr_prefixes(rules)
            import ipaddress

            for prefix in prefixes:
                self.prefix_lengths[
                    ipaddress.ip_network(prefix, strict=False).prefixlen
                ] += 1
            if prefixes:
                allocate_cidrs(
                    self.ipcache, self.identity_allocator, prefixes
                )
            if replace:
                for rule in rules:
                    for old in self.repo.search(rule.labels):
                        self._note_rule_change(old.endpoint_selector)
                    self.repo.delete_by_labels(rule.labels)
            for rule in rules:
                self._note_rule_change(rule.endpoint_selector)
            revision = self.repo.add_list(list(rules))
            metrics.policy_count.set(value=self.repo.num_rules())
            metrics.policy_revision.set(value=revision)
            log.info(
                "policy rules imported",
                extra={"fields": {
                    logfields.POLICY_REVISION: revision,
                    "count": len(rules),
                }},
            )
        self.trigger_policy_updates("policy rules added")
        return revision

    def policy_delete(self, labels) -> Tuple[int, int]:
        """PolicyDelete (daemon/policy.go:240)."""
        with self.lock:
            deleted_rules = self.repo.search(labels)
            for old in deleted_rules:
                self._note_rule_change(old.endpoint_selector)
            prefixes = get_cidr_prefixes(deleted_rules)
            revision, n_deleted = self.repo.delete_by_labels(labels)
            if n_deleted:
                import ipaddress

                for prefix in prefixes:
                    plen = ipaddress.ip_network(
                        prefix, strict=False
                    ).prefixlen
                    self.prefix_lengths[plen] -= 1
                    if self.prefix_lengths[plen] <= 0:
                        del self.prefix_lengths[plen]
                release_cidrs(
                    self.ipcache, self.identity_allocator, prefixes
                )
            metrics.policy_count.set(value=self.repo.num_rules())
        if n_deleted:
            self.trigger_policy_updates("policy rules deleted")
        return revision, n_deleted

    def policy_resolve(self, ctx: SearchContext):
        """GET /policy/resolve (daemon/policy.go:66)."""
        return trace_policy(self.repo, ctx)

    def trace_tuple(self, **kwargs):
        """Single-tuple datapath explain (`cilium policy trace` made
        stage-accurate): rerun one tuple through prefilter → LB/DNAT
        → CT → ipcache → lattice → combine against THIS daemon's
        state, reporting each stage's decision and the matching
        rules.  See policy.trace.trace_tuple."""
        from cilium_tpu.policy.trace import trace_tuple

        return trace_tuple(self, **kwargs)

    # -- regeneration (daemon/policy.go:47 TriggerPolicyUpdates) ------------

    def _note_rule_change(self, endpoint_selector) -> None:
        """Record a changed rule's endpoint selector for delta-scoped
        regeneration (a rule affects only endpoints it selects)."""
        if self._pending_rule_selectors is not None:
            self._pending_rule_selectors.append(endpoint_selector)

    def trigger_policy_updates(self, reason: str, full: bool = False) -> None:
        if full:
            # non-policy reason (endpoint/identity/config change):
            # next sweep must not be delta-scoped
            self._pending_rule_selectors = None
        self.policy_trigger.trigger_with_reason(reason)

    def _accumulate_regen_span(
        self, stats: SpanStats, success: bool
    ) -> None:
        """Fold one run's spans into the lifetime accumulators served
        by GET /debug/profile (pkg/spanstat's success/failure split)."""
        for name, span in stats.items():
            acc = self.regen_spans.span(name)
            acc.success_total += span.success_total
            acc.failure_total += span.failure_total
            acc.num_success += span.num_success
            acc.num_failure += span.num_failure
        self._export_spans("regeneration", self.regen_spans)

    @staticmethod
    def _export_spans(scope: str, spans: SpanStats) -> None:
        """Mirror a SpanStats accumulator into the metrics registry
        (one gauge sample per phase, labels-first) so /debug/profile
        and /metrics/prometheus report the SAME numbers."""
        for name, span in spans.items():
            metrics.spanstat_seconds.set(
                scope, name, value=span.total()
            )
        metrics.trace_spans_total.set(
            value=tracing.tracer.finished_total
        )
        metrics.trace_spans_dropped.set(value=tracing.tracer.dropped)

    def reset_profile(self) -> None:
        """GET /debug/profile?reset=1: zero the cumulative SpanStat
        accumulators (regeneration + datapath) so before/after
        experiments don't need a daemon restart.  The mirrored
        spanstat_seconds gauges are zeroed too, so /metrics and
        /debug/profile keep agreeing."""
        for scope, spans in (
            ("regeneration", self.regen_spans),
            ("datapath", self.datapath_spans),
        ):
            for name in spans:
                metrics.spanstat_seconds.set(scope, name, value=0.0)
            spans.clear()
        # the serving plane's rolling serving_p99_ms window resets
        # with the same seam, so bench segments / before-after
        # experiments don't bleed one load shape's tail into the
        # next (the plane keeps its own window — see
        # ServingPlane.reset_window)
        if self.serving is not None:
            self.serving.reset_window()
        else:
            # no plane → reset_window can't do it for us: clear the
            # perf plane's phase/fill/stall windows directly so
            # /debug/perf experiments get the same seam
            self.perf.reset()

    def _regenerate_for_reasons(self, reasons: List[str]) -> None:
        self.regenerate_all(", ".join(reasons) or "trigger")

    def regenerate_all(self, reason: str = "") -> int:
        with self._regen_lock:
            # the regen sweep's root span: compile/publish pipeline
            # spans (FleetCompiler, DeviceTableStore) and proxy
            # upcalls nest under it
            with self.tracer.span(
                "daemon.regenerate", site="daemon",
                attrs={"reason": reason},
            ):
                return self._regenerate_all_locked(reason)

    def _regenerate_all_locked(self, reason: str = "") -> int:
        stats = SpanStats()  # fresh per run: the histogram observes
        # THIS run's duration; regen_spans accumulates across runs
        total_span = tracing.stat_span(
            stats, "total", site="daemon.regenerate", trc=self.tracer
        ).start()
        cache, cache_version = (
            self.identity_allocator.identity_cache_versioned()
        )
        prev_version = self.selector_cache.version
        universe_version = self.selector_cache.sync(
            cache, cache_version=cache_version
        )
        # Swap the pending set and snapshot the repo revision under
        # the daemon lock: a concurrent policy_add after the swap must
        # not be fast-forwarded past (its selector isn't in `pending`).
        with self.lock:
            pending, self._pending_rule_selectors = (
                self._pending_rule_selectors,
                [],
            )
            affected_revision = self.repo.get_revision()
        affected = None
        if pending is not None and universe_version == prev_version:
            affected = frozenset().union(
                *(
                    self.selector_cache.matches(sel)
                    for sel in pending
                ),
            ) if pending else frozenset()
        self.rule_index.build(self.repo, self.selector_cache)
        n = self.endpoint_manager.regenerate_all(
            self.repo,
            cache,
            reason,
            selector_cache=self.selector_cache,
            rule_index=self.rule_index,
            universe_version=universe_version,
            affected_identities=affected,
            affected_revision=affected_revision,
            identity_cache_token=cache_version,
        )
        # Two-phase redirect realization (pkg/endpoint/bpf.go:488 +
        # policy.go:157-166): the first pass computes desired L4
        # policy; redirects then get proxy ports allocated; endpoints
        # whose redirects changed recompute so the L4 entries carry
        # the allocated ports.  The L7 tables' identity axis MUST be
        # the fleet compiler's index space (the published tables'
        # id_direct), not a sorted rebuild.
        id_index, n_identities = self.endpoint_manager.identity_index()
        from cilium_tpu.utils.completion import WaitGroup

        wait_group = WaitGroup()
        dirty = False
        attempted = []  # (endpoint, realized map before this attempt)
        universe_unchanged = universe_version == prev_version
        upcall_failed = False
        for endpoint in self.endpoint_manager.endpoints():
            l4 = endpoint.desired_l4_policy
            if l4 is None or not l4.has_redirect():
                if endpoint.realized_redirects:
                    try:
                        self.proxy.update_endpoint_redirects(
                            endpoint, cache, id_index, n_identities,
                            self.selector_cache,
                        )
                    except Exception as exc:
                        # a failed proxy upcall (dead envoy, injected
                        # proxy.upcall fault) must not crash the
                        # sweep's thread: the endpoint keeps its old
                        # redirects and retries next trigger
                        upcall_failed = True
                        endpoint.force_policy_compute = True
                        log.warning(
                            "proxy upcall failed; keeping old "
                            "redirects",
                            extra={"fields": {
                                logfields.ENDPOINT_ID: endpoint.id,
                                "error": str(exc),
                            }},
                        )
                continue
            if (
                universe_unchanged
                and not endpoint.last_policy_changed
                and endpoint.realized_redirects
            ):
                # unchanged policy + unchanged identity universe ⇒ the
                # resolved matcher inputs are identical to the live
                # redirects' — skip even the re-resolution (the
                # fingerprint check would skip only the compile)
                continue
            before = dict(endpoint.realized_redirects)
            try:
                realized = self.proxy.update_endpoint_redirects(
                    endpoint, cache, id_index, n_identities,
                    self.selector_cache, wait_group=wait_group,
                )
            except Exception as exc:
                # same containment as above: roll this endpoint back
                # to its pre-attempt redirects, flag the retry, let
                # every other endpoint's regeneration proceed
                upcall_failed = True
                endpoint.realized_redirects = before
                endpoint.force_policy_compute = True
                log.warning(
                    "proxy upcall failed; keeping old redirects",
                    extra={"fields": {
                        logfields.ENDPOINT_ID: endpoint.id,
                        "error": str(exc),
                    }},
                )
                continue
            attempted.append((endpoint, before))
            if realized != before:
                endpoint.force_policy_compute = True
                dirty = True
        if upcall_failed:
            metrics.endpoint_regenerations.inc("fail")
        # ACK gate (pkg/completion + pkg/envoy/xds/ack.go): the table
        # flip below happens only once EVERY submitted matcher
        # compile — port change or not — has ACKed its version; on
        # timeout or NACK the regeneration FAILS: realized redirect
        # state rolls back so old redirects and old published tables
        # keep serving, and the retry flag makes the next trigger
        # re-attempt (pkg/endpoint/bpf.go:442, policy.go:770-775)
        if wait_group.pending and not wait_group.wait(
            timeout=option.Config.redirect_ack_timeout
        ):
            metrics.endpoint_regenerations.inc("fail")
            for endpoint, before in attempted:
                endpoint.realized_redirects = before
                endpoint.force_policy_compute = True
            total_span.end(success=False)
            self._accumulate_regen_span(stats, success=False)
            return n
        if dirty:
            self.endpoint_manager.regenerate_all(
                self.repo,
                cache,
                reason + " (redirects realized)",
                selector_cache=self.selector_cache,
                rule_index=self.rule_index,
                universe_version=universe_version,
                affected_revision=affected_revision,
                identity_cache_token=cache_version,
            )
        metrics.policy_regeneration_count.inc(value=n)
        total_span.end()
        metrics.endpoint_regeneration_seconds.observe(
            stats.span("total").total()
        )
        self._accumulate_regen_span(stats, success=True)
        return n

    # -- endpoint API (daemon/endpoint.go) ----------------------------------

    def create_endpoint(
        self, endpoint_id: int, labels, ipv4: Optional[str] = None,
        name: str = "", ip_reserved: bool = False,
    ) -> Endpoint:
        """PUT /endpoint/{id} (daemon/endpoint.go:138): allocate the
        identity from labels, publish the IP, regenerate.

        Idempotent for runtime retries: re-creating an id with the
        SAME name returns the existing endpoint untouched (CNI ADD is
        retried by runtimes); the same id under a DIFFERENT name is a
        conflict — silently replacing would leak the old endpoint's
        IP and leave its ipcache entry pointing at a dead identity."""
        import ipaddress as _ipaddress

        from cilium_tpu.endpoint.endpoint import (
            STATE_READY,
            STATE_WAITING_FOR_IDENTITY,
        )
        from cilium_tpu.ipcache.ipcache import FROM_AGENT_LOCAL, IPIdentity
        from cilium_tpu.kvstore.ipsync import upsert_ip_mapping

        with self.lock:
            # id 0 = "agent allocates": pick a free id instead of the
            # caller deriving one (a hash-derived id collides at
            # birthday rates and surfaces as a permanent ADD failure;
            # the reference's endpointmanager allocates too).
            # Idempotency still holds: a named re-create finds the
            # live endpoint by name before allocating.
            if endpoint_id == 0:
                if name:
                    existing = self.endpoint_manager.lookup_name(name)
                    if existing is not None:
                        return existing
                endpoint_id = self._allocate_endpoint_id()
            # check-then-act under the daemon lock: the API server is
            # thread-per-connection, and two concurrent ADD retries
            # racing past the existence guard would double-allocate
            # the IP and leak the losing endpoint's resources
            existing = self.endpoint_manager.lookup(endpoint_id)
            if existing is not None:
                # idempotent ONLY for a matching non-empty name (the
                # runtime-retry case); unnamed re-creates have no
                # identity to match on and must surface as conflicts
                # rather than silently discarding the new labels/IP
                if name and existing.name == name:
                    return existing
                raise EndpointConflict(
                    f"endpoint id {endpoint_id} in use by "
                    f"{existing.name!r}"
                )
            allocated_ip = None
            if ipv4 is None:
                ipv4 = allocated_ip = self.ipam.allocate()
            elif ip_reserved:
                # the caller already holds this address from the
                # agent's own IPAM (POST /ipam — the docker IpamDriver
                # flow); re-reserving would false-conflict
                pass
            elif _ipaddress.ip_address(ipv4) in self.ipam.cidr:
                # in-pool explicit address: a duplicate must FAIL
                # (the except-everything that was here swallowed the
                # conflict and brought two endpoints up on one IP);
                # out-of-pool addresses are the caller's own numbering
                self.ipam.allocate(ipv4)
                allocated_ip = ipv4
            try:
                endpoint = Endpoint(endpoint_id, ipv4=ipv4, name=name)
                # externally-reserved addresses (POST /ipam → docker
                # IpamDriver) are NOT returned to the pool on delete;
                # their ReleaseAddress call does that.  In-memory
                # only: a restart converts ownership to the agent
                # (restore re-reserves the address itself).
                endpoint.ip_externally_owned = ip_reserved
                endpoint.set_state(
                    STATE_WAITING_FOR_IDENTITY, "creating"
                )
                ident, _ = self.identity_allocator.allocate(labels)
                endpoint.set_identity(ident)
                endpoint.set_state(STATE_READY, "identity resolved")
                self.endpoint_manager.insert(endpoint)
            except BaseException:
                # a failed create must hand its address back — the
                # runtime retries, and each leaked IP would drain the
                # pool without ever serving an endpoint
                if allocated_ip is not None:
                    self.ipam.release(allocated_ip)
                raise
            if ipv4:
                self.ipcache.upsert(
                    ipv4, IPIdentity(ident.id, FROM_AGENT_LOCAL)
                )
        # the kvstore publish is network I/O — outside the daemon
        # lock, or one wedged store round trip stalls every
        # concurrent endpoint operation
        if ipv4 and self.kvstore is not None:
            upsert_ip_mapping(
                self.kvstore, ipv4, ident.id, node=self.node_name
            )
        self.trigger_policy_updates(
            f"endpoint {endpoint_id} created", full=True
        )
        return endpoint

    def update_endpoint_labels(self, endpoint_id: int, labels) -> bool:
        """EndpointUpdateLabels (pkg/endpoint + workloads docker.go:479):
        re-allocate the identity from the new label set, republish the
        IP mapping, release the old identity, regenerate."""
        from cilium_tpu.ipcache.ipcache import FROM_AGENT_LOCAL, IPIdentity
        from cilium_tpu.kvstore.ipsync import upsert_ip_mapping

        endpoint = self.endpoint_manager.lookup(endpoint_id)
        if endpoint is None:
            return False
        old = endpoint.security_identity
        ident, _ = self.identity_allocator.allocate(labels)
        if old is not None and ident.id == old.id:
            # same identity: drop the reference allocate() just took
            # (repeated runtime START events must not leak refs)
            self.identity_allocator.release(ident)
            return True
        endpoint.set_identity(ident)
        # the identity universe may be unchanged (another endpoint
        # already holds both identities), so the revision gate would
        # skip this endpoint — force its recompute
        endpoint.force_policy_compute = True
        if endpoint.ipv4:
            self.ipcache.upsert(
                endpoint.ipv4, IPIdentity(ident.id, FROM_AGENT_LOCAL)
            )
            if self.kvstore is not None:
                upsert_ip_mapping(
                    self.kvstore, endpoint.ipv4, ident.id,
                    node=self.node_name,
                )
        if old is not None:
            self.identity_allocator.release(old)
        self.trigger_policy_updates(
            f"endpoint {endpoint_id} relabeled", full=True
        )
        return True

    # next candidate for agent-allocated endpoint ids; ids live in
    # u16 space above the reserved low range, like the reference's
    # endpointmanager allocation (pkg/endpointmanager)
    _EP_ID_BASE = 256
    _next_ep_id = 256

    def _allocate_endpoint_id(self) -> int:
        """Pick a free endpoint id (caller holds self.lock)."""
        span = 65536 - self._EP_ID_BASE
        for _ in range(span):
            candidate = self._next_ep_id
            self._next_ep_id = (
                self._EP_ID_BASE
                + (candidate + 1 - self._EP_ID_BASE) % span
            )
            if self.endpoint_manager.lookup(candidate) is None:
                return candidate
        raise EndpointConflict("endpoint id space exhausted")

    def delete_endpoint(
        self, endpoint_id: int, expected_name: Optional[str] = None
    ) -> bool:
        """`expected_name` guards hash-derived callers (the CNI shim
        maps container ids onto endpoint ids): a DEL whose id collided
        with a DIFFERENT workload's endpoint must not tear that
        endpoint down."""
        from cilium_tpu.endpoint.endpoint import (
            STATE_DISCONNECTED,
            STATE_DISCONNECTING,
        )
        from cilium_tpu.kvstore.ipsync import delete_ip_mapping

        with self.lock:
            if endpoint_id == 0 and expected_name:
                # agent-allocated ids: the caller only knows the name
                endpoint = self.endpoint_manager.lookup_name(
                    expected_name
                )
            else:
                endpoint = self.endpoint_manager.lookup(endpoint_id)
            if endpoint is None:
                return False
            if (
                expected_name is not None
                and endpoint.name != expected_name
            ):
                raise EndpointConflict(
                    f"endpoint id {endpoint_id} belongs to "
                    f"{endpoint.name!r}, not {expected_name!r}"
                )
            endpoint.set_state(STATE_DISCONNECTING, "delete")
            if endpoint.ipv4:
                self.ipcache.delete(endpoint.ipv4)
                if not getattr(
                    endpoint, "ip_externally_owned", False
                ):
                    self.ipam.release(endpoint.ipv4)
            if endpoint.security_identity is not None:
                self.identity_allocator.release(
                    endpoint.security_identity
                )
            self.endpoint_manager.remove(endpoint)
            endpoint.set_state(STATE_DISCONNECTED, "deleted")
        # network I/O outside the lock (see create_endpoint)
        if endpoint.ipv4 and self.kvstore is not None:
            delete_ip_mapping(self.kvstore, endpoint.ipv4)
        return True

    # -- persistence ---------------------------------------------------------

    def checkpoint(self) -> int:
        if not self.state_dir:
            return 0
        n = 0
        for endpoint in self.endpoint_manager.endpoints():
            save_endpoint(endpoint, self.state_dir)
            n += 1
        return n

    # -- status (daemon/status.go) ------------------------------------------

    def _ct_gc(self) -> None:
        """Periodic CT garbage collection (pkg/maps/ctmap GC loop):
        expired entries leave the host map; gc() bumps the map's
        mutation counter, so the churn snapshot cache self-invalidates
        at its next use (replay._ChurnDriver gate) and the device CT
        resyncs.  Still sweeps a NON-EMPTY table while the Conntrack
        option is off: disabling flushed it, but replay harnesses may
        repopulate the daemon map afterwards — entries must never
        accumulate unbounded just because GC went dormant."""
        if (
            not option.Config.opts.is_enabled(option.CONNTRACK)
            and not self.ct.entries
        ):
            return
        self.ct.gc(now=self.ct.now())
        self._ct_pressure_check()

    def _ct_pressure_check(self) -> None:
        """CT occupancy watermarks (ctmap's pressure-scaled GC
        interval made explicit): past the high watermark run an
        emergency sweep — expiry GC first, then soonest-to-expire
        eviction down to the low watermark — with adaptive backoff so
        sustained pressure can't turn every batch into a GC storm."""
        import time as _time

        cap = self.ct.max_entries or 1
        occupancy = len(self.ct.entries) / cap
        metrics.ct_occupancy.set(value=occupancy)
        if occupancy < self.ct_high_watermark:
            self._ct_gc_backoff = self._ct_gc_backoff_base
            return
        now = _time.monotonic()
        if now < self._ct_gc_not_before:
            return
        expired = self.ct.gc(now=self.ct.now())
        target = int(cap * self.ct_low_watermark)
        evicted = self.ct.evict_to(target)
        metrics.ct_emergency_gc_total.inc()
        metrics.ct_occupancy.set(value=len(self.ct.entries) / cap)
        # adaptive backoff: each consecutive emergency sweep doubles
        # the spacing (an ineffective sweep repeated immediately only
        # burns the hot path); any drop below the high watermark
        # resets it
        self._ct_gc_not_before = now + self._ct_gc_backoff
        self._ct_gc_backoff = min(
            self._ct_gc_backoff * 2, self._ct_gc_backoff_max
        )
        from cilium_tpu.monitor.events import AgentNotify

        self.monitor.publish(
            AgentNotify(
                kind="ct-emergency-gc",
                text=(
                    f"occupancy {occupancy:.2f}: expired {expired}, "
                    f"evicted {evicted}"
                ),
            )
        )
        log.warning(
            "CT high watermark: emergency GC",
            extra={"fields": {
                "occupancy": round(occupancy, 3),
                "expired": expired,
                "evicted": evicted,
                "next_backoff_s": self._ct_gc_backoff,
            }},
        )

    # -- resilience (circuit breaker / degraded serving) ---------------------

    def _breaker_event(
        self, name: str, old: str, new: str, reason: str
    ) -> None:
        """CircuitBreaker transition listener: gauge + monitor event
        + log — breaker state is observable through every plane the
        telemetry PR wired (Prometheus, `cilium monitor`, agent
        log)."""
        from cilium_tpu.monitor.events import AgentNotify

        metrics.breaker_state.set(name, value=STATE_CODES[new])
        self.monitor.publish(
            AgentNotify(
                kind="circuit-breaker",
                text=f"{name}: {old} -> {new} ({reason})",
            )
        )
        log.warning(
            "circuit breaker transition",
            extra={"fields": {
                "breaker": name,
                "from": old,
                "to": new,
                "reason": reason,
            }},
        )

    def attach_mesh_router(
        self,
        router,
        route_dispatch: bool = True,
        auto_publish: bool = True,
    ) -> None:
        """Adopt a ChipFailoverRouter (engine/failover.py): per-chip
        breaker transitions publish AgentNotify monitor events beside
        the router's own gauge/span-event wiring, and health() gains
        per-chip degraded reasons — a mesh losing one chip reports
        WHICH ordinal is out, not just "degraded".

        With `route_dispatch` (default) the daemon's PRODUCTION
        dispatch loop also routes every batch through the router —
        survivor re-split, replica gathers and per-chip breakers
        serve the stream instead of the single-chip program — once
        the router holds a published epoch; until then, and on any
        router error, batches fall back to the single-chip path
        under the process-wide breaker.

        With `auto_publish` (default) the router's published tables
        TRACK daemon regenerates automatically: every device-epoch
        publish the endpoint manager performs also lands in the
        router's replica store, with a delta computed against the
        ROUTER store's own standby stamp (its epoch cadence differs
        from the manager store's) — no operator publish.  The
        current published tables, if any, are pushed immediately, so
        attaching to a warm daemon engages mesh routing on the very
        next batch."""
        from cilium_tpu.monitor.events import AgentNotify

        self.mesh_router = router
        self.mesh_route_dispatch = route_dispatch
        outer = router._on_chip_transition

        def _notify(ordinal, old, new, reason):
            self.monitor.publish(
                AgentNotify(
                    kind="chip-breaker",
                    text=(
                        f"chip {ordinal}: {old} -> {new} ({reason})"
                    ),
                )
            )
            if outer is not None:
                outer(ordinal, old, new, reason)

        router._on_chip_transition = _notify
        if not auto_publish:
            return

        def _sync_router(tables):
            """Publish a fresh host compile into the router's
            replica store, delta-scoped against ITS standby."""
            try:
                delta = self.endpoint_manager.delta_for(
                    router.store.spare_stamp(), tables
                )
            except Exception:  # pragma: no cover — compiler churn
                delta = None
            try:
                _, stats = router.publish(tables, delta)
                metrics.table_publish_total.inc(
                    f"router_{stats.mode}"
                )
            except Exception as exc:  # noqa: BLE001
                log.warning(
                    "router auto-publish failed; mesh routing will "
                    "serve the previous epoch",
                    extra={"fields": {"error": str(exc)}},
                )
                return
            if router.dp_store is None:
                return
            # the fused plane tracks regenerates too: rebuild the
            # datapath world from live daemon state (the new policy
            # tables + current ipcache/CT/LB) and republish — the
            # row-diff store keeps it a delta, so fused serving
            # never answers with pre-regenerate policy
            try:
                _, dstats = router.publish_datapath(
                    self.datapath_tables(policy=tables)
                )
                metrics.table_publish_total.inc(
                    f"datapath_{dstats.mode}"
                )
            except Exception as exc:  # noqa: BLE001
                log.warning(
                    "fused datapath auto-publish failed; fused "
                    "serving will use the previous epoch",
                    extra={"fields": {"error": str(exc)}},
                )

        self.endpoint_manager.on_device_publish = _sync_router
        version, tables, _index = self.endpoint_manager.published()
        if tables is not None:
            _sync_router(tables)

    def reshard_mesh(
        self,
        target_tp: int,
        step_bytes: Optional[int] = None,
        on_fault: str = "complete",
        plane=None,
        max_steps: int = 1 << 16,
    ) -> Dict:
        """Live elastic reshard of the attached mesh router's table
        axis to `target_tp` columns — stop-free: the live epoch
        serves throughout; moved rows stream into a staged
        target-layout epoch in bounded-byte steps
        (engine/reshard.ReshardPlan), and the cutover flips epochs
        between batches (via `plane.run_at_batch_boundary` when a
        ServingPlane is passed).  While the migration window is
        open, every auto-publish the endpoint manager performs is
        DUAL-APPLIED: the store's relayout-aware publish patches the
        live epoch in place (non-donated — zero drain) and the plan
        folds the same change into the staged target host, so churn
        never blocks a reshard and a reshard never loses churn.
        Returns the plan's stats dict ({outcome, steps, bytes_h2d,
        ms, restarts, dead_cols})."""
        from cilium_tpu.engine import reshard as reshard_mod

        router = self.mesh_router
        if router is None:
            raise RuntimeError(
                "no mesh router attached; call attach_mesh_router "
                "first"
            )
        target_mesh = reshard_mod.reshard_target_mesh(
            router, target_tp
        )
        dtables = (
            self.datapath_tables()
            if router.dp_store is not None else None
        )
        kwargs = {} if step_bytes is None else {
            "step_bytes": int(step_bytes)
        }
        plan = reshard_mod.ReshardPlan(
            router, target_mesh, on_fault=on_fault,
            dtables=dtables, shadow=self.shadow, **kwargs,
        )
        prev = self.endpoint_manager.on_device_publish

        def _dual_apply(tables):
            # live-epoch patch first (the relayout-aware store
            # path), then fold the same world into the staged target
            if prev is not None:
                prev(tables)
            if plan.state == "migrating":
                dt = (
                    self.datapath_tables(policy=tables)
                    if router.dp_store is not None else None
                )
                plan.on_publish(tables, dtables=dt)

        self.endpoint_manager.on_device_publish = _dual_apply
        try:
            plan.begin()
            steps = 0
            while plan.state == "migrating":
                if plan.pending():
                    plan.step()
                    steps += 1
                    if steps > max_steps:
                        plan.rollback(reason="max_steps exceeded")
                elif plane is not None:
                    plane.run_at_batch_boundary(plan.cutover)
                else:
                    plan.cutover()
        finally:
            self.endpoint_manager.on_device_publish = prev
        return dict(plan.stats)

    def _ensure_verdict_cache(self, tables):
        """The daemon's VerdictCache, stamped to the tables about to
        be dispatched: the stamp is (publish generation, table
        layout), so any publish / repack flushes before a stale
        verdict could be served.  Returns (cache, stamp) — the
        dispatch binds its probe AND its write-back to this stamp —
        or (None, None) when the feature was disabled after this
        batch's target selection: re-creating the cache here would
        silently undo `PATCH /config {"verdict_cache": false}`'s
        promise to drop the device buffer."""
        import numpy as np

        from cilium_tpu.compiler.tables import tables_layout_version
        from cilium_tpu.engine import memo as vm

        if not self.verdict_cache_enabled:
            return None, None
        if self.verdict_cache is None:
            self.verdict_cache = vm.VerdictCache(
                n_rows=self.verdict_cache_rows
            )
        gen = int(np.asarray(tables.generation)) & 0xFFFFFFFF
        stamp = (gen, tables_layout_version(tables))
        self.verdict_cache.ensure(stamp)
        return self.verdict_cache, stamp

    def _memo_evaluate(self, tables, batch):
        """Memoized lattice dispatch (engine/memo.py): intra-batch
        dedup + the device verdict cache in front of evaluate_batch,
        bit-identical by construction.  Returns a Verdicts-like
        namespace carrying the per-tuple `cache_hit` column the flow
        plane records and the DEVICE stats row (`cache_stats`).

        NO host read happens here — the double-buffered pipeline
        keeps its host-pack/device-compute overlap.  The drain (one
        batch behind, where the verdict columns sync anyway) folds
        the stats exactly once per served batch, corrects hit/miss
        accounting to the batch's valid prefix (padding rows all
        share one key and would drown the metrics in synthetic
        hits), and — when the kernel REFUSED the batch because it
        held more distinct policy keys than the compaction capacity
        — re-dispatches it through the uncached program.  The
        commit below is safe in that case: the kernel returns the
        carried cache unchanged on overflow by construction.

        Concurrency safety: the probe and the write-back are both
        bound to OUR tables' epoch stamp — `acquire()` reads
        (stamp, rows) atomically (a concurrent publish between
        ensure and the read hands us another epoch's cache, so we
        bypass memoization for this batch) and `commit()` refuses
        the write-back when a publish flushed mid-dispatch, so
        pre-publish entries can never resurrect under the new
        stamp."""
        from types import SimpleNamespace

        import numpy as np

        from cilium_tpu.engine import memo as vm

        b = int(batch.ep_index.shape[0])
        rep_cap = max(b >> self.verdict_cache_rep_shift, min(b, 1 << 10))
        self._memo_batch_seq += 1
        backoff = (
            self.verdict_cache_overflow_streak
            >= self.verdict_cache_streak_limit
            and self._memo_batch_seq % self.verdict_cache_retry_period
        )
        cache, stamp = self._ensure_verdict_cache(tables)
        if cache is None:  # disabled mid-flight
            out = self._traced_evaluate(tables, batch)
            return SimpleNamespace(
                allowed=out.allowed,
                proxy_port=out.proxy_port,
                match_kind=out.match_kind,
                cache_hit=np.zeros(b, bool),
            )
        cur_stamp, rows_in = cache.acquire()
        if backoff or cur_stamp != stamp:
            out = self._traced_evaluate(tables, batch)
            return SimpleNamespace(
                allowed=out.allowed,
                proxy_port=out.proxy_port,
                match_kind=out.match_kind,
                cache_hit=np.zeros(b, bool),
            )
        kernel = vm.memo_evaluate_kernel(rep_cap=rep_cap)
        v, rows, hit, stats = kernel(tables, batch, rows_in)
        # memo.insert fault seam — the write-back commit is the
        # verdict-cache insert path's host half.  The fault
        # PROPAGATES (never swallowed here): guarded_dispatch retries
        # re-run the memoized attempt (kernel not donated, carried
        # cache untouched), and a persistent schedule exhausts them
        # into the dispatch breaker whose host fold serves the batch
        # bit-identically; the dispatch-failure handler flushes the
        # cache, so no partial insert can outlive the fault.
        from cilium_tpu import faultinject

        try:
            faultinject.fire("memo.insert")
        except faultinject.FaultInjected:
            metrics.memo_insert_faults_total.inc()
            raise
        cache.commit(stamp, rows)
        return SimpleNamespace(
            allowed=v.allowed,
            proxy_port=v.proxy_port,
            match_kind=v.match_kind,
            cache_hit=hit,
            cache_stats=stats,
        )

    def _fold_memo_drain(
        self, cache_stats, v, valid, padded_len, redispatch
    ):
        """THE drain-time memo fold, shared by the one-shot drain
        (_process_flows_traced._drain_oldest) and the serving
        plane's drain (serve.ServingPlane._complete) so the two can
        never diverge: when the kernel REFUSED the batch (more
        distinct keys than the compaction capacity — its verdict
        columns are unspecified, carried cache state untouched) the
        batch re-dispatches through `redispatch()` (a thunk running
        the uncached program, returning (out, degraded)); otherwise
        hit/miss accounting lands exactly once, corrected to the
        batch's valid prefix (padding rows all share one key and
        would drown the metrics in synthetic hits).  Returns
        (v, extra_degraded, overflowed) — a caller holding a shadow
        sample REFUSES it when `overflowed` (the on-device diff was
        computed against the refused kernel's unspecified columns,
        so folding it would not be a two-pinned-worlds diff)."""
        from types import SimpleNamespace

        import numpy as np

        from cilium_tpu.engine import memo as vm

        s = np.asarray(cache_stats).astype(np.int64)
        deg = False
        overflowed = bool(int(s[vm.STAT_OVERFLOW]))
        if overflowed:
            self.verdict_cache_overflow_streak += 1
            out2, deg = redispatch()
            v = SimpleNamespace(
                allowed=np.asarray(out2.allowed)[:valid],
                match_kind=np.asarray(out2.match_kind)[:valid],
                proxy_port=np.asarray(out2.proxy_port)[:valid],
                cache_hit=np.zeros(valid, bool),
            )
        else:
            self.verdict_cache_overflow_streak = 0
            if valid < int(padded_len):
                s = s.copy()
                s[vm.STAT_HIT] = int(v.cache_hit.sum())
                s[vm.STAT_TUPLES] = int(valid)
        if self.verdict_cache is not None:
            self.verdict_cache.account(s)
        return v, deg, overflowed

    # -- shadow policy rollout (cilium_tpu.shadow) ----------------------------

    @staticmethod
    def _attach_shadow(out, ticket, scols):
        """Wrap a single-chip dispatch result with its shadow sample
        (lazy shadow columns + on-device diff codes): the drain folds
        or refuses the ticket exactly once."""
        from types import SimpleNamespace

        return SimpleNamespace(
            allowed=out.allowed,
            proxy_port=out.proxy_port,
            match_kind=out.match_kind,
            cache_hit=getattr(out, "cache_hit", None),
            cache_stats=getattr(out, "cache_stats", None),
            shadow_ticket=ticket,
            shadow_cols=scols,
        )

    def _attach_shadow_routed(self, out, res, ticket):
        """The mesh-path twin of _attach_shadow: the router already
        synced both legs' columns, so the diff codes fold host-side
        through the SAME diff_codes definition the device kernel
        jits."""
        from types import SimpleNamespace

        import numpy as np

        from cilium_tpu import shadow as shadow_mod

        sv = res.shadow_verdicts
        if sv is None:
            self.shadow.refuse(ticket)
            return out
        ca, cp, ck, trans = shadow_mod.diff_codes(
            np.asarray(out.allowed),
            np.asarray(out.proxy_port),
            np.asarray(out.match_kind),
            np.asarray(sv.allowed),
            np.asarray(sv.proxy_port),
            np.asarray(sv.match_kind),
            xp=np,
        )
        return SimpleNamespace(
            allowed=out.allowed,
            proxy_port=out.proxy_port,
            match_kind=out.match_kind,
            shadow_ticket=ticket,
            shadow_cols={
                "allowed": sv.allowed,
                "proxy_port": sv.proxy_port,
                "match_kind": sv.match_kind,
                "ca": ca,
                "cp": cp,
                "ck": ck,
                "trans": trans,
            },
        )

    def _fold_shadow_drain(
        self, out, v, valid, *, ep_ids, src_identities,
        dst_identities, dports, protos, directions, tenant,
        trace_id, refuse=False,
    ):
        """THE drain-time shadow fold, shared by the one-shot drain
        and the serving plane's drain: folds (or refuses) a sampled
        batch's ticket exactly once and returns the per-row
        transition codes (np.uint8, 0 = unchanged) for the flow
        plane's diff-status join — None when unsampled/refused.
        ``refuse`` forces a clean refusal (drain-time failover or a
        memo overflow re-dispatch invalidated the on-device diff)."""
        ticket = getattr(out, "shadow_ticket", None)
        if ticket is None:
            return None
        scols = getattr(out, "shadow_cols", None)
        if refuse or scols is None:
            self.shadow.refuse(ticket)
            return None
        try:
            return self.shadow.fold(
                ticket, v, scols, valid,
                ep_ids=ep_ids,
                src_identities=src_identities,
                dst_identities=dst_identities,
                dports=dports,
                protos=protos,
                directions=directions,
                tenant=tenant,
                trace_id=trace_id,
            )
        except Exception as exc:  # noqa: BLE001 — the shadow fold
            # must never take the live drain down
            log.warning(
                "shadow diff fold failed; sample refused",
                extra={"fields": {"error": str(exc)}},
            )
            self.shadow.refuse(ticket)
            return None

    def _dispatch_or_degrade(
        self, tables, batch, host_args, pad_to: int,
        use_memo: bool = True, host_cols=None,
        shadow_sample: bool = True,
    ):
        """One batch through the guarded device dispatch: the
        engine.dispatch fault seam fires first, the watchdog bounds
        the launch, retry_call absorbs transient failures (counted in
        dispatch_retries_total), and the circuit breaker decides
        admission.  On breaker-open or exhausted retries the batch is
        served by the bit-identical host lattice fold
        (engine.hostpath.lattice_fold_host) — the stream completes,
        degraded_batches_total counts the failover.

        Mesh routing: when a ChipFailoverRouter is attached with
        route_dispatch and holds a published epoch, the batch goes
        THROUGH the per-chip failure domain instead — `host_cols`
        (a thunk returning the UNPADDED host tuple columns) feeds
        router.dispatch, whose verdicts come back in stream order
        and bit-identical whatever the survivor set; a router error
        falls back to the single-chip path below.

        Returns (verdicts, degraded flag); verdicts satisfy the
        Verdicts contract (allowed/proxy_port/match_kind, padded on
        the single-chip path, exactly valid-length on the mesh
        path — callers slice [:valid] either way).

        Span-plane attribution: the device attempt runs under an
        `engine.dispatch` span (error status + breaker events when it
        fails, per-chip children when it succeeds); the failover fold
        runs under `engine.hostpath` — one trace shows which plane
        served the batch and why."""
        from cilium_tpu.engine.hostpath import lattice_fold_host
        from cilium_tpu.engine.verdict import evaluate_batch
        from cilium_tpu.resilience import guarded_dispatch

        if (
            self.mesh_router is not None
            and self.mesh_route_dispatch
            and host_cols is not None
            and self.mesh_router.store.current() is not None
        ):
            # shadow sampling on the routed path: the pinned-stamp
            # ticket is drawn against the manager's published epoch
            # (the stamp family the arm pinned); the shadow gather
            # rides the router's re-split batch through the routed
            # evaluators (dispatch(shadow=...)).  Drain-time
            # re-dispatches pass shadow_sample=False — their batch's
            # ticket already exists and must resolve exactly once.
            ticket = (
                self.shadow.sample_ticket(tables)
                if shadow_sample
                else None
            )
            shadow_args = None
            if ticket is not None:
                # the router serves ITS store's current epoch, which
                # the auto-publish hook advances independently of the
                # `tables` snapshot the ticket was drawn against: the
                # router stamp must match the pinned live stamp both
                # BEFORE and AFTER the dispatch, else the live leg
                # may have served a third world — refuse the sample
                # (stamps only move forward, so an equal bracket
                # pins the served epoch exactly)
                rstamp = self.mesh_router.store.current_stamp()
                if (
                    rstamp is None
                    or (int(rstamp) & 0xFFFFFFFF)
                    != ticket["live_gen"]
                ):
                    self.shadow.refuse(ticket)
                    ticket = None
            if ticket is not None:
                try:
                    shadow_args = self.shadow.routed_args(
                        self.mesh_router
                    )
                except Exception as exc:  # noqa: BLE001
                    log.warning(
                        "shadow routed epoch unavailable; sample "
                        "refused",
                        extra={"fields": {"error": str(exc)}},
                    )
                    self.shadow.refuse(ticket)
                    ticket = None
            try:
                res = self.mesh_router.dispatch(
                    *host_cols(), shadow=shadow_args
                )
            except Exception as exc:  # router unserviceable: fall
                # back to the single-chip path under the
                # process-wide breaker (the router's own terminal
                # fold only fires when it CAN host-fold)
                if ticket is not None:
                    self.shadow.refuse(ticket)
                log.warning(
                    "mesh router dispatch failed; serving batch "
                    "from the single-chip path",
                    extra={"fields": {"error": str(exc)}},
                )
            else:
                if res.degraded:
                    self.degraded_batches += 1
                out = res.verdicts
                if ticket is not None:
                    # the AFTER half of the stamp bracket: a publish
                    # that advanced the router mid-dispatch makes
                    # which epoch served ambiguous — refuse
                    rstamp = self.mesh_router.store.current_stamp()
                    if (
                        res.degraded
                        or rstamp is None
                        or (int(rstamp) & 0xFFFFFFFF)
                        != ticket["live_gen"]
                    ):
                        self.shadow.refuse(ticket)
                    else:
                        out = self._attach_shadow_routed(
                            out, res, ticket
                        )
                return out, res.degraded
        if self._traced_evaluate is None:
            # jit-cache hit/miss accounting on the serving entry
            # point (a fresh batch shape class = an XLA recompile the
            # stream waits for)
            self._traced_evaluate = tracing.track_jit(
                evaluate_batch, "engine.dispatch"
            )
        target = (
            self._memo_evaluate
            if (self.verdict_cache_enabled and use_memo)
            else self._traced_evaluate
        )
        if self.dispatch_breaker.allow():
            with self.tracer.span(
                "engine.dispatch", site="engine.dispatch"
            ) as sp:
                try:
                    out = guarded_dispatch(
                        target,
                        tables,
                        batch,
                        retries=self.dispatch_retries,
                        base_delay=self.dispatch_retry_base,
                        watchdog=self.dispatch_watchdog,
                    )
                    self.dispatch_breaker.record_success()
                    dispatched = out
                except Exception as exc:
                    sp.status = "error"
                    sp.attrs["error"] = str(exc)
                    # a memoized attempt may have committed lazy
                    # rows tied to the failed computation
                    if use_memo and self.verdict_cache is not None:
                        self.verdict_cache.flush(
                            reason="dispatch-failure"
                        )
                    self.dispatch_breaker.record_failure(str(exc))
                    log.warning(
                        "device dispatch failed; serving batch from "
                        "host path",
                        extra={"fields": {"error": str(exc)}},
                    )
                    dispatched = None
            if dispatched is not None:
                tracing.record_chip_spans(
                    self.tracer, sp, 1,
                    int(batch.ep_index.shape[0]), "engine.dispatch",
                )
                # shadow sampling (single-chip path): the SECOND
                # dispatch rides the already-staged TupleBatch
                # against the shadow epoch, diffed on device
                # (shadow.dispatch / shadow.diff spans nest under
                # this batch's dispatch span); columns stay lazy —
                # the drain folds them one batch behind.  Drain-time
                # re-dispatches never draw a second ticket.
                ticket = (
                    self.shadow.sample_ticket(tables)
                    if shadow_sample
                    else None
                )
                if ticket is not None:
                    scols = self.shadow.evaluate(
                        ticket, batch, dispatched
                    )
                    if scols is not None:
                        dispatched = self._attach_shadow(
                            dispatched, ticket, scols
                        )
                return dispatched, False
        with self.tracer.span(
            "engine.hostpath", site="engine.hostpath",
            attrs={"failover": True},
        ):
            states, ep_index, identity, dport, proto, direction, frag = (
                host_args()
            )
            out = lattice_fold_host(
                states, ep_index, identity, dport, proto, direction,
                is_fragment=frag, pad_to=pad_to,
            )
        self.degraded_batches += 1
        metrics.degraded_batches_total.inc()
        return out, True

    def service_upsert(
        self, frontend, backends
    ):
        """PUT /service (daemon/loadbalancer.go SVCAdd)."""
        with self.lock:
            svc = self.services.upsert(frontend, backends)
        return svc

    def service_delete(self, frontend) -> bool:
        with self.lock:
            return self.services.delete(frontend)

    def config_patch(self, changes: Dict) -> Dict:
        """PATCH /config (daemon config handler + pkg/option runtime
        options): apply named boolean option changes and the mutable
        enforcement mode; verdict-affecting changes trigger a full
        regeneration, exactly as the reference recompiles on config
        change (config IS part of the compiled program — the options
        feed the compiler cache key)."""
        from cilium_tpu import faultinject

        applied = 0
        verdict_affecting = False
        with self.lock:
            # validate EVERYTHING before mutating anything: a partial
            # apply followed by a 400 would silently diverge daemon
            # state from what the client believes.  Validation is the
            # option LIBRARY's parse+verify (option.go ParseOption):
            # booleans for most options, level names/ints for
            # MonitorAggregationLevel, NAT46's unsupported-gate, etc.
            raw_opts = changes.get("options") or {}
            for k, v in raw_opts.items():
                option.Config.opts.parse_validate(k, v)
            # fault-site arming ({"faults": {site: spec | null}}) —
            # the config_patch surface of the chaos framework;
            # validated up front like the options
            raw_faults = changes.get("faults") or {}
            parsed_faults = {}
            for site, spec in raw_faults.items():
                if site not in faultinject.SITES:
                    raise ValueError(
                        f"unknown fault site {site!r}"
                    )
                parsed_faults[site] = (
                    None
                    if spec is None
                    else faultinject.FaultSpec.parse(spec)
                )
            enforcement = changes.get("policy_enforcement")
            if enforcement is not None and enforcement not in (
                option.DEFAULT_ENFORCEMENT,
                option.ALWAYS_ENFORCE,
                option.NEVER_ENFORCE,
            ):
                raise ValueError(
                    f"unknown enforcement mode {enforcement!r}"
                )
            verdict_cache = changes.get("verdict_cache")
            if verdict_cache is not None and not isinstance(
                verdict_cache, bool
            ):
                raise ValueError(
                    "verdict_cache must be a boolean, got "
                    f"{verdict_cache!r}"
                )
            online_retune = changes.get("online_retune")
            if online_retune is not None and not isinstance(
                online_retune, bool
            ):
                raise ValueError(
                    "online_retune must be a boolean, got "
                    f"{online_retune!r}"
                )
            # serving-plane tenant fairness weights ({"tenant_
            # weights": {name: weight}}): validated up front like
            # the options; weight must be a positive number
            tenant_weights = changes.get("tenant_weights")
            if tenant_weights is not None:
                if not isinstance(tenant_weights, dict):
                    raise ValueError(
                        "tenant_weights must be an object of "
                        f"name: weight, got {tenant_weights!r}"
                    )
                for name, w in tenant_weights.items():
                    if (
                        isinstance(w, bool)
                        or not isinstance(w, (int, float))
                        or w <= 0
                    ):
                        raise ValueError(
                            f"tenant weight {name!r} must be a "
                            f"positive number, got {w!r}"
                        )
            # named SLO classes ({"slo_classes": {name: {deadline_ms,
            # shed_priority, weight} | null}}) + tenant assignment
            # ({"tenant_slo": {tenant: class | null}}): validated up
            # front; null deletes
            slo_classes = changes.get("slo_classes")
            if slo_classes is not None:
                if not isinstance(slo_classes, dict):
                    raise ValueError(
                        "slo_classes must be an object of name: "
                        f"bundle, got {slo_classes!r}"
                    )
                for cname, bundle in slo_classes.items():
                    if bundle is None:
                        continue
                    if not isinstance(bundle, dict):
                        raise ValueError(
                            f"slo class {cname!r} must be an "
                            f"object, got {bundle!r}"
                        )
                    unknown = set(bundle) - {
                        "deadline_ms", "shed_priority", "weight",
                    }
                    if unknown:
                        raise ValueError(
                            f"slo class {cname!r}: unknown keys "
                            f"{sorted(unknown)}"
                        )
                    dl = bundle.get("deadline_ms")
                    if dl is not None and (
                        isinstance(dl, bool)
                        or not isinstance(dl, (int, float))
                        or dl <= 0
                    ):
                        raise ValueError(
                            f"slo class {cname!r}: deadline_ms "
                            f"must be a positive number, got {dl!r}"
                        )
                    pr = bundle.get("shed_priority")
                    if pr is not None and (
                        isinstance(pr, bool)
                        or not isinstance(pr, int)
                        or pr < 0
                    ):
                        raise ValueError(
                            f"slo class {cname!r}: shed_priority "
                            f"must be an int >= 0, got {pr!r}"
                        )
                    w = bundle.get("weight")
                    if w is not None and (
                        isinstance(w, bool)
                        or not isinstance(w, (int, float))
                        or w <= 0
                    ):
                        raise ValueError(
                            f"slo class {cname!r}: weight must be "
                            f"a positive number, got {w!r}"
                        )
            tenant_slo = changes.get("tenant_slo")
            if tenant_slo is not None:
                if not isinstance(tenant_slo, dict):
                    raise ValueError(
                        "tenant_slo must be an object of tenant: "
                        f"class, got {tenant_slo!r}"
                    )
                future_classes = dict(self.slo_classes)
                for cname, bundle in (slo_classes or {}).items():
                    if bundle is None:
                        future_classes.pop(cname, None)
                    else:
                        future_classes[cname] = bundle
                for tname, cname in tenant_slo.items():
                    if cname is not None and (
                        not isinstance(cname, str)
                        or cname not in future_classes
                    ):
                        raise ValueError(
                            f"tenant {tname!r} references unknown "
                            f"slo class {cname!r}"
                        )
            if raw_opts:
                ct_before = option.Config.opts.is_enabled(
                    option.CONNTRACK
                )
                applied += option.Config.opts.apply(
                    dict(raw_opts), changed_hook=self._option_changed
                )
                # conntrack on/off changes verdict semantics
                # (REPLY/RELATED bypass exists only with CT) — and it
                # can flip via DEPENDENCY propagation (enabling
                # ConntrackAccounting enables Conntrack), so compare
                # states instead of checking the request keys
                if option.Config.opts.is_enabled(
                    option.CONNTRACK
                ) != ct_before:
                    verdict_affecting = True
            if enforcement is not None:
                if option.Config.policy_enforcement != enforcement:
                    option.Config.policy_enforcement = enforcement
                    applied += 1
                    verdict_affecting = True
            # verdict memoization toggle: bit-identical by
            # construction, so no regeneration sweep (counted after
            # the regen trigger below); disabling drops the cache
            # (and its HBM) immediately
            vc_applied = 0
            if (
                verdict_cache is not None
                and verdict_cache != self.verdict_cache_enabled
            ):
                self.verdict_cache_enabled = verdict_cache
                if not verdict_cache:
                    self.verdict_cache = None
                vc_applied = 1
            # online re-tune arming: verdict-neutral (the swap
            # itself is bit-identical by the layout-stamp seam)
            if (
                online_retune is not None
                and online_retune != self.online_retune_enabled
            ):
                self.online_retune_enabled = online_retune
                vc_applied += 1
            # fairness weights apply immediately to the live plane
            # (verdict-neutral — no regeneration)
            tw_applied = 0
            if tenant_weights is not None:
                for name, w in tenant_weights.items():
                    if self.tenant_weights.get(name) != float(w):
                        tw_applied += 1
                    self.tenant_weights[name] = float(w)
                if self.serving is not None:
                    self.serving.set_tenant_weights(
                        self.tenant_weights
                    )
            # SLO classes + tenant assignment: live-applied like the
            # weights (verdict-neutral)
            slo_applied = 0
            if slo_classes is not None:
                for cname, bundle in slo_classes.items():
                    if bundle is None:
                        if self.slo_classes.pop(cname, None):
                            slo_applied += 1
                    elif self.slo_classes.get(cname) != bundle:
                        self.slo_classes[cname] = dict(bundle)
                        slo_applied += 1
            if tenant_slo is not None:
                for tname, cname in tenant_slo.items():
                    if cname is None:
                        if self.tenant_slo.pop(tname, None):
                            slo_applied += 1
                    elif self.tenant_slo.get(tname) != cname:
                        self.tenant_slo[tname] = cname
                        slo_applied += 1
            if (
                (slo_classes is not None or tenant_slo is not None)
                and self.serving is not None
            ):
                self.serving.set_slo_classes(
                    self.slo_classes, self.tenant_slo
                )
            # fault arming applies last and never triggers a regen
            # sweep (it changes no compiled state)
            fault_applied = 0
            for site, spec in parsed_faults.items():
                if spec is None:
                    if faultinject.disarm(site):
                        fault_applied += 1
                else:
                    faultinject.arm(site, spec)
                    fault_applied += 1
        if applied:
            # enforcement changes alter verdicts → full sweep; pure
            # observability toggles (tracing, notifications) do not
            self.trigger_policy_updates(
                "configuration changed", full=verdict_affecting
            )
        applied += fault_applied + vc_applied + tw_applied
        applied += slo_applied
        return {
            "applied": applied,
            "policy_enforcement": option.Config.policy_enforcement,
            "options": dict(option.Config.opts),
            "faults": faultinject.armed(),
            "verdict_cache": self.verdict_cache_enabled,
            "online_retune": self.online_retune_enabled,
            "tenant_weights": dict(self.tenant_weights),
            "slo_classes": dict(self.slo_classes),
            "tenant_slo": dict(self.tenant_slo),
        }

    def _option_changed(self, name: str, value: int) -> None:
        """Behavioral hooks behind runtime options (the analog of the
        reference regenerating datapath programs whose #defines
        changed): logging levels flip immediately; disabling
        conntrack flushes the table the way the agent tears down CT
        state when CONNTRACK is compiled out."""
        import logging as _pylogging

        from cilium_tpu import logging as tpulog

        if name == option.DEBUG:
            tpulog.set_level(
                _pylogging.DEBUG if value else _pylogging.INFO
            )
        elif name == option.DEBUG_LB:
            tpulog.set_level(
                _pylogging.DEBUG if value else _pylogging.INFO,
                subsys="lb",
            )
        elif name == option.CONNTRACK_ACCOUNTING:
            self.ct.accounting = bool(value)
        elif name == option.CONNTRACK and not value:
            self.ct.entries.clear()
            self.ct.mutations += 1

    def endpoint_config_patch(
        self, endpoint_id: int, changes: Dict
    ) -> Dict:
        """`cilium endpoint config` (pkg/endpoint applyOptsLocked):
        apply per-endpoint option changes and queue THAT endpoint's
        regeneration — per-endpoint config is compiled state in the
        reference (it lands in the generated header)."""
        opts = changes.get("options") or {}
        for k, v in opts.items():
            option.Config.opts.parse_validate(k, v)
        with self.lock:
            endpoint = self.endpoint_manager.lookup(endpoint_id)
            if endpoint is None:
                raise KeyError(f"no endpoint {endpoint_id}")
            with endpoint.lock:
                applied = endpoint.opts.apply(dict(opts))
            if applied:
                # force THIS endpoint's recompute through the delta
                # sweep (the revision gate would skip it otherwise) —
                # a per-endpoint toggle must not recompile the fleet
                endpoint.force_policy_compute = True
        if applied:
            self.trigger_policy_updates(
                f"endpoint {endpoint_id} config changed"
            )
        return {
            "applied": applied,
            "options": dict(endpoint.opts),
        }

    def verdict_notification_endpoints(self) -> set:
        """Endpoint ids with per-endpoint PolicyVerdictNotification on
        (plus all when the global option is set): the monitor fold's
        allowed-verdict scope."""
        from cilium_tpu.option import POLICY_VERDICT_NOTIFICATION

        eps = self.endpoint_manager.endpoints()
        if option.Config.opts.is_enabled(POLICY_VERDICT_NOTIFICATION):
            return {ep.id for ep in eps}
        return {
            ep.id
            for ep in eps
            if ep.opts.is_enabled(POLICY_VERDICT_NOTIFICATION)
        }

    # -- serving-path building blocks (shared with cilium_tpu.serve) ---------

    def _resolve_serving_tables(self):
        """One serving snapshot: (version, dispatch tables, endpoint
        index, host map states) — the tables AND the states they were
        compiled from, read under one lock so the degraded host fold
        stays bit-identical to the device path whatever regenerations
        land mid-stream.  The dispatch tables are the device-resident
        epoch when publication succeeds (delta-scoped scatter for a
        policy change since the last call); a failed publication
        latches a 30 s backoff and dispatches the host arrays.
        Shared by process_flows and the serving plane's batch loop."""
        import time as _time

        version, tables, index, host_states = (
            self.endpoint_manager.published_with_states()
        )
        if tables is None:
            raise RuntimeError("no published tables")
        if _time.monotonic() >= self._device_publish_retry_at:
            try:
                # epoch lookup/publication under its own span: a
                # trace distinguishes "the batch was slow" from "the
                # batch paid a delta scatter / full upload first"
                with self.tracer.span(
                    "publish.epoch_lookup", site="engine.publish",
                    attrs={"version": version},
                ):
                    tables = self.endpoint_manager.device_tables_for(
                        tables
                    )
            except Exception as exc:  # device down → numpy tables
                self._device_publish_retry_at = (
                    _time.monotonic() + 30.0
                )
                log.warning(
                    "device table publication failed; dispatching "
                    "host arrays (retrying in 30s)",
                    extra={"fields": {"error": str(exc)}},
                )
        return version, tables, index, host_states

    def _flow_luts(self, index):
        """Endpoint-axis LUTs the verdict folds translate through:
        (local identity per axis slot, endpoint id per axis slot).
        Flow records orient each tuple as src→dst — the local
        endpoint is the DESTINATION of an ingress flow and the
        SOURCE of an egress one (the send_trace_notify convention).
        Shared by process_flows and the serving plane."""
        import numpy as np

        size = max(index.values(), default=0) + 1
        local_ident_lut = np.zeros(size, dtype=np.int64)
        rev_lut = np.zeros(size, dtype=np.int64)
        for ep_id, idx in index.items():
            rev_lut[idx] = ep_id
            ep = self.endpoint_manager.lookup(ep_id)
            if ep is not None and ep.security_identity is not None:
                local_ident_lut[idx] = ep.security_identity.id
        return local_ident_lut, rev_lut

    def _prefilter_records(
        self, rec, index, local_ident_lut, tenant="", trace_id="",
    ):
        """XDP prefilter over a decoded record SoA (the daemon-owned
        deny-by-CIDR set, bpf_xdp.c): flows from denied sources drop
        BEFORE the policy program, count under the canonical CIDR
        reason, and land in the flow plane as real drops.  Returns
        (filtered rec, n_prefiltered).  Shared by process_flows and
        the serving plane's submit path."""
        import numpy as np

        from cilium_tpu.flow import capture_batch
        from cilium_tpu.replay import _ep_index_of

        prefilter_cidrs = self.prefilter.dump()
        if not prefilter_cidrs:
            return rec, 0
        import ipaddress as _ipaddress

        from cilium_tpu.monitor.events import drop_reason_name

        hit = np.zeros(len(rec["saddr"]), bool)
        saddr = rec["saddr"].astype(np.uint64)
        for cidr in prefilter_cidrs:
            net = _ipaddress.ip_network(cidr, strict=False)
            if net.version != 4:
                continue
            hit |= (saddr & int(net.netmask)) == int(
                net.network_address
            )
        n_prefiltered = int(hit.sum())
        if not n_prefiltered:
            return rec, 0
        for dirv, dname in ((0, "INGRESS"), (1, "EGRESS")):
            count = int((hit & (rec["direction"] == dirv)).sum())
            if count:
                metrics.drop_count.inc(
                    drop_reason_name(-162), dname, value=count,
                )
        pre_idx = _ep_index_of(
            {"ep_id": rec["ep_id"][hit]}, dict(index)
        )
        pre_dirs = rec["direction"][hit]
        pre_peer = rec["identity"][hit].astype(np.int64)
        pre_local = local_ident_lut[pre_idx]
        capture_batch(
            self.flow_store,
            ep_ids=rec["ep_id"][hit],
            src_identities=np.where(
                pre_dirs == 0, pre_peer, pre_local
            ),
            dst_identities=np.where(
                pre_dirs == 0, pre_local, pre_peer
            ),
            dports=rec["dport"][hit],
            protos=rec["proto"][hit],
            directions=pre_dirs,
            allowed=np.zeros(n_prefiltered, bool),
            match_kind=np.zeros(n_prefiltered, np.int32),
            pre_dropped=np.ones(n_prefiltered, bool),
            allow_sample=0,
            metrics_registry=metrics,
            trace_id=trace_id,
            tenant=tenant,
        )
        rec = {k: v[~hit] for k, v in rec.items()}
        return rec, n_prefiltered

    def datapath_tables(self, policy=None, subword=None):
        """Assemble the FUSED DatapathTables from the daemon's
        current state — published policy tables + the ipcache
        listener's CIDR→identity view (idx-specialized) + the CT map
        snapshot + compiled services + the prefilter set.  This is
        the world ChipFailoverRouter.attach_datapath serves, and
        what the fused serving plane re-publishes on churn.

        `policy` pins the policy tables to an EXACT snapshot (the
        auto-publish listener passes the tables it just installed,
        so the router's lattice epoch and its fused epoch can never
        come from two different regenerates); None reads the current
        published tables.  The CT entry dict and the service map are
        shallow-snapshotted before compilation — the ct-gc
        controller thread mutates the live CTMap without the daemon
        lock, and iterating it directly would race.

        `subword` (default: the `datapath_subword` config option)
        applies the sub-word hot-lane transform
        (engine.datapath.subword_datapath_tables) to the assembled
        world — planes whose semantics don't fit their compact
        fields keep the wide layout.  The transform is a pure,
        deterministic function of the assembled tables, so the
        DatapathStore's row-diff delta still ships O(change) bytes
        through churn, and every width joins the layout stamp the
        store refuses cross-layout deltas on."""
        import copy

        from cilium_tpu.ct.device import compile_ct
        from cilium_tpu.engine.datapath import DatapathTables
        from cilium_tpu.ipcache.lpm import (
            build_ipcache,
            specialize_ipcache_to_idx,
        )
        from cilium_tpu.lb.device import compile_lb
        from cilium_tpu.prefilter import build_prefilter

        pol = policy
        if pol is None:
            _, pol, _ = self.endpoint_manager.published()
        if pol is None:
            raise RuntimeError("no published tables")
        with self.lock:
            mappings = dict(self.lpm_builder.mappings)
            prefilter_cidrs = self.prefilter.dump()
            services = copy.copy(self.services)
            services.by_frontend = dict(self.services.by_frontend)
        # dict() of the entries is atomic under the GIL; entry
        # values are only ever replaced, not mutated in the packed
        # fields, so the shallow snapshot is a consistent view
        ct_snap = copy.copy(self.ct)
        ct_snap.entries = dict(self.ct.entries)
        ipc = specialize_ipcache_to_idx(
            build_ipcache(mappings), pol
        )
        dt = DatapathTables(
            prefilter=build_prefilter(prefilter_cidrs),
            ipcache=ipc,
            ct=compile_ct(ct_snap),
            lb=compile_lb(services),
            policy=pol,
        )
        if subword is None:
            subword = bool(getattr(self, "datapath_subword", False))
        ct_lanes = getattr(self, "datapath_ct_lanes", None)
        if subword:
            from cilium_tpu.engine.datapath import (
                subword_datapath_tables,
            )

            dt, _report = subword_datapath_tables(
                dt, ct_lanes=ct_lanes
            )
        else:
            # plane-scoped lane overrides from the online autotuner
            # sweep (retune_candidates' CT/ipcache width grid) apply
            # without the global sub-word transform; a plane whose
            # semantics don't fit keeps its wide layout
            import dataclasses as _dc

            if ct_lanes:
                from cilium_tpu.ct.device import compact_ct_snapshot

                try:
                    dt = _dc.replace(
                        dt,
                        ct=compact_ct_snapshot(
                            dt.ct, lanes=int(ct_lanes)
                        ),
                    )
                except ValueError:
                    pass
            if getattr(self, "datapath_ip_subword", False):
                from cilium_tpu.ipcache.lpm import (
                    IPCacheDevice,
                    subword_ipcache,
                )

                if (
                    isinstance(dt.ipcache, IPCacheDevice)
                    and dt.ipcache.values_are_idx
                ):
                    try:
                        dt = _dc.replace(
                            dt, ipcache=subword_ipcache(dt.ipcache)
                        )
                    except ValueError:
                        pass
        return dt

    def serving_plane(self, **overrides):
        """The daemon's continuous serving plane
        (cilium_tpu.serve.ServingPlane), created and started on
        first use — the steady-state ingest pipeline behind
        `POST /datapath/flows?stream=1` and `cilium-tpu
        serve-bench`.  Constructor overrides apply only on first
        creation (the plane is one shared queue)."""
        with self.lock:
            if self.serving is None:
                from cilium_tpu.serve import ServingPlane

                self.serving = ServingPlane(
                    self,
                    tenant_weights=dict(self.tenant_weights),
                    slo_classes=dict(self.slo_classes),
                    tenant_slo=dict(self.tenant_slo),
                    **overrides,
                )
                self.serving.start()
            return self.serving

    def maybe_online_retune(self) -> "Optional[dict]":
        """The serving loop's retune poll (every 64 completed
        batches): delegate to engine.autotune.online_retune when the
        operator armed it, never concurrently, and never let a
        controller fault take down the serve loop — a missed retune
        is a performance bug, a dead plane is an outage."""
        if not self.online_retune_enabled:
            return None
        if not self._retune_inflight.acquire(blocking=False):
            return None  # one controller at a time
        try:
            from cilium_tpu.engine.autotune import online_retune

            return online_retune(
                self, config=self.online_retune_config
            )
        except Exception:
            log.exception("online retune failed (serve loop kept)")
            return None
        finally:
            self._retune_inflight.release()

    def _perf_byte_model(self, leaves: bool = False) -> Dict:
        """The gatherprof/autotune byte model evaluated LIVE: the
        published layout stamp's hot/cold bytes-per-tuple, shrunk by
        the OBSERVED verdict-cache dedup/hit factors, and priced
        into a modeled GB/s gauge at the perf plane's measured
        verdicts/s EWMA.  The per-leaf breakdown rides along on
        demand (`leaves=True` ≙ /debug/perf?leaves=1).  The static
        walk is cached per (generation, layout)."""
        from cilium_tpu.compiler.tables import tables_layout_version
        from cilium_tpu.engine import autotune

        gen, pol, _ = self.endpoint_manager.published()
        if pol is None:
            return {"published": False}
        layout = tables_layout_version(pol)
        cached = self._perf_model_cache
        if cached is None or cached[0] != (gen, layout):
            try:
                dt = self.datapath_tables(policy=pol)
            except Exception:
                return {"published": False}
            profile = autotune.hot_gather_profile(dt)
            hot = sum(
                r["bytes_per_tuple"] for r in profile
                if r["plane"] == "hot"
            )
            cold = sum(
                r["bytes_per_tuple"] for r in profile
                if r["plane"] == "cold"
            )
            cached = ((gen, layout), hot, cold, profile)
            self._perf_model_cache = cached
        _, hot, cold, profile = cached
        hits = metrics.verdict_cache_hits_total.get()
        misses = metrics.verdict_cache_misses_total.get()
        inserts = metrics.verdict_cache_insertions_total.get()
        lookups = hits + misses
        hit_rate = hits / lookups if lookups else 0.0
        # observed intra-batch dedup on the missed population:
        # tuples evaluated per representative inserted
        dedup = misses / inserts if inserts else 1.0
        effective = (
            hot / max(dedup, 1.0) * (1.0 - hit_rate)
            if lookups
            else hot
        )
        vps = self.perf.verdicts_per_sec()
        model = {
            "published": True,
            "generation": gen,
            "layout_stamp": layout,
            "hot_bytes_per_tuple": hot,
            "cold_bytes_per_tuple": cold,
            "effective_bytes_per_tuple": effective,
            "cache_hit_rate": hit_rate,
            "dedup_factor": max(dedup, 1.0),
            "modeled_gbps": effective * vps / 1e9,
        }
        metrics.perf_model_bytes_per_tuple.set("hot", value=hot)
        metrics.perf_model_bytes_per_tuple.set("cold", value=cold)
        metrics.perf_model_bytes_per_tuple.set(
            "effective", value=effective
        )
        metrics.perf_model_gbps.set(value=model["modeled_gbps"])
        if leaves:
            model["leaves"] = profile
        return model

    def perf_snapshot(
        self, since: "Optional[int]" = None, leaves: bool = False
    ) -> Dict:
        """GET /debug/perf — the live performance plane in one
        document: phase windows + stall/SLO ledger (PerfPlane
        .snapshot, since-cursor honored), the serving plane's own
        snapshot, the live byte model, dispatch-overlap bookkeeping
        and per-chip HBM via the store's chip_bytes seam.  Also the
        payload behind `cilium-tpu top` and bugtool's perf.json."""
        snap = self.perf.snapshot(since=since)
        snap["byte_model"] = self._perf_byte_model(leaves=leaves)
        plane = self.serving
        if plane is not None:
            snap["serving"] = plane.snapshot()
            d = getattr(plane, "_dispatcher", None)
            if d is not None:
                snap["overlap"] = {
                    "pack_s": d.pack_s,
                    "block_s": d.block_s,
                    "wall_s": d.wall_s,
                    "submitted": d.submitted,
                    "failed": d.failed,
                }
        store = self.endpoint_manager._device_store
        if store is not None:
            try:
                snap["hbm"] = {
                    "chip_bytes": {
                        str(k): int(v)
                        for k, v in (store.chip_bytes() or {}).items()
                    }
                }
            except Exception:  # pragma: no cover — defensive
                pass
        return snap

    def process_flows(
        self,
        buf: bytes,
        batch_size: int = 1 << 20,
        collect_verdicts: bool = False,
        async_depth: "Optional[int]" = None,
        tenant: str = "",
    ) -> "object":
        """Datapath execution under the agent with monitor folding —
        the production path behind `cilium monitor`: replay the
        record stream through the PUBLISHED lattice tables and fold
        every batch's verdicts into the monitor bus (drops always;
        allowed-verdict events for endpoints with
        PolicyVerdictNotification on, per-endpoint or global).

        This is the Hubble-style audit form (identity pre-resolved in
        the record); it reads verdict bits back per batch, which is
        the monitoring cost the reference pays through its perf ring.

        Resilience semantics (the graceful-degradation contract the
        chaos storm asserts): a malformed record buffer raises a
        clean ValueError (HTTP 400 at the API seam); device dispatch
        runs under retry + the dispatch circuit breaker and fails
        over per batch to the bit-identical host lattice fold —
        the verdict stream completes, bit-identical, with
        degraded_batches_total counting the failovers; bounded
        admission (self.admission) sheds whole batches under the
        canonical Overload drop reason instead of queueing
        unboundedly.

        With `collect_verdicts` the per-tuple verdict columns of
        every evaluated batch land in stats.verdicts (allowed /
        match_kind / proxy_port, stream order) — the chaos harness's
        bit-identity probe.

        Dispatch is double-buffered (`async_depth`, default
        self.dispatch_async_depth = 1): the host packs batch N+1
        while the device computes batch N, and results drain one
        batch behind in submission order — event/flow/telemetry
        folds see identical ordering and counts to synchronous
        serving (async_depth=0).  A device failure surfacing at
        drain time fails over that in-flight batch to the host fold
        under the breaker, same as a submit-time failure.

        Flow observability: every batch additionally folds into
        self.flow_store (cilium_tpu.flow) — ALL drops plus allows
        head-sampled per the MonitorAggregationLevel knob, classified
        through the same telemetry_masks definitions as the PR 1
        histogram.  Shed (Overload) flows are accounted in metrics
        only: building per-flow records under overload would amplify
        the overload being shed.  Returns ReplayStats.

        Tracing: the whole call runs under a `daemon.process_flows`
        span (a child of the REST request's root span when driven
        over the API); each phase/batch below opens child spans that
        SHARE their clock window with the SpanStat accumulators
        (tracing.StatSpan), so `/debug/profile` totals and
        `/debug/traces` durations agree; captured FlowRecords carry
        the trace id (GET /flows?trace-id=...)."""
        with self.tracer.span(
            "daemon.process_flows", site="daemon",
            attrs={"bytes": len(buf)},
        ) as proc_span:
            return self._process_flows_traced(
                buf, batch_size, collect_verdicts, proc_span,
                async_depth, tenant,
            )

    def _process_flows_traced(
        self, buf, batch_size, collect_verdicts, proc_span,
        async_depth=None, tenant="",
    ):
        import time as _time
        from types import SimpleNamespace

        import numpy as np

        from cilium_tpu.flow import allow_sample_for_level, capture_batch
        from cilium_tpu.monitor import verdicts_to_events
        from cilium_tpu.native import decode_flow_records
        from cilium_tpu.replay import (
            ReplayStats,
            _ep_index_of,
            read_batches_from_rec,
        )

        # tables AND the map-state snapshot they were compiled from,
        # read under one lock (see _resolve_serving_tables — the
        # block the serving plane shares)
        version, tables, index, host_states = (
            self._resolve_serving_tables()
        )
        # records for endpoints this node doesn't own are dropped up
        # front (the index→axis mapping sends unknown ids to axis 0,
        # which would evaluate them under — and attribute their
        # events to — the endpoint that happens to sit there).  ONE
        # decode pass: the filtered SoA feeds batching directly, and
        # the drop count is surfaced in stats.
        spans = self.datapath_spans
        host_pack = tracing.stat_span(
            spans, "host_pack", site="daemon", trc=self.tracer
        ).start()
        rec = decode_flow_records(buf)
        known = np.isin(
            rec["ep_id"], np.fromiter(index, dtype=np.int64)
        )
        n_dropped = int((~known).sum())
        if n_dropped:
            rec = {k: v[known] for k, v in rec.items()}
        # endpoint-axis LUTs (identity orientation + index→ep-id),
        # shared with the serving plane
        local_ident_lut, rev_lut = self._flow_luts(index)
        # allowed-flow record budget per batch — the SAME aggregation
        # knob that gates the monitor fold's per-packet traces; drops
        # are never sampled
        flow_allow_sample = allow_sample_for_level(
            option.Config.opts.level(option.MONITOR_AGGREGATION)
        )
        # XDP prefilter (shared _prefilter_records): denied sources
        # drop before the policy program, recorded as real drops —
        # keeps this audit path in agreement with trace_tuple's
        # prefilter stage
        rec, n_prefiltered = self._prefilter_records(
            rec, index, local_ident_lut, tenant=tenant,
            trace_id=tracing.current_trace_id(),
        )
        verdict_eps = self.verdict_notification_endpoints()
        # CT occupancy check on the serving path (the watermark
        # trigger must not wait for the 30 s GC controller tick)
        self._ct_pressure_check()
        # host-side endpoint-axis translation of the (filtered)
        # record stream — the degraded host fold and the shed
        # accounting read these slices without touching the device
        ep_idx_host = _ep_index_of(rec, dict(index))
        host_pack.end()
        stats = ReplayStats()
        stats.dropped = n_dropped
        # prefiltered flows received a verdict (deny) without
        # evaluation — they count toward the totals
        stats.total += n_prefiltered
        stats.denied += n_prefiltered
        collected = [] if collect_verdicts else None
        t0 = _time.perf_counter()
        offset = 0
        # Double-buffered async dispatch (engine/publish's epoch
        # ping-pong applied to BATCHES): the device computes batch N
        # while the host packs batch N+1 — read_batches_from_rec's
        # next() does the decode-slice + single-transfer upload after
        # _dispatch_or_degrade has merely ENQUEUED the previous
        # batch.  Results drain one batch behind, in submission
        # order, so the event fold / flow capture / tracing planes
        # keep their exact per-batch ordering and counts; admission
        # units stay reserved until their batch drains (the in-flight
        # accounting covers the whole pipeline, not just the
        # enqueue).  depth 0 restores fully synchronous serving.
        #
        # Kept inline rather than on AsyncBatchDispatcher: the
        # per-batch failover/span/admission interleaving (dispatch
        # span at submit, breaker + host-fold at drain, release in
        # the drain's finally) is daemon policy the generic pipeline
        # deliberately doesn't know about; the ordering semantics are
        # the same and pinned by tests/test_async_dispatch.py.
        #
        # batch_duration semantics under overlap: observed from
        # submit to drain-complete — the PIPELINE latency of the
        # batch, which at depth N includes up to N later batches'
        # pack+enqueue time.  depth 0 restores the historical
        # synchronous reading exactly.
        from collections import deque as _dq

        depth = (
            self.dispatch_async_depth
            if async_depth is None
            else async_depth
        )
        pending = _dq()
        trace_ctx = tracing.current_trace_id()

        def _host_args_for(s, e):
            return (
                host_states,
                ep_idx_host[s:e],
                rec["identity"][s:e],
                rec["dport"][s:e],
                rec["proto"][s:e],
                rec["direction"][s:e],
                rec["is_fragment"][s:e].astype(bool),
            )

        def _drain_oldest():
            from cilium_tpu.engine.hostpath import lattice_fold_host

            out, degraded, start, end, valid, batch_t0, dev_batch = (
                pending.popleft()
            )
            shadow_refuse = False
            try:
                drain_span = tracing.stat_span(
                    spans, "drain", site="daemon", trc=self.tracer,
                ).start()
                try:
                    hit_col = getattr(out, "cache_hit", None)
                    v = SimpleNamespace(
                        allowed=np.asarray(out.allowed)[:valid],
                        match_kind=np.asarray(out.match_kind)[:valid],
                        proxy_port=np.asarray(out.proxy_port)[:valid],
                        cache_hit=(
                            None
                            if hit_col is None
                            else np.asarray(hit_col)[:valid]
                        ),
                    )
                    # deferred memo fold (one per served batch — the
                    # dispatch target never syncs): correct hit/miss
                    # accounting to the valid prefix, and when the
                    # kernel REFUSED the batch (more distinct keys
                    # than the compaction capacity; its verdict
                    # columns are unspecified, carried cache state
                    # untouched) re-dispatch through the uncached
                    # program
                    cstats = getattr(out, "cache_stats", None)
                    if cstats is not None:

                        def _redispatch(s0=start, e0=end):
                            def _ha():
                                return _host_args_for(s0, e0)

                            return self._dispatch_or_degrade(
                                tables, dev_batch, _ha,
                                batch_size, use_memo=False,
                                shadow_sample=False,
                            )

                        v, deg2, overflowed = self._fold_memo_drain(
                            cstats, v, valid,
                            int(out.allowed.shape[0]),
                            _redispatch,
                        )
                        degraded = degraded or deg2
                        # an overflow re-dispatch replaced the live
                        # columns the device diff compared against
                        shadow_refuse = shadow_refuse or overflowed
                except Exception as exc:
                    # the overlapped batch died ON DEVICE after a
                    # successful enqueue: the breaker learns the
                    # failure and the in-flight batch drains through
                    # the bit-identical host fold instead of
                    # vanishing mid-pipeline.  A memoized dispatch
                    # committed its (lazy) output rows before the
                    # failure surfaced — drop them, or every later
                    # kernel feeds the poisoned buffer back in and
                    # serving stays degraded until an unrelated
                    # publish changes the stamp
                    if self.verdict_cache is not None:
                        self.verdict_cache.flush(
                            reason="drain-failure"
                        )
                    self.dispatch_breaker.record_failure(str(exc))
                    log.warning(
                        "async drain failed; serving in-flight "
                        "batch from host path",
                        extra={"fields": {"error": str(exc)}},
                    )
                    with self.tracer.span(
                        "engine.hostpath", site="engine.hostpath",
                        attrs={"failover": True, "drain": True},
                    ):
                        host_out = lattice_fold_host(
                            *_host_args_for(start, end),
                            pad_to=batch_size,
                        )
                    degraded = True
                    shadow_refuse = True  # the shadow columns came
                    # from the dead device dispatch; refuse cleanly
                    self.degraded_batches += 1
                    metrics.degraded_batches_total.inc()
                    v = SimpleNamespace(
                        allowed=np.asarray(host_out.allowed)[:valid],
                        match_kind=np.asarray(
                            host_out.match_kind
                        )[:valid],
                        proxy_port=np.asarray(
                            host_out.proxy_port
                        )[:valid],
                    )
                drain_span.end()
                n_allowed = int(v.allowed.sum())
                stats.total += int(valid)
                stats.allowed += n_allowed
                stats.denied += int(valid) - n_allowed
                stats.redirected += int((v.proxy_port > 0).sum())
                stats.batches += 1
                if degraded:
                    stats.degraded_batches += 1
                if collected is not None:
                    collected.append(v)
                event_fold = tracing.stat_span(
                    spans, "event_fold", site="daemon",
                    trc=self.tracer,
                ).start()
                ep_idx = ep_idx_host[start:end]
                opts = option.Config.opts
                verdicts_to_events(
                    self.monitor,
                    v,
                    ep_ids=rev_lut[ep_idx],
                    identities=rec["identity"][start:end],
                    dports=rec["dport"][start:end],
                    protos=rec["proto"][start:end],
                    directions=rec["direction"][start:end],
                    verdict_eps=verdict_eps,
                    emit_drops=opts.is_enabled(
                        option.DROP_NOTIFICATION
                    ),
                    emit_trace=(
                        opts.is_enabled(option.TRACE_NOTIFICATION)
                        and opts.level(option.MONITOR_AGGREGATION)
                        == option.MONITOR_AGG_NONE
                    ),
                )
                event_fold.end()
                # flow-record fold (the Hubble plane): all drops +
                # head-sampled allows, classified through the shared
                # telemetry_masks definitions
                flow_capture = tracing.stat_span(
                    spans, "flow_capture", site="daemon",
                    trc=self.tracer,
                ).start()
                dirs = rec["direction"][start:end]
                peer = rec["identity"][start:end].astype(np.int64)
                local = local_ident_lut[ep_idx]
                src_ids = np.where(dirs == 0, peer, local)
                dst_ids = np.where(dirs == 0, local, peer)
                # shadow verdict-diff fold (one per sampled batch,
                # exactly once): counters + diff records land in the
                # armed window; the returned transition codes join
                # the flow records (observe --diff-status)
                diff_col = self._fold_shadow_drain(
                    out, v, valid,
                    ep_ids=rev_lut[ep_idx],
                    src_identities=src_ids,
                    dst_identities=dst_ids,
                    dports=rec["dport"][start:end],
                    protos=rec["proto"][start:end],
                    directions=dirs,
                    tenant=tenant,
                    trace_id=trace_ctx,
                    refuse=shadow_refuse,
                )
                capture_batch(
                    self.flow_store,
                    ep_ids=rev_lut[ep_idx],
                    src_identities=src_ids,
                    dst_identities=dst_ids,
                    dports=rec["dport"][start:end],
                    protos=rec["proto"][start:end],
                    directions=dirs,
                    allowed=v.allowed,
                    match_kind=v.match_kind,
                    proxy_port=v.proxy_port,
                    cache_hit=getattr(v, "cache_hit", None),
                    diff_status=diff_col,
                    allow_sample=flow_allow_sample,
                    metrics_registry=metrics,
                    trace_id=trace_ctx,
                    tenant=tenant,
                )
                flow_capture.end()
            finally:
                self.admission.release(valid)
            metrics.batch_duration.observe(
                _time.perf_counter() - batch_t0
            )

        try:
            for batch, valid in read_batches_from_rec(
                rec, batch_size, ep_index=ep_idx_host
            ):
                start, end = offset, offset + valid
                offset = end
                batch_t0 = _time.perf_counter()
                # bounded admission: a batch the gate refuses is
                # SHED — counted under the canonical Overload drop
                # reason, never queued (backpressure on the datapath
                # is attribution, not buffering)
                if not self.admission.reserve(valid):
                    stats.shed += valid
                    metrics.shed_flows_total.inc(value=valid)
                    from cilium_tpu.monitor.events import (
                        DROP_OVERLOAD,
                        drop_reason_name,
                    )

                    for dirv, dname in ((0, "INGRESS"), (1, "EGRESS")):
                        count = int(
                            (rec["direction"][start:end] == dirv).sum()
                        )
                        if count:
                            metrics.drop_count.inc(
                                drop_reason_name(DROP_OVERLOAD),
                                dname, value=count,
                            )
                    continue
                try:
                    dispatch_span = tracing.stat_span(
                        spans, "dispatch", site="daemon",
                        attrs={
                            "batch": stats.batches + len(pending),
                            "rows": valid,
                        },
                        trc=self.tracer,
                    ).start()

                    def _host_args(s=start, e=end):
                        return _host_args_for(s, e)

                    def _host_cols(s=start, e=end):
                        # the UNPADDED host tuple columns the mesh
                        # router re-splits across survivors
                        return (
                            ep_idx_host[s:e],
                            rec["identity"][s:e],
                            rec["dport"][s:e],
                            rec["proto"][s:e],
                            rec["direction"][s:e],
                            rec["is_fragment"][s:e].astype(bool),
                        )

                    out, degraded = self._dispatch_or_degrade(
                        tables, batch, _host_args, batch_size,
                        host_cols=_host_cols,
                    )
                    dispatch_span.end(success=not degraded)
                except Exception:
                    self.admission.release(valid)
                    raise
                # the device batch rides `pending` so a drain-time
                # overflow refusal can re-dispatch it uncached
                pending.append(
                    (out, degraded, start, end, valid, batch_t0,
                     batch)
                )
                while len(pending) > depth:
                    _drain_oldest()
            while pending:
                _drain_oldest()
        finally:
            # an exception escaping mid-stream (decode, drain-side
            # fold, host-fold failure) must not leak the reserved
            # admission units of batches still in flight — the gate's
            # outstanding count would stay inflated forever and later
            # calls would spuriously shed.  In-flight shadow tickets
            # refuse (exactly-once accounting) rather than dangle.
            while pending:
                dropped = pending.popleft()
                tk = getattr(dropped[0], "shadow_ticket", None)
                if tk is not None:
                    self.shadow.refuse(tk)
                self.admission.release(dropped[4])
        stats.seconds = _time.perf_counter() - t0
        stats.spans = spans
        proc_span.attrs.update(
            total=stats.total, batches=stats.batches,
            allowed=stats.allowed, denied=stats.denied,
            dropped=stats.dropped, shed=stats.shed,
            degraded_batches=stats.degraded_batches,
        )
        self._export_spans("datapath", spans)
        if collected is not None:
            stats.verdicts = {
                field: np.concatenate(
                    [np.asarray(getattr(c, field)) for c in collected]
                )
                if collected
                else np.zeros(0)
                for field in ("allowed", "match_kind", "proxy_port")
            }
        if stats.seconds > 0:
            metrics.verdict_throughput.set(
                value=stats.total / stats.seconds
            )
        return stats

    def health(self) -> Dict:
        """Node health rollup (status.go's aggregate): degraded when
        the dispatch breaker is not closed (serving from the host
        path) or any controller is stuck failing past the threshold —
        background-thread failures must surface, not rot silently."""
        reasons = []
        breaker_state = self.dispatch_breaker.state
        if breaker_state != "closed":
            reasons.append(
                f"dispatch breaker {breaker_state}: device verdicts "
                f"degraded to host path"
            )
        chip_states = {}
        if self.mesh_router is not None:
            chip_states = self.mesh_router.chip_states()
            for ordinal, state in chip_states.items():
                if state != "closed":
                    reasons.append(
                        f"chip {ordinal} breaker {state}: its batch "
                        f"shard re-splits across survivors and its "
                        f"table rows serve from replicas"
                    )
        for name, s in self.controllers.statuses().items():
            if (
                s.consecutive_failures
                >= self.controller_failure_threshold
            ):
                reasons.append(
                    f"controller {name} failing "
                    f"({s.consecutive_failures} consecutive: "
                    f"{s.last_error})"
                )
        out = {
            "status": "degraded" if reasons else "ok",
            "reasons": reasons,
            "breaker": {
                **self.dispatch_breaker.snapshot(),
                "state": breaker_state,
            },
            "degraded_batches": self.degraded_batches,
            "shed_flows": self.admission.shed_total,
        }
        if self.mesh_router is not None:
            out["chips"] = {
                str(o): s for o, s in chip_states.items()
            }
        return out

    def status(self) -> Dict:
        version, tables, index = self.endpoint_manager.published()
        build_fail_count, build_fail_last = (
            self.endpoint_manager.build_failure_snapshot()
        )
        health = self.health()
        out = {
            "node": self.node_name,
            "health": health["status"],
            "health_reasons": health["reasons"],
            "breaker": health["breaker"],
            "degraded_batches": self.degraded_batches,
            "shed_flows": self.admission.shed_total,
            "policy_revision": self.repo.get_revision(),
            "num_rules": self.repo.num_rules(),
            "num_endpoints": len(self.endpoint_manager.endpoints()),
            "num_identities": len(self.identity_cache()),
            "ipcache_entries": len(self.ipcache.ip_to_identity),
            "tables_version": version,
            "table_endpoints": len(index),
            "kvstore": "connected" if self.kvstore else "disabled",
            "clustermesh_clusters": self.clustermesh.num_connected(),
            "build_failures": build_fail_count,
            "last_build_failures": [
                {"endpoint": e, "reason": r, "error": err}
                for e, r, err in build_fail_last
            ],
            "controllers": {
                name: {
                    "success": s.success_count,
                    "failure": s.failure_count,
                    "consecutive_failures": s.consecutive_failures,
                    "last_error": s.last_error,
                }
                for name, s in self.controllers.statuses().items()
            },
        }
        if self.serving is not None:
            out["serving"] = self.serving.snapshot()
        return out
