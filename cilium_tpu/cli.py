"""cilium-tpu CLI.

Re-design of /root/reference/cilium/cmd (cobra commands over the REST
API): the same command surface driven in-process against a Daemon —
policy import/get/delete/trace, endpoint list/get/regenerate,
identity list, ipcache dump (bpf ipcache analog), service list,
metrics, status.  `python -m cilium_tpu.cli --help` for usage.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from cilium_tpu.daemon import Daemon
from cilium_tpu.labels import LabelArray
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.policy.api import rules_from_json
from cilium_tpu.policy.search import Port, SearchContext


def _daemon() -> Daemon:
    # CLI sessions are self-contained (the reference talks to the
    # agent's unix socket; an RPC transport can replace this factory).
    return Daemon()


def cmd_policy_import(daemon: Daemon, args) -> int:
    with open(args.file) as f:
        rules = rules_from_json(f.read())
    revision = daemon.policy_add(rules, replace=args.replace)
    print(f"Revision: {revision}")
    return 0


def cmd_policy_get(daemon: Daemon, args) -> int:
    print(
        json.dumps(
            {
                "revision": daemon.repo.get_revision(),
                "count": daemon.repo.num_rules(),
            }
        )
    )
    return 0


def cmd_policy_delete(daemon: Daemon, args) -> int:
    labels = LabelArray.parse(*args.labels)
    revision, deleted = daemon.policy_delete(labels)
    print(f"Revision: {revision}, deleted: {deleted}")
    return 0


def cmd_policy_trace(daemon: Daemon, args) -> int:
    ctx = SearchContext(
        from_labels=LabelArray.parse_select(*args.src.split(",")),
        to_labels=LabelArray.parse_select(*args.dst.split(",")),
        dports=[Port(int(p), "TCP") for p in (args.dport or [])],
    )
    verdict, trace = daemon.policy_resolve(ctx)
    print(trace, end="")
    print(f"Final verdict: {str(verdict).upper()}")
    return 0 if str(verdict) == "allowed" else 1


def cmd_endpoint_list(daemon: Daemon, args) -> int:
    for endpoint in sorted(
        daemon.endpoint_manager.endpoints(), key=lambda e: e.id
    ):
        ident = (
            endpoint.security_identity.id
            if endpoint.security_identity
            else "-"
        )
        print(
            f"{endpoint.id}\t{endpoint.state}\t{ident}\t"
            f"{endpoint.ipv4 or '-'}\t{endpoint.name}"
        )
    return 0


def cmd_identity_list(daemon: Daemon, args) -> int:
    for num_id, labels in sorted(daemon.identity_cache().items()):
        print(f"{num_id}\t{','.join(str(l) for l in labels)}")
    return 0


def cmd_ipcache_dump(daemon: Daemon, args) -> int:
    for ip, ident in sorted(daemon.ipcache.ip_to_identity.items()):
        print(f"{ip}\t{ident.id}\t{ident.source}")
    return 0


def cmd_status(daemon: Daemon, args) -> int:
    print(json.dumps(daemon.status(), indent=2))
    return 0


def cmd_metrics(daemon: Daemon, args) -> int:
    print(metrics.expose(), end="")
    return 0


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="cilium-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("policy")
    psub = p.add_subparsers(dest="subcmd", required=True)
    imp = psub.add_parser("import")
    imp.add_argument("file")
    imp.add_argument("--replace", action="store_true")
    imp.set_defaults(func=cmd_policy_import)
    get = psub.add_parser("get")
    get.set_defaults(func=cmd_policy_get)
    dele = psub.add_parser("delete")
    dele.add_argument("labels", nargs="+")
    dele.set_defaults(func=cmd_policy_delete)
    trace = psub.add_parser("trace")
    trace.add_argument("--src", required=True)
    trace.add_argument("--dst", required=True)
    trace.add_argument("--dport", action="append")
    trace.set_defaults(func=cmd_policy_trace)

    endpoint = sub.add_parser("endpoint")
    esub = endpoint.add_subparsers(dest="subcmd", required=True)
    elist = esub.add_parser("list")
    elist.set_defaults(func=cmd_endpoint_list)

    ident = sub.add_parser("identity")
    isub = ident.add_subparsers(dest="subcmd", required=True)
    ilist = isub.add_parser("list")
    ilist.set_defaults(func=cmd_identity_list)

    ipc = sub.add_parser("ipcache")
    ipsub = ipc.add_subparsers(dest="subcmd", required=True)
    dump = ipsub.add_parser("dump")
    dump.set_defaults(func=cmd_ipcache_dump)

    status = sub.add_parser("status")
    status.set_defaults(func=cmd_status)
    met = sub.add_parser("metrics")
    met.set_defaults(func=cmd_metrics)
    return parser


def main(argv=None, daemon: Optional[Daemon] = None) -> int:
    args = make_parser().parse_args(argv)
    return args.func(daemon or _daemon(), args)


if __name__ == "__main__":
    sys.exit(main())
