"""cilium-tpu CLI.

Re-design of /root/reference/cilium/cmd (cobra commands over the REST
API): policy import/get/delete/trace, endpoint list/get, identity
list, ipcache dump, metrics, status — driven through the api/v1
contract (api.server.DaemonAPI).

Like the reference CLI, commands talk to a RUNNING agent through its
unix socket (``--socket`` or $CILIUM_TPU_SOCK — run one with
``python -m cilium_tpu.agent``); without a socket they fall back to a
self-contained in-process daemon (useful for one-shot policy
evaluation, the DryMode analog).  Both paths go through the same
DaemonAPI operations, so output is identical either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

SOCK_ENV = "CILIUM_TPU_SOCK"


def _api(args):
    """APIClient against a live agent socket, or DaemonAPI over a
    fresh in-process daemon (the factory the RPC transport replaces,
    now actually replaced)."""
    socket_path = getattr(args, "socket", None) or os.environ.get(
        SOCK_ENV
    )
    if socket_path:
        from cilium_tpu.api.client import APIClient

        return APIClient(socket_path)
    from cilium_tpu.api.server import DaemonAPI
    from cilium_tpu.daemon import Daemon

    return DaemonAPI(Daemon())


def cmd_policy_import(api, args) -> int:
    with open(args.file) as f:
        got = api.policy_add(f.read(), args.replace)
    print(f"Revision: {got['revision']}")
    return 0


def cmd_policy_get(api, args) -> int:
    got = api.policy_get()
    print(
        json.dumps(
            {"revision": got["revision"], "count": got["count"]}
        )
    )
    if args.verbose:
        for rule in got.get("rules", []):
            print(rule)
    return 0


def cmd_policy_delete(api, args) -> int:
    got = api.policy_delete(args.labels)
    print(f"Revision: {got['revision']}, deleted: {got['deleted']}")
    return 0


def cmd_policy_trace_tuple(api, args) -> int:
    """Single-tuple datapath explain: every stage's decision plus the
    matching rules (the `cilium policy trace` analogue run through
    the composed pipeline stages)."""
    proto = args.proto.lower()
    proto_num = {"tcp": 6, "udp": 17}.get(proto)
    if proto_num is None:
        try:
            proto_num = int(proto)
        except ValueError:
            print(f"error: unknown protocol {args.proto!r}",
                  file=sys.stderr)
            return 2
    got = api.trace_tuple(
        {
            "ep_id": args.ep_id,
            "saddr": args.saddr,
            "daddr": args.daddr,
            "dport": args.dport,
            "sport": args.sport,
            "proto": proto_num,
            "direction": args.direction,
            "is_fragment": args.fragment,
        }
    )
    if args.json:
        print(json.dumps(got, indent=2))
    else:
        print(got["text"], end="")
    return 0 if got["verdict"] == "allowed" else 1


def cmd_policy_shadow(api, args) -> int:
    """`cilium-tpu policy shadow arm|disarm|promote` — the shadow
    rollout lifecycle: arm a candidate rule file (or --standby for
    the previous publish), watch `policy diff`, then promote or
    disarm."""
    body = {"action": args.shadow_action}
    if args.shadow_action == "arm":
        if args.file:
            with open(args.file) as f:
                body["rules"] = json.loads(f.read())
        elif not args.standby:
            print(
                "error: give a candidate rule file, or --standby "
                "to diff against the previous publish",
                file=sys.stderr,
            )
            return 2
        body["sample_rate"] = args.sample_rate
        body["seed"] = args.seed
    got = api.policy_shadow(body)
    print(json.dumps(got, indent=2))
    return 0


def _format_diff_compact(flow: dict) -> str:
    """One compact line per diff record: the tuple, both worlds'
    verdicts, and the transition."""
    from cilium_tpu.monitor.dissect import proto_name

    def verdict(allowed, reason):
        return "ALLOW" if allowed else f"DENY({reason})"

    return (
        f"identity {flow['src_identity']} -> "
        f"{flow['dst_identity']} ep={flow['ep_id']} "
        f":{flow['dport']}/{proto_name(flow['proto'])} "
        f"{flow['direction']} "
        f"{verdict(flow['live_allowed'], flow['live_reason'])} => "
        f"{verdict(flow['shadow_allowed'], flow['shadow_reason'])} "
        f"[{flow['transition']}]"
    )


def cmd_policy_diff(api, args) -> int:
    """`cilium-tpu policy diff` — the verdict-diff canary surface:
    summary of the armed shadow window; --live adds the captured
    diff records; --follow tails new records (seq-cursor polls)."""
    got = api.policy_diff({"last": args.last})
    if args.json and not args.follow:
        print(json.dumps(got, indent=2))
        return 0 if got.get("state") == "armed" else 1
    state = got.get("state")
    w = got.get("window") or got.get("last_window") or {}
    print(f"state: {state}")
    if w:
        print(
            f"mode={w.get('mode')} live_gen={w.get('live_gen')} "
            f"shadow_gen={w.get('shadow_gen')} "
            f"sample_rate={w.get('sample_rate')}"
        )
        print(
            f"sampled={w.get('sampled')} "
            f"changed={w.get('changed')} "
            f"allow->deny={w.get('allow_to_deny')} "
            f"deny->allow={w.get('deny_to_allow')} "
            f"refused={w.get('refused')}"
        )
        for row in w.get("top_reverdicted_pairs", []):
            print(
                f"  pair {row['src_identity']} -> "
                f"{row['dst_identity']}: {row['count']} re-verdicts"
            )
    if args.live or args.follow:
        for flow in got.get("flows", []):
            print(
                json.dumps(flow)
                if args.json
                else _format_diff_compact(flow)
            )
    if not args.follow:
        return 0 if state == "armed" else 1
    import time as _time

    cursor = got.get("last_seq", 0)
    try:
        while True:
            _time.sleep(args.interval)
            got = api.policy_diff(
                {"last": 0, "since-seq": cursor}
            )
            for flow in got.get("flows", []):
                print(
                    json.dumps(flow)
                    if args.json
                    else _format_diff_compact(flow)
                )
            cursor = max(cursor, got.get("last_seq", cursor))
            if got.get("state") != "armed":
                print(
                    f"# window closed: {got.get('state')}",
                    file=sys.stderr,
                )
                return 1
    except KeyboardInterrupt:
        return 0


def cmd_policy_trace(api, args) -> int:
    got = api.policy_resolve(
        {
            "from": args.src.split(","),
            "to": args.dst.split(","),
            "dports": [
                {"port": int(p), "protocol": "TCP"}
                for p in (args.dport or [])
            ],
        }
    )
    print(got["trace"], end="")
    print(f"Final verdict: {got['verdict'].upper()}")
    return 0 if got["verdict"] == "allowed" else 1


def cmd_endpoint_list(api, args) -> int:
    for ep in sorted(api.endpoint_list(), key=lambda e: e["id"]):
        print(
            f"{ep['id']}\t{ep['state']}\t{ep['identity'] or '-'}\t"
            f"{ep['ipv4'] or '-'}\t{ep['name']}"
        )
    return 0


def cmd_identity_list(api, args) -> int:
    for num_id, labels in sorted(
        api.identity_list().items(), key=lambda kv: int(kv[0])
    ):
        print(f"{num_id}\t{','.join(labels)}")
    return 0


def cmd_ipcache_dump(api, args) -> int:
    for cidr, ident in sorted(api.ipcache_dump().items()):
        print(f"{cidr}\t{ident}")
    return 0


def cmd_config_get(api, args) -> int:
    print(json.dumps(api.config_get(), indent=2))
    return 0


_TRUE = ("1", "true", "on", "enabled")
_FALSE = ("0", "false", "off", "disabled")


def cmd_config_set(api, args) -> int:
    changes = {}
    opts = {}
    for kv in args.set:
        key, sep, value = kv.partition("=")
        if not sep:
            print(
                f"error: {kv!r} is not Key=value", file=sys.stderr
            )
            return 1
        if key == "policy-enforcement":
            changes["policy_enforcement"] = value
            continue
        low = value.lower()
        if low in _TRUE:
            opts[key] = True
        elif low in _FALSE:
            opts[key] = False
        else:
            # a typo ('ture') must not silently DISABLE the option
            print(
                f"error: {key}={value!r} is not a boolean "
                f"({'/'.join(_TRUE)} or {'/'.join(_FALSE)})",
                file=sys.stderr,
            )
            return 1
    if opts:
        changes["options"] = opts
    print(json.dumps(api.config_patch(changes), indent=2))
    return 0


def cmd_service_list(api, args) -> int:
    print(json.dumps(api.service_list(), indent=2))
    return 0


def cmd_ct_list(api, args) -> int:
    print(json.dumps(api.ct_list(), indent=2))
    return 0


def cmd_monitor(api, args) -> int:
    """`cilium monitor` follow mode over the REST stream."""
    sid = api.monitor_open()["session"]
    printed = 0
    ack = None
    try:
        while args.count == 0 or printed < args.count:
            # cap the poll at the remaining budget: events the server
            # dequeues for this reply but the CLI would not print
            # could never be retrieved again
            remaining = (
                args.count - printed if args.count else 1024
            )
            got = api.monitor_poll(
                sid, timeout=args.timeout, max_events=remaining,
                ack=ack,
            )
            ack = got.get("seq", ack)
            # a re-delivered batch may exceed this poll's budget
            for ev in got["events"][:remaining]:
                if args.verbose:
                    # `cilium monitor -v`: dissected one-line
                    # rendering (pkg/monitor/dissect.go + the
                    # per-event formatters)
                    from cilium_tpu.monitor.dissect import (
                        dissect_event,
                    )

                    print(dissect_event(ev))
                else:
                    print(json.dumps(ev))
                printed += 1
            if args.once and not got["events"]:
                break
    except KeyboardInterrupt:
        pass
    finally:
        try:
            api.monitor_close(sid)
        except Exception:
            pass
    return 0


def _format_flow_compact(flow: dict) -> str:
    """One `hubble observe -o compact`-style line per record."""
    import time as _time

    from cilium_tpu.monitor.dissect import proto_name

    stamp = _time.strftime(
        "%b %d %H:%M:%S", _time.localtime(flow.get("ts", 0))
    )
    line = (
        f"{stamp} [chip {flow.get('chip', 0)}] "
        f"identity {flow.get('src_identity', 0)} -> "
        f"{flow.get('dst_identity', 0)} "
        f"ep={flow.get('ep_id', 0)} "
        f":{flow.get('dport', 0)}/{proto_name(flow.get('proto', 0))} "
        f"{flow.get('direction', '')} {flow.get('verdict', '')}"
    )
    if flow.get("drop_reason"):
        line += f" ({flow['drop_reason']})"
    if flow.get("proxy_port"):
        line += f" -> proxy {flow['proxy_port']}"
    if flow.get("cache_hit"):
        line += " [cached]"
    if flow.get("diff_status"):
        line += f" [shadow:{flow['diff_status']}]"
    return line


def cmd_observe(api, args) -> int:
    """`cilium-tpu observe` — the hubble observe analog: filtered
    one-shot dump of the agent's flow ring, or --follow to tail it
    (long-polls riding the FlowStore condvar)."""
    params = {}
    for key, val in (
        ("verdict", args.verdict),
        ("drop-reason", args.drop_reason),
        ("identity", args.identity),
        ("ep", args.ep),
        ("port", args.port),
        ("proto", args.proto),
        ("direction", args.direction),
        ("since", args.since),
        ("chip", args.chip),
        ("trace-id", args.trace_id),
        ("tenant", args.tenant),
        ("diff-status", args.diff_status),
    ):
        if val is not None:
            params[key] = val
    if getattr(args, "cache_hit", False):
        params["cache-hit"] = "1"
    params["last"] = args.last

    def emit(flows) -> None:
        for flow in flows:
            if args.output == "json":
                print(json.dumps(flow))
            else:
                print(_format_flow_compact(flow))

    if args.summary:
        print(json.dumps(api.flows_summary(top=args.top), indent=2))
        return 0
    if not args.follow:
        got = api.flows_get(params)
        emit(got["flows"])
        if got.get("evicted"):
            print(
                f"# ring evicted {got['evicted']} records",
                file=sys.stderr,
            )
        return 0
    # follow mode: start from the current cursor, re-poll with the
    # reply's last_seq so nothing is skipped or repeated
    cursor = api.flows_get({"last": 0})["last_seq"]
    try:
        while True:
            got = api.flows_get(
                {
                    **params,
                    "follow": 1,
                    "since-seq": cursor,
                    "timeout": args.timeout,
                    "last": 0,
                }
            )
            emit(got["flows"])
            cursor = max(cursor, got["last_seq"])
    except KeyboardInterrupt:
        return 0


def cmd_trace(api, args) -> int:
    """`cilium-tpu trace` — the span-plane reader: render one trace
    as an indented tree with per-span ms (`trace <trace_id>`), or
    rank traces by root duration (`trace --slowest N`)."""
    from cilium_tpu.tracing import render_span_tree

    if args.slowest is not None:
        got = api.traces_get({"slowest": args.slowest})
        if args.json:
            print(json.dumps(got, indent=2))
            return 0
        if not got["traces"]:
            print("(no traces)")
            return 0
        for row in got["traces"]:
            print(
                f"{row['trace_id']}  {row['duration_ms']:>10.3f}ms  "
                f"{row['spans']:>4} spans  {row['root']} "
                f"({row['site']})"
                + ("" if row["status"] == "ok" else f" [{row['status']}]")
            )
        return 0
    if not args.trace_id:
        print(
            "error: give a trace id, or --slowest N", file=sys.stderr
        )
        return 2
    got = api.traces_get({"trace-id": args.trace_id})
    spans = got["spans"]
    if args.json:
        print(json.dumps(got, indent=2))
        return 0 if spans else 1
    if not spans:
        print(
            f"no spans for trace {args.trace_id} "
            f"(ring dropped {got.get('dropped', 0)})",
            file=sys.stderr,
        )
        return 1
    print(render_span_tree(spans), end="")
    return 0


def cmd_fault_list(api, args) -> int:
    print(json.dumps(api.fault_list(), indent=2))
    return 0


def cmd_fault_arm(api, args) -> int:
    """Arm a chaos fault site ("cilium-tpu fault arm engine.dispatch
    raise:next=3") — the CLI face of the fault-injection framework."""
    got = api.fault_arm({"site": args.site, "spec": args.spec})
    print(json.dumps(got, indent=2))
    return 0


def cmd_fault_disarm(api, args) -> int:
    # disarming EVERYTHING must be the explicit --all, never the
    # default of a bare `fault disarm` mid-chaos-run
    if args.site is None and not args.all:
        print(
            "error: give a site to disarm, or --all",
            file=sys.stderr,
        )
        return 2
    got = api.fault_disarm(None if args.all else args.site)
    print(json.dumps(got, indent=2))
    return 0


def cmd_serve_bench(api, args) -> int:
    """`cilium-tpu serve-bench` — the continuous-serving-plane
    driver: a self-contained demo daemon, open-loop (Poisson)
    arrivals split across tenants, the coalescing serve loop in
    front of the real dispatch path.  Prints the sustained-QPS
    serving metrics (serving_p99_ms, sustained_verdicts_per_sec,
    batch fill, per-tenant admitted/shed) as JSON.  Runs in-process
    (no agent socket needed): the serving plane is a daemon-side
    loop, and this is its standalone bench harness."""
    from cilium_tpu.serve import (
        build_demo_daemon,
        demo_record_maker,
        run_serve_bench,
    )

    tenants = {}
    for part in (args.tenants or "default=1").split(","):
        name, _, share = part.partition("=")
        tenants[name.strip()] = float(share or 1.0)
    d, client = build_demo_daemon()
    if args.weights:
        weights = {}
        for part in args.weights.split(","):
            name, _, w = part.partition("=")
            weights[name.strip()] = float(w or 1.0)
        d.config_patch({"tenant_weights": weights})
    try:
        out = run_serve_bench(
            d,
            seconds=args.seconds,
            qps=args.qps,
            flows_per_submit=args.flows,
            tenants=tenants,
            batch_size=args.batch_size,
            slo_ms=args.slo_ms,
            make_records=demo_record_maker(
                client.security_identity.id
            ),
            seed=args.seed,
        )
    finally:
        if d.serving is not None:
            d.serving.stop()
    print(json.dumps(out, indent=2))
    return 0


def cmd_top(api, args) -> int:
    """`cilium-tpu top` — the live performance-plane view: phase
    breakdown (p50/p99/max), batch fill, queue delay, ingest-stall
    fraction, per-tenant SLO error-budget burn, the modeled
    gather-bytes line and the last re-tune.  Refreshes in place
    every --interval seconds until interrupted; `--once` prints a
    single frame, and `--once -o json` emits the raw /debug/perf
    snapshot (the same document bugtool archives as perf.json)."""
    from cilium_tpu.perfplane import render_top

    params = {}
    if args.leaves:
        params["leaves"] = "1"

    def frame():
        return api.debug_perf(params)

    if args.once:
        snap = frame()
        if args.output == "json":
            print(json.dumps(snap, indent=2))
        else:
            print(render_top(snap))
        return 0
    try:
        while True:
            snap = frame()
            # clear + home, then one frame — the classic top(1)
            # in-place refresh
            sys.stdout.write("\x1b[2J\x1b[H" + render_top(snap) + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_status(api, args) -> int:
    print(json.dumps(api.status(), indent=2))
    return 0


def cmd_metrics(api, args) -> int:
    print(api.metrics_dump()["text"], end="")
    return 0


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="cilium-tpu")
    parser.add_argument(
        "--socket",
        default=None,
        help=f"agent unix socket (default: ${SOCK_ENV})",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("policy")
    psub = p.add_subparsers(dest="subcmd", required=True)
    imp = psub.add_parser("import")
    imp.add_argument("file")
    imp.add_argument("--replace", action="store_true")
    imp.set_defaults(func=cmd_policy_import)
    get = psub.add_parser("get")
    get.add_argument("--verbose", action="store_true")
    get.set_defaults(func=cmd_policy_get)
    dele = psub.add_parser("delete")
    dele.add_argument("labels", nargs="+")
    dele.set_defaults(func=cmd_policy_delete)
    trace = psub.add_parser("trace")
    trace.add_argument("--src", required=True)
    trace.add_argument("--dst", required=True)
    trace.add_argument("--dport", action="append")
    trace.set_defaults(func=cmd_policy_trace)
    ttuple = psub.add_parser(
        "trace-tuple",
        help="stage-accurate single-tuple datapath explain",
    )
    ttuple.add_argument("--ep-id", type=int, required=True)
    ttuple.add_argument("--saddr", required=True)
    ttuple.add_argument("--daddr", required=True)
    ttuple.add_argument("--dport", type=int, required=True)
    ttuple.add_argument("--sport", type=int, default=0)
    ttuple.add_argument("--proto", default="tcp",
                        help="tcp|udp|<number>")
    ttuple.add_argument("--direction", default="ingress",
                        choices=["ingress", "egress"])
    ttuple.add_argument("--fragment", action="store_true")
    ttuple.add_argument("--json", action="store_true",
                        help="machine-readable stage dump")
    ttuple.set_defaults(func=cmd_policy_trace_tuple)
    pshadow = psub.add_parser(
        "shadow",
        help="shadow rollout lifecycle: arm a candidate policy (or "
        "--standby), disarm, or promote the armed candidate",
    )
    pshadow.add_argument(
        "shadow_action", choices=["arm", "disarm", "promote"]
    )
    pshadow.add_argument(
        "file", nargs="?", default=None,
        help="candidate rule JSON file (arm)",
    )
    pshadow.add_argument(
        "--standby", action="store_true",
        help="arm against the PREVIOUS publish instead of a "
        "candidate file (what did my last change re-verdict)",
    )
    pshadow.add_argument("--sample-rate", type=float, default=1.0,
                         help="fraction of live batches dual-"
                         "dispatched (0 < r <= 1)")
    pshadow.add_argument("--seed", type=int, default=0,
                         help="batch-sampler seed")
    pshadow.set_defaults(func=cmd_policy_shadow)
    pdiff = psub.add_parser(
        "diff",
        help="live verdict-diff of the armed shadow window "
        "(GET /policy/diff)",
    )
    pdiff.add_argument("--live", action="store_true",
                       help="print the captured diff records, not "
                       "just the summary")
    pdiff.add_argument("--follow", action="store_true",
                       help="tail new diff records (seq-cursor "
                       "polls) until the window closes")
    pdiff.add_argument("--last", type=int, default=256,
                       help="newest N records")
    pdiff.add_argument("--interval", type=float, default=1.0,
                       help="follow-mode poll interval seconds")
    pdiff.add_argument("--json", action="store_true")
    pdiff.set_defaults(func=cmd_policy_diff)

    endpoint = sub.add_parser("endpoint")
    esub = endpoint.add_subparsers(dest="subcmd", required=True)
    elist = esub.add_parser("list")
    elist.set_defaults(func=cmd_endpoint_list)

    ident = sub.add_parser("identity")
    isub = ident.add_subparsers(dest="subcmd", required=True)
    ilist = isub.add_parser("list")
    ilist.set_defaults(func=cmd_identity_list)

    ipc = sub.add_parser("ipcache")
    ipsub = ipc.add_subparsers(dest="subcmd", required=True)
    dump = ipsub.add_parser("dump")
    dump.set_defaults(func=cmd_ipcache_dump)

    svc = sub.add_parser("service")
    svcsub = svc.add_subparsers(dest="service_cmd", required=True)
    slist = svcsub.add_parser("list")
    slist.set_defaults(func=cmd_service_list)

    ctp = sub.add_parser("ct")
    ctsub = ctp.add_subparsers(dest="ct_cmd", required=True)
    clist = ctsub.add_parser("list")
    clist.set_defaults(func=cmd_ct_list)

    obs = sub.add_parser(
        "observe",
        help="flow observability (the hubble observe analog): "
        "filtered dump or --follow tail of the agent's flow ring",
    )
    obs.add_argument("--follow", action="store_true",
                     help="tail new flows (long-poll)")
    obs.add_argument("-o", "--output", choices=["json", "compact"],
                     default="compact")
    obs.add_argument("--last", type=int, default=256,
                     help="newest N matches (one-shot mode)")
    obs.add_argument("--verdict", default=None,
                     help="FORWARDED|DROPPED")
    obs.add_argument("--drop-reason", default=None,
                     help='canonical reason, e.g. "Policy denied (L3)"')
    obs.add_argument("--identity", type=int, default=None,
                     help="matches either side of the pair")
    obs.add_argument("--ep", type=int, default=None)
    obs.add_argument("--port", type=int, default=None)
    obs.add_argument("--proto", default=None, help="tcp|udp|<number>")
    obs.add_argument("--direction", default=None,
                     choices=["ingress", "egress"])
    obs.add_argument("--since", default=None,
                     help="unix seconds or 30s/5m/1h window")
    obs.add_argument("--chip", type=int, default=None)
    obs.add_argument("--trace-id", default=None,
                     help="only flows captured under this trace "
                     "(the /debug/traces join key)")
    obs.add_argument("--cache-hit", action="store_true",
                     help="only flows whose verdict was served from "
                     "the device verdict cache")
    obs.add_argument("--tenant", default=None,
                     help="only flows submitted by this tenant/"
                     "namespace (the serving plane's fairness unit; "
                     "shed flows carry it on their Overload record)")
    obs.add_argument("--diff-status", default=None,
                     help="only flows the armed shadow window "
                     "re-verdicted: any, allow-to-deny, "
                     "deny-to-allow, changed")
    obs.add_argument("--timeout", type=float, default=5.0,
                     help="follow-mode poll timeout")
    obs.add_argument("--summary", action="store_true",
                     help="aggregations instead of records")
    obs.add_argument("--top", type=int, default=10,
                     help="rows per summary ranking")
    obs.set_defaults(func=cmd_observe)

    trc = sub.add_parser(
        "trace",
        help="span-plane reader: tree view of one trace, or "
        "--slowest N ranking (GET /debug/traces)",
    )
    trc.add_argument("trace_id", nargs="?", default=None,
                     help="32-hex trace id (as returned in "
                     "X-Trace-Id / flow records)")
    trc.add_argument("--slowest", type=int, default=None,
                     help="rank the N slowest traces by root span")
    trc.add_argument("--json", action="store_true",
                     help="machine-readable span dump")
    trc.set_defaults(func=cmd_trace)

    mon = sub.add_parser("monitor")
    mon.add_argument("--count", type=int, default=0,
                     help="stop after N events (0 = follow)")
    mon.add_argument("--timeout", type=float, default=5.0)
    mon.add_argument("--once", action="store_true",
                     help="exit after one empty poll")
    mon.add_argument("-v", "--verbose", action="store_true",
                     help="dissected human-readable rendering")
    mon.set_defaults(func=cmd_monitor)

    config = sub.add_parser("config")
    csub = config.add_subparsers(dest="config_cmd", required=True)
    cget = csub.add_parser("get")
    cget.set_defaults(func=cmd_config_get)
    cset = csub.add_parser("set")
    cset.add_argument(
        "set", nargs="+",
        help="Option=true|false pairs (or policy-enforcement=MODE)",
    )
    cset.set_defaults(func=cmd_config_set)

    fault = sub.add_parser(
        "fault", help="fault-injection framework (chaos testing)"
    )
    fsub = fault.add_subparsers(dest="fault_cmd", required=True)
    flist = fsub.add_parser("list")
    flist.set_defaults(func=cmd_fault_list)
    farm = fsub.add_parser("arm")
    farm.add_argument("site", help="e.g. engine.dispatch")
    farm.add_argument(
        "spec", nargs="?", default="raise",
        help='schedule, e.g. "raise:next=3", "hang:delay=0.5"; '
        'add chip=<ordinal> to kill exactly one mesh chip '
        '("raise:chip=3" — only the failover router\'s per-chip '
        "attribution probes see it)",
    )
    farm.set_defaults(func=cmd_fault_arm)
    fdisarm = fsub.add_parser("disarm")
    fdisarm.add_argument("site", nargs="?", default=None)
    fdisarm.add_argument("--all", action="store_true")
    fdisarm.set_defaults(func=cmd_fault_disarm)

    sbench = sub.add_parser(
        "serve-bench",
        help="sustained-QPS bench of the continuous serving plane "
        "(open-loop arrivals, SLO-aware dynamic batching, "
        "multi-tenant fair dispatch) — in-process demo world",
    )
    sbench.add_argument("--seconds", type=float, default=5.0)
    sbench.add_argument("--qps", type=float, default=200.0,
                        help="offered submissions/second across all "
                        "tenants (open loop)")
    sbench.add_argument("--flows", type=int, default=64,
                        help="flows per submission")
    sbench.add_argument("--tenants", default="default=1",
                        help='offered-load shares, e.g. '
                        '"compliant=1,noisy=10"')
    sbench.add_argument("--weights", default=None,
                        help='fairness weights (DRR), e.g. '
                        '"compliant=1,noisy=1"')
    sbench.add_argument("--batch-size", type=int, default=1 << 12,
                        help="coalesced device batch jit class")
    sbench.add_argument("--slo-ms", type=float, default=50.0,
                        help="per-flow deadline the dynamic batcher "
                        "dispatches early to protect")
    sbench.add_argument("--seed", type=int, default=7)
    sbench.set_defaults(func=cmd_serve_bench)

    top = sub.add_parser(
        "top",
        help="live performance plane: phase breakdown, batch fill, "
        "SLO burn, stall fraction, modeled gather bytes "
        "(GET /debug/perf, refreshed in place)",
    )
    top.add_argument("--once", action="store_true",
                     help="print one frame and exit")
    top.add_argument("-o", "--output", choices=("text", "json"),
                     default="text",
                     help="--once output format (json = the raw "
                     "/debug/perf snapshot)")
    top.add_argument("--interval", type=float, default=2.0,
                     help="refresh period in seconds")
    top.add_argument("--leaves", action="store_true",
                     help="include the per-leaf byte-model rows")
    top.set_defaults(func=cmd_top)

    status = sub.add_parser("status")
    status.set_defaults(func=cmd_status)
    met = sub.add_parser("metrics")
    met.set_defaults(func=cmd_metrics)
    return parser


def main(argv=None, api=None) -> int:
    args = make_parser().parse_args(argv)
    return args.func(api or _api(args), args)


if __name__ == "__main__":
    sys.exit(main())
