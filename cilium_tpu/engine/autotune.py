"""Batch-size / jit-class autotuning + gather-byte accounting.

The fused dispatch has two knobs that trade dispatch overhead against
latency and bytes-moved-per-tuple:

  * the BATCH SIZE — bigger batches amortize the per-dispatch floor
    but push p99 batch latency up linearly;
  * the HOT-PLANE PACK WIDTH — the hashed L4 entry tables' row lane
    count (compiler.tables.L4H_LANES): narrower rows halve the
    dominant per-tuple gather and lane-compare work, wider rows halve
    the bucket count (compiler.tables.repack_hash_lanes re-places the
    entries at any width without recompiling policy).

`autotune` runs a caller-supplied measurement over a small candidate
grid and picks the highest verdicts/s whose p99 batch latency stays
under the bound.  The choice is cached per TABLE SHAPE CLASS (the jit
cache key the dispatch programs compile against), so a long-running
server tunes once per layout instead of per publish — recompile
storms would otherwise show up in the existing
`cilium_jit_cache_*{site}` metrics this module deliberately rides.

`hot_gather_profile` is the bytes-moved model behind the tuner and
the bench's `hot_bytes_per_tuple` line: per-leaf bytes GATHERED per
tuple by the fused per-direction pipeline, split into the hot plane
(leaves the hashed-probe kernels actually gather) and the cold plane
(dense-fallback leaves a hot-only publication never ships).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class Trial:
    params: dict
    verdicts_per_sec: float
    p99_batch_ms: float
    admitted: bool


@dataclass
class TuneChoice:
    params: dict
    verdicts_per_sec: float
    p99_batch_ms: float
    trials: List[Trial] = field(default_factory=list)
    cached: bool = False


# shape-class key → TuneChoice (process-lifetime: the jit caches the
# tuned programs live exactly as long)
_CHOICES: Dict[tuple, TuneChoice] = {}


def shape_class_key(policy_tables) -> tuple:
    """The table shape class a tuned choice is valid for — the same
    axes that key the dispatch programs' jit cache entries."""
    rows = getattr(policy_tables, "l4_hash_rows", None)
    wrows = getattr(policy_tables, "l4_wild_rows", None)
    return (
        tuple(policy_tables.l4_meta.shape),
        int(policy_tables.id_table.shape[0]),
        None if rows is None else tuple(rows.shape),
        None if wrows is None else tuple(wrows.shape),
    )


def cached_choice(key: tuple) -> Optional[TuneChoice]:
    return _CHOICES.get(key)


def autotune(
    candidates: Sequence[dict],
    run_candidate: Callable[[dict], Tuple[float, float]],
    p99_bound_ms: float = float("inf"),
    cache_key: Optional[tuple] = None,
    log: Optional[Callable[[str], None]] = None,
) -> TuneChoice:
    """Measure every candidate (`run_candidate(params)` →
    (verdicts_per_sec, p99_batch_ms)) and pick the fastest admitted
    one; candidates over the p99 bound are rejected unless nothing
    fits (then the lowest-latency candidate wins — a serving plane
    must pick SOMETHING).  With `cache_key` the choice is memoized
    per table shape class."""
    if cache_key is not None:
        hit = _CHOICES.get(cache_key)
        if hit is not None:
            return hit
    trials: List[Trial] = []
    for params in candidates:
        vps, p99 = run_candidate(dict(params))
        admitted = p99 <= p99_bound_ms
        trials.append(Trial(dict(params), vps, p99, admitted))
        if log is not None:
            log(
                f"autotune candidate {params}: "
                f"{vps / 1e6:.1f}M verdicts/s, p99 {p99:.0f} ms"
                f"{'' if admitted else ' (over p99 bound)'}"
            )
    admitted = [t for t in trials if t.admitted]
    if admitted:
        best = max(admitted, key=lambda t: t.verdicts_per_sec)
    else:
        # bound unsatisfiable on this hardware: throughput wins
        # (shrinking the batch further only lowers BOTH)
        best = max(trials, key=lambda t: t.verdicts_per_sec)
    choice = TuneChoice(
        params=best.params,
        verdicts_per_sec=best.verdicts_per_sec,
        p99_batch_ms=best.p99_batch_ms,
        trials=trials,
    )
    if cache_key is not None:
        _CHOICES[cache_key] = choice
        choice.cached = True
    return choice


def measure_dispatch(
    step: Callable,
    make_args: Callable[[], tuple],
    n_tuples_per_call: int,
    reps: int = 4,
    outstanding: int = 2,
    sync_reps: int = 3,
) -> Tuple[float, float]:
    """One candidate measurement: a short pipelined loop for
    sustained verdicts/s (dispatch overlap, like the serving loop)
    plus a few synchronous calls for the per-batch latency tail.
    `make_args()` returns fresh call args per rep (carried/donated
    buffers must be re-made by the caller's closure)."""
    import jax

    # warmup/compile
    out = step(*make_args())
    jax.block_until_ready(out)
    outs = []
    t0 = time.perf_counter()
    for _ in range(reps):
        outs.append(step(*make_args()))
        if len(outs) > outstanding:
            jax.block_until_ready(outs.pop(0))
    jax.block_until_ready(outs)
    vps = reps * n_tuples_per_call / (time.perf_counter() - t0)
    lat = []
    for _ in range(sync_reps):
        t1 = time.perf_counter()
        jax.block_until_ready(step(*make_args()))
        lat.append(time.perf_counter() - t1)
    # p99 over a handful of sync reps is the max — the honest tail
    # estimate at this sample count
    return vps, max(lat) * 1000.0


# ---------------------------------------------------------------------------
# Gather-byte accounting (the bytes-moved model)
# ---------------------------------------------------------------------------


def hot_gather_profile(tables, packed_io: bool = True) -> List[dict]:
    """Per-leaf bytes gathered per tuple by the fused per-direction
    pipeline, with the plane ('hot'/'cold') and pipeline stage of
    each.  `tables` is an engine.datapath.DatapathTables; cold rows
    are reported at ZERO bytes when the policy tables carry the
    hashed entry pair (the kernel never gathers them) and at their
    dense-probe cost otherwise.

    Broadcast compares (stashes, prefilter ranges) move no
    gather bytes — they are compute, priced separately — so they do
    not appear here."""
    rows: List[dict] = []
    pol = tables.policy

    def add(stage, leaf, plane, nbytes, note=""):
        rows.append(
            {
                "stage": stage,
                "leaf": leaf,
                "plane": plane,
                "bytes_per_tuple": float(nbytes),
                "note": note,
            }
        )

    # CT: one bucket-row gather serves the service + flow probes
    ct_lanes = int(np.asarray(tables.ct.buckets).shape[1])
    ct_ew = int(getattr(tables.ct, "entry_words", 5))
    add(
        "ct", "ct.buckets", "hot", ct_lanes * 4,
        "1 row gather"
        + (f", sub-word {ct_ew}-word entries" if ct_ew != 5 else ""),
    )
    # LB: service bucket row gather (egress only — averaged at 1/2);
    # the inline layout keys+backends in one row, the classic layout
    # pays a second backend-row gather on service hits (rare, priced
    # at the key row only)
    lb_rows = getattr(tables.lb, "rows", None)
    if lb_rows is None:
        lb_rows = getattr(tables.lb, "buckets", None)
    if lb_rows is not None:
        lb_lanes = int(np.asarray(lb_rows).shape[1])
        add(
            "lb", "lb.rows", "hot", lb_lanes * 4 / 2,
            "egress half-batches only",
        )
    # ipcache: the bucketized form pays one bucket-row gather plus
    # one range-class row gather per distinct non-/32 prefix length
    # (the hashed table that replaced the [B, P] broadcast scan);
    # the DIR-24-8 fallback is two element gathers
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    ipc = getattr(tables, "ipcache", None)
    if isinstance(ipc, IPCacheDevice):
        ip_lanes = int(np.asarray(ipc.buckets).shape[1])
        sub_note = ""
        if getattr(ipc, "bucket_entries", 0):
            sub_note = (
                f", sub-word val{ipc.value_width}/"
                f"l3w{ipc.l3_width}"
            )
        add(
            "ipcache", "ipcache.buckets", "hot", ip_lanes * 4,
            "1 bucket-row gather" + sub_note,
        )
        if ipc.range_rows is not None:
            n_classes = len(ipc.range_class_plens)
            rw = int(np.asarray(ipc.range_rows).shape[1])
            add(
                "ipcache", "ipcache.range_rows", "hot",
                n_classes * rw * 4,
                f"{n_classes} prefix-length class gathers",
            )
        else:
            add(
                "ipcache", "ipcache.ranges", "hot", 0,
                "[B, P] broadcast scan (compute, not gathers)",
            )
    else:
        add("ipcache", "ipcache.dir24_8", "hot", 8, "2 element gathers")
    hash_rows = getattr(pol, "l4_hash_rows", None)
    if hash_rows is not None:
        from cilium_tpu.compiler.tables import l4_entry_words

        lanes = int(np.asarray(hash_rows).shape[1])
        wlanes = int(np.asarray(pol.l4_wild_rows).shape[1])
        ew = l4_entry_words(pol)
        add(
            "lattice", "l4_hash_rows", "hot", lanes * 4,
            f"pack width {lanes}"
            + (", sub-word 2-word entries" if ew == 2 else ""),
        )
        add(
            "lattice", "l4_wild_rows", "hot", wlanes * 4,
            f"pack width {wlanes}"
            + (", sub-word 2-word entries" if ew == 2 else ""),
        )
        if ew == 2:
            # the compact form drops the per-entry proxy copy and
            # reconstructs it with ONE l4_meta element gather at the
            # combined slot index — priced honestly
            add(
                "lattice", "l4_meta", "hot", 4,
                "compact-entry proxy reconstruction",
            )
        # identity index rides the idx-form ipcache when present;
        # otherwise one id_direct element gather
        add("lattice", "id_direct", "hot", 4, "skipped w/ idx ipcache")
        for leaf in ("port_slot", "l4_allow_bits"):
            add("lattice", leaf, "cold", 0, "hashed probe active")
        add("lattice", "l3_allow_bits", "hot", 0, "l3-plane ipcache")
    else:
        add("lattice", "port_slot", "cold", 2, "dense slot probe")
        add("lattice", "l4_allow_bits", "cold", 4, "dense bit probe")
        add("lattice", "l4_meta", "cold", 4, "dense meta probe")
        add("lattice", "l3_allow_bits", "hot", 4, "l3 word gather")
        add("lattice", "id_direct", "hot", 4, "identity index")
    # batch IO: packed flow columns in, packed verdict words out
    add(
        "io", "flow_batch", "hot", 16 if packed_io else 32,
        "H2D packed columns" if packed_io else "H2D u32 columns",
    )
    return rows


def hot_bytes_per_tuple(tables, packed_io: bool = True) -> float:
    """Total HOT-plane bytes gathered per tuple (the headline
    `hot_bytes_per_tuple` bench metric)."""
    return sum(
        r["bytes_per_tuple"]
        for r in hot_gather_profile(tables, packed_io=packed_io)
        if r["plane"] == "hot"
    )


def cold_bytes_per_tuple(tables) -> float:
    return sum(
        r["bytes_per_tuple"]
        for r in hot_gather_profile(tables)
        if r["plane"] == "cold"
    )


# ---------------------------------------------------------------------------
# Verdict memoization (engine/memo.py) tuning
# ---------------------------------------------------------------------------


MEMO_ROWS_MIN = 1 << 10
MEMO_ROWS_MAX = 1 << 20


def memo_rows_for_headroom(
    headroom_bytes: int,
    entries: int = 8,
    headroom_frac: float = 0.25,
) -> int:
    """Largest pow2 verdict-cache row count whose device buffer fits
    within `headroom_frac` of the given HBM headroom (the ROADMAP's
    lever (d): size the cache for the access pattern AND the budget,
    not a fixed list).  Row cost mirrors engine/memo.py's layout:
    CACHE_WORDS * entries + 1 u32 words per row, one scratch row.
    Clamped to [MEMO_ROWS_MIN, MEMO_ROWS_MAX]; returns 0 when even
    the minimum doesn't fit (the tuner then keeps memo off)."""
    from cilium_tpu.engine.memo import CACHE_WORDS

    row_bytes = (CACHE_WORDS * int(entries) + 1) * 4
    budget = max(int(headroom_bytes * headroom_frac), 0)
    rows = MEMO_ROWS_MIN
    if (rows + 1) * row_bytes > budget:
        return 0
    while (
        rows < MEMO_ROWS_MAX
        and (rows * 2 + 1) * row_bytes <= budget
    ):
        rows <<= 1
    return rows


def memo_candidates(
    batch: int,
    include_off: bool = True,
    rows_options: "Optional[Sequence[int]]" = None,
    rep_shifts: Sequence[int] = (2,),
    store=None,
    hbm_bytes: int = 16 << 30,
    headroom_frac: float = 0.25,
    rows_cap: Optional[int] = None,
) -> List[dict]:
    """Verdict-memoization candidates for the tuner (the schema
    bench's `_run_memo_candidate` consumes): cache row counts ×
    rep/miss compaction capacities (batch >> shift, so the lattice
    gather chain shrinks when the workload's key skew covers it).
    `{"memo": False}` is the ENABLE THRESHOLD: when the sort+probe
    overhead beats the gathers saved on this workload the tuner
    keeps the uncached program — the choice is cached per table
    shape class like the batch/pack-width choice, so a long-running
    server decides once per layout.

    Capacity is HBM-aware: with a `store` (any object exposing
    chip_bytes() → {ordinal: resident bytes}, e.g. the daemon's
    DeviceTableStore or the router's DatapathStore), the candidate
    row counts derive from the MEASURED per-shard headroom —
    hbm_bytes minus the worst chip's resident table bytes — instead
    of a fixed list, so the cache never competes with the sharded
    table planes for the same HBM.  An explicit `rows_options`
    overrides."""
    if rows_options is None:
        if store is not None:
            try:
                per_chip = store.chip_bytes() or {}
            except Exception:  # pragma: no cover — defensive
                per_chip = {}
            worst = max(per_chip.values()) if per_chip else 0
            rows = memo_rows_for_headroom(
                max(hbm_bytes - worst, 0),
                headroom_frac=headroom_frac,
            )
            if rows and rows_cap:
                rows = min(rows, int(rows_cap))
            rows_options = (rows,) if rows else ()
        else:
            rows_options = (1 << 14,)
    cands: List[dict] = [{"memo": False}] if include_off else []
    for rows in rows_options:
        for shift in rep_shifts:
            cands.append(
                {
                    "memo": True,
                    "rows": int(rows),
                    "rep_cap": max(int(batch) >> shift, 1 << 10),
                }
            )
    return cands


# ---------------------------------------------------------------------------
# Online re-tune: the telemetry-driven layout loop (perf plane consumer)
# ---------------------------------------------------------------------------


# hysteresis bounds: drift must exceed these before a re-tune fires,
# and the cooldown gates how often one may fire at all — the
# README's "retune hysteresis contract"
RETUNE_DEFAULTS = {
    # serving-window p99 above factor x the post-swap baseline
    "p99_factor": 1.5,
    # batch-fill p50 below this while the plane is actually batching
    "fill_low_pct": 30.0,
    # windowed ingest-stall fraction above this
    "stall_frac": 0.25,
    # wall-clock + batch-count cooldown between swaps
    "cooldown_s": 30.0,
    "min_batches": 64,
    # windows thinner than this can't witness drift
    "min_window": 32,
}


def retune_trigger(perf, plane, config=None):
    """The drift detector: reads the perf plane's serving_p99 /
    batch-fill / stall windows against the hysteresis bounds and
    returns a trigger name ('p99_drift' | 'fill_low' | 'stall') or
    None.  Pure read — no side effects, so tests can drive it with
    injected telemetry."""
    cfg = dict(RETUNE_DEFAULTS)
    cfg.update(config or {})
    now = time.monotonic()
    # cooldown: wall clock AND batch count since the last swap
    if perf.last_retune_monotonic is not None:
        if now - perf.last_retune_monotonic < cfg["cooldown_s"]:
            return None
        if perf.seq - perf.batches_at_retune < cfg["min_batches"]:
            return None
    wall = perf.phases["wall"].stats(now)
    if wall["n"] < cfg["min_window"]:
        return None
    p99_ms = (
        plane._window_p99_ms() if plane is not None else 0.0
    )
    if perf.baseline_p99_ms is None:
        # first full window since start/swap: learn the baseline,
        # never fire on it
        perf.baseline_p99_ms = p99_ms
        return None
    if (
        perf.baseline_p99_ms > 0
        and p99_ms > cfg["p99_factor"] * perf.baseline_p99_ms
    ):
        return "p99_drift"
    fill = perf.fill.stats(now)
    if fill["n"] >= cfg["min_window"] and (
        fill["p50"] < cfg["fill_low_pct"]
    ):
        return "fill_low"
    if perf.stall_fraction(now) > cfg["stall_frac"]:
        return "stall"
    return None


def _datapath_lane_options(daemon):
    """(ct_opts, ip_opts) for the fused-plane hot-lane sweep: CT
    bucket-row widths (compact_ct_snapshot's lanes seam, priced at
    lanes*4 bytes/tuple like every bucketized gather) and the
    ipcache plane's wide-vs-sub-word row widths.  Each option is
    None ("keep the current layout") or a dict of candidate params;
    worlds with no assemblable fused datapath sweep nothing."""
    ct_opts: List[Optional[dict]] = [None]
    ip_opts: List[Optional[dict]] = [None]
    try:
        dt = daemon.datapath_tables()
    except Exception:
        return ct_opts, ip_opts
    from cilium_tpu.ct.device import compact_ct_snapshot
    from cilium_tpu.ipcache.lpm import (
        IPCacheDevice,
        subword_ipcache,
    )

    ct_now = int(np.asarray(dt.ct.buckets).shape[1])
    for lanes in (32, 64):
        if lanes == ct_now:
            continue
        try:  # only offer widths this snapshot can actually pack to
            compact_ct_snapshot(dt.ct, lanes=lanes)
        except ValueError:
            continue
        ct_opts.append({"ct_lanes": lanes})
    ipc = dt.ipcache
    if isinstance(ipc, IPCacheDevice) and hasattr(ipc, "buckets"):
        ip_now = int(np.asarray(ipc.buckets).shape[1])
        if getattr(ipc, "bucket_entries", 0):
            # currently sub-word: nothing narrower to offer; the
            # wide layout is not reachable through a lane knob
            pass
        elif ipc.values_are_idx:
            try:
                packed = subword_ipcache(ipc)
                ip_packed = int(
                    np.asarray(packed.buckets).shape[1]
                )
                if ip_packed != ip_now:
                    ip_opts.append({
                        "ip_lanes": ip_packed,
                        "ip_subword": True,
                    })
            except ValueError:
                pass
    return ct_opts, ip_opts


def retune_candidates(daemon, plane):
    """The online candidate grid: batch class (half/same/double),
    hot-plane pack width (the repack_hash_lanes widths), memo
    capacity (HBM-aware via the store's chip_bytes seam), and the
    fused plane's CT / ipcache hot-lane widths (the
    subword_datapath_tables ct_lanes seam + the ipcache sub-word
    toggle), all scored by the same gatherprof byte model."""
    batch = plane.batch_size if plane is not None else 1 << 12
    batches = sorted(
        {max(batch // 2, 256), batch, min(batch * 2, 1 << 15)}
    )
    compiler = daemon.endpoint_manager._fleet_compiler
    lanes_now = compiler.hash_lanes
    lanes_opts = sorted({lanes_now, 32, 64})
    store = getattr(
        daemon.endpoint_manager, "_device_store", None
    )
    memo_rows = [daemon.verdict_cache_rows]
    if store is not None:
        for c in memo_candidates(
            batch, include_off=False, store=store
        ):
            memo_rows.append(c["rows"])
    memo_rows = sorted(set(memo_rows))
    ct_opts, ip_opts = _datapath_lane_options(daemon)
    cands = []
    for b in batches:
        for lanes in lanes_opts:
            for rows in memo_rows:
                for ct in ct_opts:
                    for ip in ip_opts:
                        cand = {
                            "batch": b, "hash_lanes": lanes,
                            "memo_rows": rows,
                        }
                        if ct:
                            cand.update(ct)
                        if ip:
                            cand.update(ip)
                        cands.append(cand)
    return cands


def _model_run_candidate(daemon, plane):
    """Default candidate scorer when no measured `run_candidate` is
    supplied: rank by the gatherprof byte model at each candidate's
    pack width, scaled by the plane's measured verdicts/s EWMA —
    deterministic and sweep-free, so the serve loop never pays a
    device measurement campaign mid-stream.  Callers wanting a
    MEASURED sweep (bench) pass their own run_candidate."""
    _, tables, _ = daemon.endpoint_manager.published()
    base_vps = max(daemon.perf.verdicts_per_sec(), 1.0)
    base_lanes = daemon.endpoint_manager._fleet_compiler.hash_lanes
    base_batch = plane.batch_size if plane is not None else 1 << 12
    base_bpt = None
    base_ct_lanes = base_ip_lanes = None
    if tables is not None:
        try:
            dt = daemon.datapath_tables(policy=tables)
            base_bpt = hot_bytes_per_tuple(dt)
            base_ct_lanes = int(
                np.asarray(dt.ct.buckets).shape[1]
            )
            if hasattr(dt.ipcache, "buckets"):
                base_ip_lanes = int(
                    np.asarray(dt.ipcache.buckets).shape[1]
                )
        except Exception:
            base_bpt = None

    def run(params):
        lanes = int(params.get("hash_lanes", base_lanes))
        batch = int(params.get("batch", base_batch))
        # modeled bytes scale with the dominant hashed-pair lanes;
        # throughput ~ 1/bytes, p99 ~ batch/vps
        if base_bpt:
            # the hashed pair contributes lanes*4 + wlanes*4; scale
            # only that share of the model
            delta_b = (lanes - base_lanes) * 4 * 2
            # the fused plane's bucketized gathers each price one
            # row at lanes*4 (hot_gather_profile): a candidate CT /
            # ipcache width moves exactly that share
            if base_ct_lanes and params.get("ct_lanes"):
                delta_b += (
                    int(params["ct_lanes"]) - base_ct_lanes
                ) * 4
            if base_ip_lanes and params.get("ip_lanes"):
                delta_b += (
                    int(params["ip_lanes"]) - base_ip_lanes
                ) * 4
            bpt = max(base_bpt + delta_b, 1.0)
            vps = base_vps * base_bpt / bpt
        else:
            vps = base_vps
        vps *= batch / max(base_batch, 1)  # amortized floor
        p99_ms = batch / max(vps, 1.0) * 1000.0
        return vps, p99_ms

    return run


def online_retune(
    daemon,
    *,
    trigger=None,
    force: bool = False,
    candidates=None,
    run_candidate=None,
    p99_bound_ms: Optional[float] = None,
    config=None,
) -> Optional[dict]:
    """The serve-loop-driven re-tune controller: watch the perf
    plane's serving_p99 / batch-fill / stall windows, and when drift
    exceeds the hysteresis bounds re-run the cached autotuner over
    the candidate grid (batch class, pack width, memo capacity) and
    apply the choice through the existing seams —

      * pack width: FleetCompiler.set_hash_lanes + regenerate_all →
        new layout stamp → the device store refuses the delta,
        full-uploads, deltas resume (bit-identity by construction);
      * batch class: ServingPlane.set_batch_size (in-flight batches
        keep their meta-snapshotted pad class);
      * memo capacity: verdict_cache_rows + drop the device buffer
        (the lazy _ensure_verdict_cache recreates it at the new
        size, stamp-checked as ever).

    Returns the retune record (also appended to perf.retunes and
    counted in cilium_retune_total{trigger}), or None when the
    hysteresis said "hold"."""
    from cilium_tpu import tracing
    from cilium_tpu.metrics import registry as metrics

    perf = daemon.perf
    plane = getattr(daemon, "serving", None)
    if trigger is None:
        if force:
            trigger = "forced"
        else:
            trigger = retune_trigger(perf, plane, config)
            if trigger is None:
                return None
    if candidates is None:
        candidates = retune_candidates(daemon, plane)
    if run_candidate is None:
        run_candidate = _model_run_candidate(daemon, plane)
    if p99_bound_ms is None:
        p99_bound_ms = (
            plane.slo_s * 1000.0 if plane is not None
            else float("inf")
        )
    _, tables, _ = daemon.endpoint_manager.published()
    cache_key = None
    if tables is not None:
        cache_key = shape_class_key(tables) + ("online",)
    compiler = daemon.endpoint_manager._fleet_compiler
    before = {
        "batch": plane.batch_size if plane is not None else None,
        "hash_lanes": compiler.hash_lanes,
        "memo_rows": daemon.verdict_cache_rows,
        "layout_stamp": (
            tables_layout_stamp(tables)
            if tables is not None else None
        ),
    }
    with tracing.tracer.span(
        "autotune.retune", site="autotune",
        attrs={"trigger": trigger},
    ) as sp:
        choice = autotune(
            candidates, run_candidate,
            p99_bound_ms=p99_bound_ms, cache_key=cache_key,
        )
        params = choice.params
        applied = {}
        if (
            plane is not None
            and params.get("batch")
            and int(params["batch"]) != plane.batch_size
        ):
            plane.set_batch_size(int(params["batch"]))
            applied["batch"] = int(params["batch"])
        rows = params.get("memo_rows")
        if rows and int(rows) != daemon.verdict_cache_rows:
            daemon.verdict_cache_rows = int(rows)
            with daemon.lock:
                daemon.verdict_cache = None  # lazy re-create
            applied["memo_rows"] = int(rows)
        lanes = params.get("hash_lanes")
        if lanes and int(lanes) != compiler.hash_lanes:
            compiler.set_hash_lanes(int(lanes))
            daemon.regenerate_all(f"online retune ({trigger})")
            applied["hash_lanes"] = int(lanes)
        # fused-plane hot-lane widths: the CT row width / ipcache
        # sub-word knobs feed daemon.datapath_tables, so the NEXT
        # datapath publish ships the new layout and the store's
        # cross-layout refusal turns it into exactly one full
        # upload (candidates only carry these keys when they differ
        # from the current layout — see retune_candidates)
        dp_changed = False
        ct_l = params.get("ct_lanes")
        if ct_l and int(ct_l) != getattr(
            daemon, "datapath_ct_lanes", None
        ):
            daemon.datapath_ct_lanes = int(ct_l)
            applied["ct_lanes"] = int(ct_l)
            dp_changed = True
        if "ip_subword" in params and bool(
            params["ip_subword"]
        ) != bool(getattr(daemon, "datapath_ip_subword", False)):
            daemon.datapath_ip_subword = bool(params["ip_subword"])
            applied["ip_subword"] = bool(params["ip_subword"])
            dp_changed = True
        if dp_changed:
            router = getattr(daemon, "mesh_router", None)
            if router is not None and router.dp_store is not None:
                try:
                    router.publish_datapath(
                        daemon.datapath_tables()
                    )
                except Exception:  # noqa: BLE001 — next churn
                    pass  # publish re-ships the new layout
        _, tables_after, _ = daemon.endpoint_manager.published()
        after_stamp = (
            tables_layout_stamp(tables_after)
            if tables_after is not None else None
        )
        sp.attrs["applied"] = dict(applied)
        metrics.retune_total.inc(trigger)
        record = perf.note_retune(
            {
                "trigger": trigger,
                "choice": dict(params),
                "applied": applied,
                "before": before,
                "layout_stamp_after": after_stamp,
            }
        )
    return record


def tables_layout_stamp(tables) -> Optional[int]:
    """The published tables' layout stamp (compiler.tables
    .tables_layout_version) — None for tables without the hashed
    pair (the stamp would not gate a delta anyway)."""
    try:
        from cilium_tpu.compiler.tables import (
            tables_layout_version,
        )

        return int(tables_layout_version(tables))
    except Exception:
        return None


def effective_hot_bytes_per_tuple(
    tables, dedup_factor: float, packed_io: bool = True
) -> float:
    """The gather-byte model under intra-batch dedup: gatherprof's
    hot_bytes_per_tuple divided by the measured dedup factor — the
    bytes the lattice ACTUALLY moves per tuple once duplicates
    collapse onto one representative.  Cache hits shrink it further
    (a hit gathers one cache row instead of the lattice rows); this
    line deliberately prices only the dedup level so the bench's
    `effective_verdicts_per_sec_per_chip` stays the measured truth
    and the model stays conservative."""
    return hot_bytes_per_tuple(tables, packed_io=packed_io) / max(
        float(dedup_factor), 1.0
    )
