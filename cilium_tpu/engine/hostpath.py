"""Composed host-side datapath oracle: the differential-testing twin
of the fused device step (engine/datapath.py).

Every stage is the plain-Python reference implementation over the host
data structures (HostLPM / ServiceManager / CTMap / policy map
states), evaluated per tuple in the same order the fused program
fuses: prefilter → LB/DNAT with service-scope stickiness → conntrack
→ ipcache identity derivation → policy lattice → combine
(bpf_lxc.c:440/899).  Device outputs must be BIT-IDENTICAL to this on
any input — the bench's pre-timing gate, the multichip dryrun and the
test suite all cross-check through it.
"""

from __future__ import annotations

import ipaddress
from typing import Dict

import numpy as np


class HostLPM:
    """Fast host-side LPM oracle: /32s in a dict, other prefixes
    scanned longest-first (their count stays small in the bench
    worlds, unlike the /32 population)."""

    def __init__(self, mapping: Dict[str, int]):
        self.exact = {}
        self.ranges = []
        for cidr, num_id in mapping.items():
            net = ipaddress.ip_network(cidr, strict=False)
            if net.version != 4:
                continue
            if net.prefixlen == 32:
                self.exact[int(net.network_address)] = num_id
            else:
                self.ranges.append(
                    (
                        net.prefixlen,
                        int(net.network_address),
                        int(net.netmask),
                        num_id,
                    )
                )
        self.ranges.sort(key=lambda r: -r[0])

    def lookup(self, ip: int) -> int:
        hit = self.exact.get(ip)
        if hit is not None:
            return hit
        for _, base, mask, num_id in self.ranges:
            if (ip & mask) == base:
                return num_id
        return 0


def lb_select_host(ct, svc, saddr, daddr, sport, dport, proto):
    """Host-side backend selection for one flow against a looked-up
    service: the CT service-scope stickiness probe first (lb4_local's
    ct lookup over both key layouts), fnv1a hash fallback.  The ONE
    reference implementation — composed_oracle and
    policy.trace.trace_tuple both call it, so the explain tool can
    never diverge from the oracle's backend choice.  Returns
    (slave 1-based, sticky bool)."""
    from cilium_tpu.ct.table import (
        CT_ESTABLISHED,
        CT_REPLY,
        CT_SERVICE,
        CTTuple,
        TUPLE_F_SERVICE,
    )
    from cilium_tpu.engine.hashtable import _fnv1a_host

    slave = 0
    sticky = False
    st_res = ct.lookup(
        CTTuple(daddr, saddr, dport, sport, proto), CT_SERVICE
    )
    if st_res in (CT_ESTABLISHED, CT_REPLY):
        for key in (
            CTTuple(saddr, daddr, sport, dport, proto,
                    TUPLE_F_SERVICE | 1),
            CTTuple(daddr, saddr, dport, sport, proto,
                    TUPLE_F_SERVICE),
            CTTuple(saddr, daddr, sport, dport, proto,
                    TUPLE_F_SERVICE),
            CTTuple(daddr, saddr, dport, sport, proto,
                    TUPLE_F_SERVICE | 1),
        ):
            e = ct.entries.get(key)
            if e is not None:
                slave = e.slave
                sticky = True
                break
    if not (0 < slave <= len(svc.backends)):
        words = np.array(
            [[saddr, daddr, (sport << 16) | dport, proto]],
            dtype=np.uint32,
        )
        slave = (
            int(_fnv1a_host(words)[0]) % len(svc.backends)
        ) + 1
        sticky = False
    return slave, sticky


def lattice_fold_host(
    states,
    ep_index,
    identity,
    dport,
    proto,
    direction,
    is_fragment=None,
    pad_to: int = 0,
):
    """Host-path fold of the bare verdict lattice — the degraded-mode
    twin of engine.verdict.evaluate_batch: the ONE lattice reference
    (engine.oracle.policy_can_access, counterless form) applied per
    tuple over the per-endpoint realized map states, so verdicts are
    bit-identical to the device kernel on any input.  The daemon
    fails over to this when the dispatch circuit breaker opens.

    `count_hits=False` on the oracle call: the device path this
    substitutes for (evaluate_batch) carries no entry counters, and
    degraded service must not leave different observable state than
    healthy service.  Missing endpoints (None state) evaluate
    against an empty map: default-deny, like an axis the compiler
    padded.

    Returns a Verdicts-shaped namespace (allowed u8, proxy_port i32,
    match_kind u8), zero-padded to `pad_to` when given — the batch
    shape the drain/event-fold slices with [:valid]."""
    from types import SimpleNamespace

    from cilium_tpu.engine.oracle import policy_can_access

    b = len(ep_index)
    n = max(b, pad_to)
    allowed = np.zeros(n, np.uint8)
    proxy = np.zeros(n, np.int32)
    kind = np.zeros(n, np.uint8)
    if is_fragment is None:
        is_fragment = np.zeros(b, bool)
    empty: Dict = {}
    for i in range(b):
        state = states[int(ep_index[i])]
        v = policy_can_access(
            empty if state is None else state,
            int(identity[i]),
            int(dport[i]),
            int(proto[i]),
            int(direction[i]),
            bool(is_fragment[i]),
            count_hits=False,
        )
        allowed[i] = 1 if v.allowed else 0
        proxy[i] = v.proxy_port
        kind[i] = v.match_kind
    return SimpleNamespace(
        allowed=allowed, proxy_port=proxy, match_kind=kind
    )


def composed_oracle(ctx, states, flows_dict, idx_list,
                    return_stages: bool = False):
    """Per-tuple host evaluation of the FULL fused pipeline.  `ctx`
    carries {"prefilter": HostLPM, "ipcache": HostLPM, "ct": CTMap,
    "mgr": ServiceManager}; `states` is the per-endpoint realized map
    state list in endpoint-axis order.  Returns (allowed, proxy,
    sec_id) arrays for the sampled indices; with `return_stages` a
    fourth dict {pre_drop, ct_res, match_kind, lb_hit, ipcache_miss}
    of per-stage intermediate decisions rides along — the telemetry
    plane's per-stage bit-identity gate compares the device's stage
    columns against these."""
    from cilium_tpu.ct.table import (
        CT_EGRESS,
        CT_ESTABLISHED,
        CT_INGRESS,
        CT_NEW,
        CT_RELATED,
        CT_REPLY,
        CTTuple,
    )
    from cilium_tpu.engine.oracle import policy_can_access
    from cilium_tpu.identity import RESERVED_WORLD
    from cilium_tpu.lb.service import L3n4Addr
    from cilium_tpu.maps.policymap import INGRESS

    pre, ipc, ct, mgr = (
        ctx["prefilter"], ctx["ipcache"], ctx["ct"], ctx["mgr"],
    )
    out_allow = np.zeros(len(idx_list), np.uint8)
    out_proxy = np.zeros(len(idx_list), np.int32)
    out_sec = np.zeros(len(idx_list), np.uint32)
    st_pre = np.zeros(len(idx_list), bool)
    st_ct = np.zeros(len(idx_list), np.uint8)
    st_kind = np.zeros(len(idx_list), np.uint8)
    st_lb = np.zeros(len(idx_list), bool)
    st_miss = np.zeros(len(idx_list), bool)
    f = flows_dict
    for row, i in enumerate(idx_list):
        ep = int(f["ep_index"][i])
        saddr, daddr = int(f["saddr"][i]), int(f["daddr"][i])
        sport, dport = int(f["sport"][i]), int(f["dport"][i])
        proto = int(f["proto"][i])
        direction = int(f["direction"][i])
        frag = bool(f["is_fragment"][i])

        pre_drop = pre.lookup(saddr) != 0

        eff_daddr, eff_dport = daddr, dport
        if direction != INGRESS:
            svc = mgr.lookup(
                L3n4Addr(str(ipaddress.ip_address(daddr)), dport, proto)
            )
            if svc is not None and svc.backends:
                slave, _ = lb_select_host(
                    ct, svc, saddr, daddr, sport, dport, proto
                )
                b = svc.backends[slave - 1]
                eff_daddr = b.addr.ip_u32()
                eff_dport = b.addr.port
                st_lb[row] = True

        ct_res = ct.lookup(
            CTTuple(eff_daddr, saddr, eff_dport, sport, proto),
            CT_INGRESS if direction == INGRESS else CT_EGRESS,
        )

        sec_ip = saddr if direction == INGRESS else eff_daddr
        sec_id = ipc.lookup(sec_ip)
        if sec_id == 0:
            sec_id = RESERVED_WORLD
            st_miss[row] = True

        v = policy_can_access(
            states[ep], sec_id, eff_dport, proto, direction, frag
        )
        pass_ct = ct_res in (CT_REPLY, CT_RELATED)
        allowed = (not pre_drop) and (pass_ct or v.allowed)
        proxy = (
            v.proxy_port
            if v.allowed and ct_res in (CT_NEW, CT_ESTABLISHED) and allowed
            else 0
        )
        out_allow[row] = 1 if allowed else 0
        out_proxy[row] = proxy
        out_sec[row] = sec_id
        st_pre[row] = pre_drop
        st_ct[row] = ct_res
        st_kind[row] = v.match_kind
    if return_stages:
        return out_allow, out_proxy, out_sec, {
            "pre_drop": st_pre,
            "ct_res": st_ct,
            "match_kind": st_kind,
            "lb_hit": st_lb,
            "ipcache_miss": st_miss,
        }
    return out_allow, out_proxy, out_sec
