"""Host reference evaluator: the 3-probe verdict lattice on a
PolicyMapState dict.

Line-for-line semantic port of `__policy_can_access`
(/root/reference/bpf/lib/policy.h:46-110):

  probe 1: exact (identity, dport, proto)   [skipped for fragments]
  probe 2: L3-only (identity, 0, 0)         → plain allow, no proxy
  probe 3: L4 wildcard (0, dport, proto)    [skipped for fragments]
  miss:    DROP_POLICY (DROP_FRAG_NOSUPPORT for fragments)

Probe hits bump the entry's packets/bytes counters (policy.h:66-68,
92-93, 101-102), which is why this oracle mutates the state's entries.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

from cilium_tpu.maps.policymap import (
    PolicyKey,
    PolicyMapState,
)

# Match-kind codes returned alongside the verdict (the engine returns
# the same codes, so oracle/device outputs are comparable elementwise).
MATCH_NONE = 0  # DROP_POLICY
MATCH_L4 = 1  # probe 1 hit
MATCH_L3 = 2  # probe 2 hit
MATCH_L4_WILD = 3  # probe 3 hit
MATCH_FRAG_DROP = 4  # DROP_FRAG_NOSUPPORT

# Drop reason codes (bpf/lib/common.h:240,264; negative returns).
DROP_POLICY = -133
DROP_FRAG_NOSUPPORT = -157


@dataclass
class Verdict:
    allowed: bool
    proxy_port: int
    match_kind: int


def policy_can_access(
    state: PolicyMapState,
    identity: int,
    dport: int,
    proto: int,
    direction: int,
    is_fragment: bool = False,
    pkt_len: int = 0,
    count_hits: bool = True,
) -> Verdict:
    """One tuple through the lattice (policy.h:46).  With
    `count_hits=False` the matched entry's packet/byte counters are
    left untouched — the degraded-serving host fold substitutes for
    the counterless device kernel (evaluate_batch) and must not
    leave different observable state than healthy service."""
    if not is_fragment:
        entry = state.get(
            PolicyKey(identity, dport, proto, direction)
        )
        if entry is not None:
            if count_hits:
                entry.packets += 1
                entry.bytes += pkt_len
            return Verdict(True, entry.proxy_port, MATCH_L4)

    entry = state.get(PolicyKey(identity, 0, 0, direction))
    if entry is not None:
        if count_hits:
            entry.packets += 1
            entry.bytes += pkt_len
        return Verdict(True, 0, MATCH_L3)

    if not is_fragment:
        entry = state.get(PolicyKey(0, dport, proto, direction))
        if entry is not None:
            if count_hits:
                entry.packets += 1
                entry.bytes += pkt_len
            return Verdict(True, entry.proxy_port, MATCH_L4_WILD)

    if is_fragment:
        return Verdict(False, 0, MATCH_FRAG_DROP)
    return Verdict(False, 0, MATCH_NONE)


def evaluate_batch_oracle(
    states: Sequence[PolicyMapState],
    ep_index: np.ndarray,
    identity: np.ndarray,
    dport: np.ndarray,
    proto: np.ndarray,
    direction: np.ndarray,
    is_fragment: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch of tuples against E map states; returns
    (allowed u8, proxy_port u16, match_kind u8) arrays."""
    b = len(ep_index)
    if is_fragment is None:
        is_fragment = np.zeros(b, dtype=bool)
    allowed = np.zeros(b, dtype=np.uint8)
    proxy = np.zeros(b, dtype=np.uint16)
    kind = np.zeros(b, dtype=np.uint8)
    for i in range(b):
        v = policy_can_access(
            states[int(ep_index[i])],
            int(identity[i]),
            int(dport[i]),
            int(proto[i]),
            int(direction[i]),
            bool(is_fragment[i]),
        )
        allowed[i] = 1 if v.allowed else 0
        proxy[i] = v.proxy_port
        kind[i] = v.match_kind
    return allowed, proxy, kind
