"""Sub-word hot lanes: pack/unpack helpers for nibble/byte/halfword
table planes.

The fused pipeline is memory-bound on ROW GATHERS: every probe moves
`lanes * 4` bytes per tuple whatever the entry fields actually need.
PR 6/7 shrank the rows by cutting entries per row (the pack-width
lever); this module cuts the BITS PER FIELD — verdict-deciding fields
whose semantic range fits a nibble/byte/halfword (CT state+flags,
ipcache identity indices, prefix-class lengths, verdict-cache probe
bits) are packed k-per-u32-lane on the host and unpacked INSIDE the
jit, exactly like the packed4 staging precedent
(engine/datapath.pack_flow_records4): host-visible semantics are
unchanged and bit-identity gated, only the gathered footprint shrinks.

The packing is positional and exact: `pack_lanes` / `unpack_lanes`
round-trip every value in [0, 2^width) at any supported width — the
property suite in tests/test_subword.py pins widths {4, 8, 16} over
their full ranges.  Widths must divide 32 so no field straddles a
lane.
"""

from __future__ import annotations

import numpy as np

SUPPORTED_WIDTHS = (1, 2, 4, 8, 16, 32)


def lanes_for(entries: int, width: int) -> int:
    """u32 lanes needed for `entries` fields of `width` bits."""
    if width not in SUPPORTED_WIDTHS:
        raise ValueError(f"unsupported sub-word width {width}")
    per = 32 // width
    return (entries + per - 1) // per


def pack_lanes(values: np.ndarray, width: int) -> np.ndarray:
    """Host half: pack the last axis of `values` (uints < 2^width)
    into u32 lanes, `32 // width` fields per lane, field i at bit
    `(i % per) * width` of lane `i // per`.  Trailing partial lanes
    are zero-padded (padding fields decode to 0)."""
    if width not in SUPPORTED_WIDTHS:
        raise ValueError(f"unsupported sub-word width {width}")
    v = np.asarray(values, dtype=np.uint64)
    if width < 32 and v.size and int(v.max()) >= (1 << width):
        raise ValueError(
            f"value {int(v.max())} exceeds the {width}-bit sub-word "
            f"field"
        )
    if width == 32:
        return v.astype(np.uint32)
    per = 32 // width
    e = v.shape[-1]
    n_lanes = lanes_for(e, width)
    pad = n_lanes * per - e
    if pad:
        v = np.concatenate(
            [v, np.zeros(v.shape[:-1] + (pad,), np.uint64)], axis=-1
        )
    v = v.reshape(v.shape[:-1] + (n_lanes, per))
    shifts = (np.arange(per, dtype=np.uint64) * width)
    return (v << shifts).sum(axis=-1).astype(np.uint32)


def unpack_lanes(words, width: int, entries: int, xp=None):
    """Device/host half: u32 lanes -> the original fields along the
    last axis ([..., entries]).  Traced-safe (xp=jnp inside a jit);
    exact inverse of pack_lanes for values < 2^width."""
    if xp is None:
        import jax.numpy as jnp

        xp = jnp
    if width == 32:
        return words[..., :entries]
    per = 32 // width
    lane = xp.arange(entries) // per
    shift = ((xp.arange(entries) % per) * width).astype(xp.uint32)
    mask = xp.uint32((1 << width) - 1)
    return (words[..., lane] >> shift) & mask


def unpack_lanes_np(words: np.ndarray, width: int, entries: int):
    """NumPy spelling of unpack_lanes (host-side round-trip checks
    and table decoders)."""
    return np.asarray(unpack_lanes(words, width, entries, xp=np))


def width_for_max(max_value: int, floor: int = 4) -> int:
    """Smallest supported width (>= floor) holding `max_value` —
    the "where semantics allow" decision, made from the REALIZED
    values at pack time, never assumed."""
    for w in SUPPORTED_WIDTHS:
        if w >= floor and max_value < (1 << w):
            return w
    return 32
