"""One datapath, one mesh: the FULL fused pipeline over partitioned
N+1 tables.

engine/datapath.py fuses prefilter + LB/DNAT + CT + ipcache + lattice
into one jit, but every leaf it gathers is REPLICATED per chip — a
mesh buys throughput, never capacity, and the CT/ipcache/LB planes
cap the universe at one chip's HBM exactly as the policy leaves did
before PR 7.  This module is the closing move: the same routed-gather
construction the partitioned/failover lattice evaluators use
(engine/sharded.py), applied to EVERY hashed bucket-row plane of the
pipeline under the declarative family rules of compiler/partition.py:

  * CT bucket rows, ipcache /32 bucket rows + hashed range-class
    rows, and the inline LB service rows shard along the same table
    axis as `l4_hash_rows` and join the N+1 replica placement
    (DATAPATH_REPLICA_LEAVES) — each shard holds its slice plus its
    left neighbour's backup copy;
  * inside shard_map, each tuple's bucket routes to its owning shard
    (the backup owner when the primary's chip is dead, exactly the
    alive-masked routing of make_failover_evaluator); the owner
    computes the probe's SMALL outputs locally — found bits, masked
    value sums, LB backend selection — and one integer psum per probe
    returns them to the batch shard (`ct_probe_row_parts` /
    `lb_slot_outputs` / `ipcache_bucket_parts` / `range_row_parts`
    are the owner-maskable halves the single-chip kernels now share);
  * stashes, the broadcast-fallback range arrays, prefilter and
    tunnel tables replicate and contribute OUTSIDE the psums (a
    replicated term summed across the table axis would inflate by
    tp);
  * the policy lattice is the shared `failover_lattice_probes` body —
    identical routing, counters and replica semantics to
    make_failover_evaluator, with idx/known derived from the routed
    ipcache lookup instead of id_direct.

The result is bit-identical to the single-device fused program (which
is itself gated against the composed host oracle in
tests/test_datapath.py) at every table-axis size and under any
survivor set that keeps one owner per slice alive — and per-chip HBM
for the CT/ipcache/LB planes drops toward replicated/N.

`DatapathStore` is the publication half: the augmented pytree lives
sharded on device, and a re-publish diffs each sharded plane's rows
against the previously published host snapshot and scatters ONLY the
changed rows (in augmented coordinates, so primary and backup copies
stay bit-identical through churn) — CT writeback churn, DNS-driven
ipcache upserts and backend flips all ride the delta path, bytes
proportional to the change.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cilium_tpu import tracing
from cilium_tpu.compiler import partition
from cilium_tpu.engine.datapath import (
    DatapathTables,
    DatapathVerdicts,
    FlowBatch,
    flow_batch_from_packed4,
)
from cilium_tpu.engine.publish import next_pow2
from cilium_tpu.engine.sharded import (
    failover_counts,
    failover_lattice_probes,
    fold_l3_aug,
    shard_map,
)
from cilium_tpu.maps.policymap import INGRESS

__all__ = [
    "DatapathStore",
    "make_failover_datapath_evaluator",
    "make_failover_datapath_pair_evaluator",
]


def _routed_rows(rows_l, bucket, ntp, my_col, alive_row, sharded,
                 n_global):
    """One routed bucket-row gather with N+1 replica fallback — the
    shared routing step of every hashed plane: the owning shard
    (backup owner when the primary's chip is dead) gathers its local
    row, everyone else gathers a clipped dummy and masks.  Returns
    (row [B, lanes], owns bool [B], served_from_backup bool [B])."""
    if not sharded:
        ones = jnp.ones(bucket.shape, bool)
        return rows_l[bucket], ones, jnp.zeros(bucket.shape, bool)
    n = n_global // ntp
    p = bucket // n
    ap = alive_row[p]
    owner = jnp.where(
        ap, p, (p + partition.REPLICA_BACKUP_OFFSET) % ntp
    )
    owns = owner == my_col
    bl = (bucket - p * n) + jnp.where(ap, 0, n)
    bl = jnp.clip(bl, 0, 2 * n - 1)
    return rows_l[bl], owns, owns & ~ap


def _geometry(dtables: DatapathTables) -> tuple:
    """Static geometry signature the evaluator closures route by —
    any change (hash-plane regrow, stash trim crossing a pow2 class,
    range-class schedule change, layout form flip) must rebuild the
    evaluator AND full-upload the store."""
    from cilium_tpu.ipcache.lpm import IPCacheDevice
    from cilium_tpu.lb.device import LBInline

    ipc = dtables.ipcache
    lb = dtables.lb
    return (
        tuple(np.asarray(dtables.ct.buckets).shape),
        type(ipc).__name__,
        tuple(np.asarray(ipc.buckets).shape)
        if isinstance(ipc, IPCacheDevice) else (),
        None
        if not isinstance(ipc, IPCacheDevice)
        or ipc.range_rows is None
        else tuple(np.asarray(ipc.range_rows).shape),
        tuple(ipc.range_class_plens)
        if isinstance(ipc, IPCacheDevice) else (),
        bool(getattr(ipc, "l3_planes", False)),
        int(getattr(ipc, "world_plus1", 0)),
        type(lb).__name__,
        tuple(np.asarray(lb.rows).shape)
        if isinstance(lb, LBInline)
        else tuple(np.asarray(lb.buckets).shape),
        type(dtables.prefilter).__name__,
        tuple(np.asarray(dtables.policy.l4_hash_rows).shape),
        tuple(np.asarray(dtables.policy.l3_allow_bits).shape),
        # sub-word layout markers: a width flip at an unchanged
        # shape is still a different program AND a different
        # resident encoding — must rebuild + full upload
        int(getattr(dtables.ct, "entry_words", 5)),
        (
            int(getattr(ipc, "bucket_entries", 0)),
            int(getattr(ipc, "value_width", 32)),
            int(getattr(ipc, "l3_width", 32)),
            tuple(getattr(ipc, "range_widths", ()) or ()),
        )
        if isinstance(ipc, IPCacheDevice) else (),
        int(np.asarray(dtables.policy.l4_hash_stash).shape[-1]),
        int(np.asarray(dtables.policy.l4_wild_stash).shape[-1]),
    )


def _check_fused_world(dtables: DatapathTables) -> None:
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    if dtables.policy.l4_hash_rows is None:
        raise ValueError(
            "fused mesh datapath requires the hashed L4 entry tables"
        )
    ipc = dtables.ipcache
    if not isinstance(ipc, IPCacheDevice) or not ipc.values_are_idx:
        raise ValueError(
            "fused mesh datapath requires an idx-form IPCacheDevice "
            "(specialize_ipcache_to_idx); the DIR-24-8 fallback is "
            "host-compiled for range-heavy worlds only"
        )


def _fused_geom(dtables: DatapathTables, ntp: int, table_axis: str):
    """Closure constants of the fused kernel: per-plane global row
    counts + sharded flags (from the divisibility-checked family
    rules) and the lattice geometry of the failover evaluator."""
    from cilium_tpu.lb.device import LBInline

    rep_axes = partition.datapath_replica_axes(
        dtables, ntp, table_axis
    )
    pol = dtables.policy
    rows_sharded = "l4_hash_rows" in partition.replica_axes(
        pol, ntp, table_axis
    )
    l3_sharded = "l3_allow_bits" in partition.replica_axes(
        pol, ntp, table_axis
    )
    ipc = dtables.ipcache
    return {
        "ntp": ntp,
        "ct_sharded": ("ct", "buckets") in rep_axes,
        "ct_ew": int(getattr(dtables.ct, "entry_words", 5)),
        "range_widths": tuple(
            getattr(ipc, "range_widths", ()) or ()
        ),
        "n_ct": int(np.asarray(dtables.ct.buckets).shape[0]),
        "lb_inline": isinstance(dtables.lb, LBInline),
        "lb_sharded": ("lb", "rows") in rep_axes,
        "n_lb": int(
            np.asarray(dtables.lb.rows).shape[0]
            if isinstance(dtables.lb, LBInline)
            else 0
        ),
        "ipc_sharded": ("ipcache", "buckets") in rep_axes,
        "n_ipc": int(np.asarray(ipc.buckets).shape[0]),
        "range_sharded": ("ipcache", "range_rows") in rep_axes,
        "n_range": (
            0
            if ipc.range_rows is None
            else int(np.asarray(ipc.range_rows).shape[0])
        ),
        "range_planes": 5 if ipc.l3_planes else 3,
        "world_plus1": int(ipc.world_plus1),
        "rows_sharded": rows_sharded,
        "l3_sharded": l3_sharded,
        "n_rows_global": int(pol.l4_hash_rows.shape[0]),
        "n_row_shard": (
            int(pol.l4_hash_rows.shape[0]) // ntp
            if rows_sharded else 0
        ),
        "w_global": int(pol.l3_allow_bits.shape[-1]),
        "wn": (
            int(pol.l3_allow_bits.shape[-1]) // ntp
            if l3_sharded else 0
        ),
        "n_ids": int(pol.l3_allow_bits.shape[-1]) * 32,
    }


def _fused_core(
    dt_l: DatapathTables,
    flows_l: FlowBatch,
    alive_row,
    my_col,
    valid_l,
    g: dict,
    table_axis: str,
    batch_axis: str,
    static_direction=None,
    collect_telemetry: bool = False,
):
    """The routed fused pipeline body (one direction program when
    `static_direction` is set — the per-direction specialization of
    engine/datapath.py carried onto the mesh).  Stage order and
    combine semantics mirror _datapath_core exactly; every hashed
    gather is owner-routed with replica fallback and returned
    through one small integer psum."""
    from cilium_tpu.ct.device import (
        _normalize_device,
        ct_probe_combine,
        ct_probe_keys,
        ct_probe_row_parts,
        ct_probe_stash_parts,
    )
    from cilium_tpu.ct.table import (
        CT_ESTABLISHED,
        CT_NEW,
        CT_RELATED,
        CT_REPLY,
        CT_SERVICE,
    )
    from cilium_tpu.engine.hashtable import fnv1a_device
    from cilium_tpu.engine.verdict import _combine, telemetry_masks
    from cilium_tpu.ipcache.lpm import (
        UNKNOWN_IDX,
        ipcache_bucket_parts,
        ipcache_stash_parts,
        range_class_key,
        range_row_parts,
        range_take_fold,
    )
    from cilium_tpu.lb.device import (
        flow_hash,
        lb_inline_slot,
        lb_inline_stash_slot,
        lb_service_key,
        lb_slot_outputs,
    )
    from cilium_tpu.prefilter import prefilter_drop

    ntp = g["ntp"]

    def psum_i(x):
        return jax.lax.psum(x.astype(jnp.int32), table_axis) > 0

    def psum_u(x):
        return jax.lax.psum(x, table_axis)

    if static_direction is None:
        ingress = flows_l.direction == INGRESS
    else:
        ingress = jnp.full(
            flows_l.direction.shape, static_direction == INGRESS
        )
    saddr = flows_l.saddr.astype(jnp.uint32)
    daddr = flows_l.daddr.astype(jnp.uint32)
    backup = jnp.zeros(saddr.shape, bool)

    # -- 1. XDP prefilter (replicated broadcast) ------------------------
    pre_drop = prefilter_drop(dt_l.prefilter, flows_l.saddr)

    # -- 2+3. routed CT row gather serves both probes -------------------
    lo_a, hi_a, lo_p, hi_p, _sw = _normalize_device(
        flows_l.daddr, flows_l.saddr, flows_l.dport, flows_l.sport
    )
    proto_u = flows_l.proto.astype(jnp.uint32) & 0xFF
    hct = fnv1a_device(
        jnp.stack([lo_a, hi_a, (lo_p << 16) | hi_p, proto_u], axis=1)
    )
    ct_bucket = (hct & jnp.uint32(g["n_ct"] - 1)).astype(jnp.int32)
    ct_rows, owns_ct, rep_ct = _routed_rows(
        dt_l.ct.buckets, ct_bucket, ntp, my_col, alive_row,
        g["ct_sharded"], g["n_ct"],
    )
    backup = backup | rep_ct

    def ct_probe(p_daddr, p_dport, direction_v):
        """One routed CT probe against the fetched rows: owner-local
        row parts psum'd, replicated stash parts added after."""
        ka, kb, kw, w3f, w3r, rel = ct_probe_keys(
            p_daddr, flows_l.saddr, p_dport, flows_l.sport,
            flows_l.proto, direction_v,
        )
        rf, rr, rfv, rrv = ct_probe_row_parts(
            ct_rows, ka, kb, kw, w3f, w3r, owns=owns_ct,
            entry_words=g["ct_ew"],
        )
        if g["ct_sharded"]:
            rf, rr = psum_i(rf), psum_i(rr)
            rfv, rrv = psum_u(rfv), psum_u(rrv)
        sf, sr, sfv, srv = ct_probe_stash_parts(
            dt_l.ct, ka, kb, kw, w3f, w3r
        )
        return ct_probe_combine(
            rf | sf, rr | sr, rfv + sfv, rrv + srv, rel
        )

    if static_direction == INGRESS:
        zero = jnp.zeros(flows_l.dport.shape, jnp.int32)
        eff_daddr = daddr
        eff_dport = flows_l.dport
        rev_nat = zero
        lb_slave = zero
    else:
        svc_dir = jnp.full_like(flows_l.direction, CT_SERVICE)
        _, _, svc_slave = ct_probe(
            flows_l.daddr, flows_l.dport, svc_dir
        )
        # routed LB service resolution (inline rows): the owner
        # computes the backend selection from its slot and the
        # five small output columns psum back
        vip, w1lb = lb_service_key(
            flows_l.daddr, flows_l.dport, flows_l.proto
        )
        fh = flow_hash(
            flows_l.saddr, flows_l.daddr, flows_l.sport,
            flows_l.dport, flows_l.proto,
        )
        if g["lb_inline"]:
            hlb = fnv1a_device(jnp.stack([vip, w1lb], axis=1))
            lb_bucket = (
                hlb & jnp.uint32(g["n_lb"] - 1)
            ).astype(jnp.int32)
            lb_rows, owns_lb, rep_lb = _routed_rows(
                dt_l.lb.rows, lb_bucket, ntp, my_col, alive_row,
                g["lb_sharded"], g["n_lb"],
            )
            backup = backup | rep_lb
            slot_r, row_found = lb_inline_slot(
                lb_rows, vip, w1lb, owns=owns_lb
            )
            f_r, sl_r, da_r, dp_r, rn_r = lb_slot_outputs(
                slot_r, row_found, fh, ct_slave=svc_slave
            )
            if g["lb_sharded"]:
                f_r = psum_i(f_r)
                sl_r = jax.lax.psum(sl_r, table_axis)
                da_r = psum_u(da_r)
                dp_r = jax.lax.psum(dp_r, table_axis)
                rn_r = jax.lax.psum(rn_r, table_axis)
            slot_s, s_found = lb_inline_stash_slot(
                dt_l.lb, vip, w1lb
            )
            f_s, sl_s, da_s, dp_s, rn_s = lb_slot_outputs(
                slot_s, s_found, fh, ct_slave=svc_slave
            )
            svc_found = f_r | f_s
            slave = sl_r + sl_s
            lb_daddr = da_r + da_s
            lb_dport = dp_r + dp_s
            lb_rev = rn_r + rn_s
        else:
            # classic layout: replicated wholesale (identical on
            # every shard), so the single-chip select is exact
            from cilium_tpu.lb.device import lb_select_batch

            svc_found, slave, lb_daddr, lb_dport, lb_rev = (
                lb_select_batch(
                    dt_l.lb, flows_l.saddr, flows_l.daddr,
                    flows_l.sport, flows_l.dport, flows_l.proto,
                    ct_slave=svc_slave,
                )
            )
        do_lb = (~ingress) & svc_found
        eff_daddr = jnp.where(do_lb, lb_daddr, daddr)
        eff_dport = jnp.where(do_lb, lb_dport, flows_l.dport)
        rev_nat = jnp.where(do_lb, lb_rev, 0)
        lb_slave = jnp.where(do_lb, slave, 0)

    ct_res, _ct_rev, _ = ct_probe(
        eff_daddr, eff_dport, flows_l.direction
    )

    # -- 4. routed ipcache (idx-form) -----------------------------------
    ipc = dt_l.ipcache
    sec_ip = jnp.where(ingress, saddr, eff_daddr)
    hip = fnv1a_device(sec_ip[:, None])
    ip_bucket = (hip & jnp.uint32(g["n_ipc"] - 1)).astype(jnp.int32)
    ip_rows, owns_ip, rep_ip = _routed_rows(
        ipc.buckets, ip_bucket, ntp, my_col, alive_row,
        g["ipc_sharded"], g["n_ipc"],
    )
    backup = backup | rep_ip
    bf, bv, _bl3 = ipcache_bucket_parts(
        ipc, ip_rows, sec_ip, ingress=ingress, owns=owns_ip
    )
    if g["ipc_sharded"]:
        bf, bv = psum_i(bf), psum_u(bv)
    sf2, sv2, _sl3 = ipcache_stash_parts(
        ipc, sec_ip, ingress=ingress
    )
    exact_found = bf | sf2
    exact_val = bv + sv2
    if ipc.range_rows is not None:
        classes = []
        for sp in ipc.range_class_plens:  # static, longest first
            w0c, hc = range_class_key(sec_ip, sp)
            r_bucket = (
                hc & jnp.uint32(g["n_range"] - 1)
            ).astype(jnp.int32)
            r_row, owns_r, rep_r = _routed_rows(
                ipc.range_rows, r_bucket, ntp, my_col, alive_row,
                g["range_sharded"], g["n_range"],
            )
            backup = backup | rep_r
            hitc, rv, _li, _lo = range_row_parts(
                r_row, w0c, sp, g["range_planes"], owns=owns_r,
                widths=g["range_widths"],
            )
            if g["range_sharded"]:
                hitc, rv = psum_i(hitc), psum_u(rv)
            zero_u = jnp.zeros(sec_ip.shape, jnp.uint32)
            classes.append((hitc, rv, zero_u, zero_u))
        range_found, range_val, _, _ = range_take_fold(
            classes, sec_ip.shape
        )
    else:
        # broadcast fallback over the replicated range arrays —
        # same selection as ipcache_lookup_fused's fallback branch
        match = (
            sec_ip[:, None] & jnp.asarray(ipc.range_mask)[None, :]
        ) == jnp.asarray(ipc.range_base)[None, :]
        plen = jnp.asarray(ipc.range_plen)
        best = jnp.max(jnp.where(match, plen[None, :], 0), axis=1)
        range_sel = match & (plen[None, :] == best[:, None])
        range_found = best > 0
        range_val = jnp.sum(
            jnp.where(
                range_sel, jnp.asarray(ipc.range_value)[None, :], 0
            ),
            axis=1, dtype=jnp.uint32,
        )
    looked = jnp.where(
        exact_found, exact_val,
        jnp.where(range_found, range_val, 0),
    )
    n_pad = dt_l.policy.id_table.shape[0]
    miss = looked == 0
    ipc_miss = miss
    vp = jnp.where(miss, jnp.uint32(g["world_plus1"]), looked)
    known = (vp != 0) & (vp != jnp.uint32(UNKNOWN_IDX))
    idx = jnp.where(known, vp - 1, jnp.uint32(n_pad - 1)).astype(
        jnp.int32
    )
    sec_id = dt_l.policy.id_table[idx]

    # -- 5. the routed replica-aware policy lattice ---------------------
    lat_dport = jnp.clip(eff_dport, 0, 65535).astype(jnp.int32)
    lat_proto = jnp.clip(flows_l.proto, 0, 255).astype(jnp.int32)
    lat = failover_lattice_probes(
        dt_l.policy, flows_l.ep_index, flows_l.direction, lat_dport,
        lat_proto, idx, known, alive_row, my_col, ntp,
        g["rows_sharded"], g["l3_sharded"], g["n_rows_global"],
        g["n_row_shard"], g["wn"], table_axis,
    )
    v = _combine(
        lat["probe1"], lat["probe2"], lat["probe3"], lat["proxy"],
        flows_l.is_fragment,
    )
    backup = backup | lat["replica"]
    l4_counts, l3_counts = failover_counts(
        dt_l.policy, flows_l.ep_index, flows_l.direction,
        v.match_kind, lat["j"], idx, lat["p2_local"], valid_l,
        g["l3_sharded"], g["wn"], lat["wp"], lat["apw"], g["n_ids"],
        batch_axis,
    )

    # -- 6. combine (bpf_lxc.c:962-985) ---------------------------------
    pol_allow = v.allowed.astype(bool)
    pass_ct = (ct_res == CT_REPLY) | (ct_res == CT_RELATED)
    allowed = (~pre_drop) & (pass_ct | pol_allow)
    ct_delete = (
        (ct_res == CT_ESTABLISHED) & ~pol_allow & ~pass_ct & ~pre_drop
    )
    ct_create = (ct_res == CT_NEW) & allowed
    proxy = jnp.where(
        pol_allow
        & ((ct_res == CT_NEW) | (ct_res == CT_ESTABLISHED))
        & allowed,
        v.proxy_port,
        0,
    )

    # -- 7. overlay forwarding (replicated tunnel tables) ---------------
    if dt_l.tunnel is not None and static_direction != INGRESS:
        from cilium_tpu.tunnel import tunnel_select

        tunnel_ep = jnp.where(
            allowed & ~ingress,
            tunnel_select(dt_l.tunnel, eff_daddr),
            jnp.uint32(0),
        )
    else:
        tunnel_ep = jnp.zeros(eff_daddr.shape, jnp.uint32)

    out = DatapathVerdicts(
        allowed=allowed.astype(jnp.uint8),
        proxy_port=proxy,
        match_kind=v.match_kind,
        ct_result=ct_res,
        pre_dropped=pre_drop,
        sec_id=sec_id,
        final_daddr=eff_daddr,
        final_dport=eff_dport,
        rev_nat=rev_nat,
        lb_slave=lb_slave,
        ct_create=ct_create,
        ct_delete=ct_delete,
        tunnel_endpoint=tunnel_ep,
        l4_slot=lat["j"],
        ipcache_miss=ipc_miss,
    )
    replica_hits = jax.lax.psum(
        jax.lax.psum(
            jnp.sum((backup & valid_l).astype(jnp.uint32)),
            batch_axis,
        ),
        table_axis,
    )
    trow = None
    if collect_telemetry:
        masks = telemetry_masks(
            pre_drop, ct_res, v.match_kind, allowed, ct_delete,
            proxy, lb_slave, ipc_miss,
        )
        ing_v = ingress & valid_l
        row_in = jnp.stack(
            [jnp.sum(m & ing_v, dtype=jnp.uint32) for m in masks]
        )
        col_total = jnp.stack(
            [jnp.sum(m & valid_l, dtype=jnp.uint32) for m in masks]
        )
        trow = jnp.stack([row_in, col_total - row_in])
    return out, l4_counts, l3_counts, replica_hits, trow


def _verdict_out_specs(batch_axis: str):
    s = P(batch_axis)
    return DatapathVerdicts(
        allowed=s, proxy_port=s, match_kind=s, ct_result=s,
        pre_dropped=s, sec_id=s, final_daddr=s, final_dport=s,
        rev_nat=s, lb_slave=s, ct_create=s, ct_delete=s,
        tunnel_endpoint=s, l4_slot=s, ipcache_miss=s,
    )


def _flow_specs(batch_axis: str) -> FlowBatch:
    s = P(batch_axis)
    return FlowBatch(
        ep_index=s, saddr=s, daddr=s, sport=s, dport=s, proto=s,
        direction=s, is_fragment=s,
    )


def make_failover_datapath_evaluator(
    mesh: Mesh,
    dtables: DatapathTables,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """The fused failover datapath program: the FULL pipeline over
    the N+1 AUGMENTED DatapathTables (replicate_datapath_leaves) with
    the same two routing inputs as make_failover_evaluator —
    `alive` bool [dp, tp] chip health and `valid` bool [B] real-tuple
    mask from the router's batch re-split.

    Returns run(dtables_aug, flows, alive, valid) ->
    (DatapathVerdicts [batch-sharded columns], l4_counts [E, 2, Kg],
    l3_counts [E, 2, N] GLOBAL (fold_l3_aug applied host-side),
    replica_hits u32 scalar [, per-chip telemetry [dp, 2, T]]) —
    bit-identical on the valid mask to the single-device fused
    program (engine/datapath.datapath_step*) and the composed host
    oracle, whatever the survivor set, as long as one owner of every
    slice is alive."""
    _check_fused_world(dtables)
    ntp = int(mesh.shape[table_axis])
    g = _fused_geom(dtables, ntp, table_axis)
    t_specs = partition.datapath_partition_specs(
        dtables, ntp, table_axis
    )
    f_specs = _flow_specs(batch_axis)
    l3_spec = (
        P(None, None, table_axis) if g["l3_sharded"] else P()
    )
    out_specs = (_verdict_out_specs(batch_axis), P(), l3_spec, P())
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, f_specs, P(), P(batch_axis)),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(dt_l, flows_l, alive_l, valid_l):
        alive_row = alive_l[jax.lax.axis_index(batch_axis)]
        my_col = jax.lax.axis_index(table_axis)
        out, l4c, l3c, hits, trow = _fused_core(
            dt_l, flows_l, alive_row, my_col, valid_l, g,
            table_axis, batch_axis,
            collect_telemetry=collect_telemetry,
        )
        base = (out, l4c, l3c, hits)
        return base + ((trow[None],) if collect_telemetry else ())

    sh = lambda s: NamedSharding(mesh, s)  # noqa: E731
    in_shardings = (
        jax.tree.map(sh, t_specs, is_leaf=lambda x: isinstance(x, P)),
        jax.tree.map(sh, f_specs, is_leaf=lambda x: isinstance(x, P)),
        sh(P()),
        sh(P(batch_axis)),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)
    built = _geometry(dtables)

    def run(dtables_aug, flows: FlowBatch, alive, valid):
        got_rows = int(
            np.asarray(dtables_aug.policy.l4_hash_rows).shape[0]
        )
        want_rows = g["n_rows_global"] * (
            2 if g["rows_sharded"] else 1
        )
        if got_rows != want_rows:
            raise ValueError(
                "fused datapath evaluator was built for another "
                f"table geometry (hash rows {want_rows} != "
                f"{got_rows}); rebuild with "
                "make_failover_datapath_evaluator"
            )
        out = jitted(dtables_aug, flows, alive, valid)
        if g["l3_sharded"]:
            out = (out[0], out[1], fold_l3_aug(out[2], ntp)) + tuple(
                out[3:]
            )
        return out

    run.geometry = built
    run.geom = g
    return run


def make_failover_datapath_pair_evaluator(
    mesh: Mesh,
    dtables: DatapathTables,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = True,
):
    """The packed4 PAIR shape of the fused failover datapath: both
    direction-specialized half-batch programs in ONE dispatch over a
    [2, 4, B] staged array (row 0 = ingress half, row 1 = egress
    half — the engine/datapath.py headline staging format carried
    onto the mesh), with the counters and telemetry riding the same
    dispatch.  The ingress program compiles with no LB/service-CT
    stages at all, exactly like datapath_step_accum_ingress.

    Returns run(dtables_aug, pair, alive, valid [2, B]) ->
    (out_ingress, out_egress, l4_counts, l3_counts (global),
    replica_hits [, telemetry rows [dp, 2, T] folded over both
    halves])."""
    from cilium_tpu.maps.policymap import EGRESS

    _check_fused_world(dtables)
    ntp = int(mesh.shape[table_axis])
    g = _fused_geom(dtables, ntp, table_axis)
    t_specs = partition.datapath_partition_specs(
        dtables, ntp, table_axis
    )
    l3_spec = (
        P(None, None, table_axis) if g["l3_sharded"] else P()
    )
    v_specs = _verdict_out_specs(batch_axis)
    out_specs = (v_specs, v_specs, P(), l3_spec, P())
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)
    pair_spec = P(None, None, batch_axis)
    valid_spec = P(None, batch_axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, pair_spec, P(), valid_spec),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(dt_l, pair_l, alive_l, valid_l):
        alive_row = alive_l[jax.lax.axis_index(batch_axis)]
        my_col = jax.lax.axis_index(table_axis)
        out_i, l4_i, l3_i, hits_i, trow_i = _fused_core(
            dt_l, flow_batch_from_packed4(pair_l[0]), alive_row,
            my_col, valid_l[0], g, table_axis, batch_axis,
            static_direction=INGRESS,
            collect_telemetry=collect_telemetry,
        )
        out_e, l4_e, l3_e, hits_e, trow_e = _fused_core(
            dt_l, flow_batch_from_packed4(pair_l[1]), alive_row,
            my_col, valid_l[1], g, table_axis, batch_axis,
            static_direction=EGRESS,
            collect_telemetry=collect_telemetry,
        )
        base = (
            out_i, out_e, l4_i + l4_e, l3_i + l3_e, hits_i + hits_e,
        )
        if collect_telemetry:
            base = base + ((trow_i + trow_e)[None],)
        return base

    sh = lambda s: NamedSharding(mesh, s)  # noqa: E731
    in_shardings = (
        jax.tree.map(sh, t_specs, is_leaf=lambda x: isinstance(x, P)),
        sh(pair_spec),
        sh(P()),
        sh(valid_spec),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)

    def run(dtables_aug, pair, alive, valid):
        out = jitted(dtables_aug, pair, alive, valid)
        if g["l3_sharded"]:
            out = out[:3] + (fold_l3_aug(out[3], ntp),) + tuple(
                out[4:]
            )
        return out

    run.geom = g
    return run


# ---------------------------------------------------------------------------
# Publication: the datapath epoch with generic row-diff delta scatter
# ---------------------------------------------------------------------------


@dataclass
class DatapathPublishStats:
    epoch: int
    mode: str  # "full" | "delta"
    bytes_h2d: int
    seconds: float
    scattered_rows: int = 0
    replaced_leaves: int = 0


class DatapathStore:
    """TWO device-resident fused-datapath epochs over the
    partitioned N+1 layout, ping-ponging exactly like the policy
    plane's DeviceTableStore: a publish lands in the SPARE slot (the
    donated row scatter patches only buffers no in-flight dispatch
    can hold) while batches dispatched against the CURRENT epoch
    finish on it untouched — a publish concurrent with a fused
    serving-plane dispatch is safe by construction.

    Publication is ROW-DIFF delta: each sharded plane's new
    augmented host rows are diffed against the SPARE slot's retained
    snapshot and only the CHANGED rows scatter (XLA routes each row
    to its owning chip, in augmented coordinates so primary and
    backup copies stay bit-identical).  Replicated leaves re-place
    wholesale only when they changed.  A geometry change (hash-plane
    regrow, layout form, idx-form world change) forces a full upload
    — and the caller must rebuild the fused evaluator, which closes
    over the same geometry (the partition digest guards
    cross-partitioning publishes the same way the policy store's
    layout stamp does).

    Scaling note: the diff itself is a host-side compare of every
    augmented leaf — H2D bytes are proportional to the CHANGE, but
    publish CPU is O(world).  Scoping the diff through per-subsystem
    change records (the compiler-delta pattern) is the follow-on for
    multi-million-identity worlds; at today's scales the vectorized
    compare is microseconds per MB."""

    def __init__(self, mesh: Mesh, table_axis: str = "table") -> None:
        self.mesh = mesh
        self.table_axis = table_axis
        self.ntp = int(mesh.shape[table_axis])
        self.partition_digest = partition.datapath_partition_digest(
            table_axis, ntp=self.ntp
        )
        self._lock = threading.Lock()
        # each slot: {"dev": device pytree, "host": augmented host
        # pytree (the diff base + repair value source), "geom":
        # geometry signature, "digest": partition digest,
        # "epoch": publish counter at install}
        self._slots = [None, None]
        self._cur = 0
        self.epoch = 0
        self._scatter_cache: Dict[tuple, object] = {}
        self._shardings = None
        # per-epoch change records (publish(changes=...)): epoch ->
        # {family: {leaf: row-idx array | True}} or None (= no
        # record, that publish was full-diffed).  A scoped publish
        # unions the records since the SPARE slot's epoch — the
        # ping-pong means the spare is two publishes old.
        self._change_log: Dict[int, object] = {}
        # open relayout window (engine/reshard.py): the spare slot
        # holds the migration target epoch under the NEW ntp/digest;
        # publish() patches the LIVE slot (non-donated) until the
        # cutover rebinds mesh/ntp/digest to the target
        self._relayout: Optional[Dict] = None

    # -- internals -----------------------------------------------------------

    def _scatter_fn(self, key: tuple, axis: int,
                    donate: bool = True):
        key = key + (bool(donate),)
        fn = self._scatter_cache.get(key)
        if fn is None:
            def apply(leaf, idx, rows):
                index = (slice(None),) * axis + (idx,)
                return leaf.at[index].set(rows)

            fn = tracing.track_jit(
                jax.jit(
                    apply,
                    donate_argnums=(0,) if donate else (),
                ),
                "datapath.scatter" if donate
                else "datapath.scatter_live",
            )
            self._scatter_cache[key] = fn
        return fn

    @staticmethod
    def _tree_nbytes(tree) -> int:
        return sum(
            int(np.asarray(l).nbytes) for l in jax.tree.leaves(tree)
        )

    def _full_place(self, aug: DatapathTables):
        self._shardings = partition.datapath_table_shardings(
            self.mesh, aug, self.table_axis
        )
        dev = jax.tree.map(
            lambda leaf, s: jax.device_put(np.asarray(leaf), s),
            aug, self._shardings,
        )
        jax.block_until_ready(dev)
        return dev, self._tree_nbytes(aug)

    # -- API -----------------------------------------------------------------

    def publish(
        self, dtables: DatapathTables, changes=None
    ) -> Tuple[DatapathTables, DatapathPublishStats]:
        """Install `dtables` (host, UN-augmented) as the serving
        datapath epoch — into the SPARE slot (in-flight batches
        finish on the current epoch untouched), then flip.
        Steady-state churn (CT writeback, ipcache upserts, LB
        backend flips, policy deltas) rides the row-diff scatter
        against the spare's retained snapshot; geometry changes
        full-upload.

        `changes` is an optional per-subsystem CHANGE RECORD —
        {family: {leaf: sharded-row index array | True}} — the
        compiler-delta pattern applied to the fused plane: with a
        record the publish diffs ONLY the named rows (publish CPU is
        O(change), not O(world); no re-augmentation of unchanged
        leaves), shipping exactly the rows that really moved.  The
        caller WARRANTS every unlisted leaf unchanged since the
        previous publish (the churn gate proves resident equality).
        The record is logged per epoch so the ping-pong unions the
        right set against the two-publishes-old spare; any
        intervening record-less publish falls back to the full
        row-diff, as does a geometry/digest change."""
        _check_fused_world(dtables)
        with self._lock, tracing.tracer.span(
            "datapath.publish", site="engine.datapath_mesh"
        ) as sp:
            t0 = time.perf_counter()
            geom = _geometry(dtables)
            self.epoch += 1
            self._change_log[self.epoch] = changes
            for e in list(self._change_log):
                if e <= self.epoch - 8:
                    del self._change_log[e]
            if (
                self._relayout is not None
                and not self._relayout.get("broken")
            ):
                # the spare slot is the staged reshard target: churn
                # patches the LIVE slot instead (non-donated — fused
                # dispatches may hold the live pytree), and anything
                # the delta path cannot absorb breaks the window so
                # the migration plan restarts as a full upload into
                # the target layout
                dev, stats = self._publish_relayout_locked(
                    dtables, geom, sp
                )
                stats.seconds = time.perf_counter() - t0
                return dev, stats
            spare_i = self._cur ^ 1
            spare = self._slots[spare_i]
            union = (
                None if spare is None
                else self._union_changes(spare.get("epoch", 0))
            )
            if (
                spare is None
                or geom != spare["geom"]
                or spare["digest"] != self.partition_digest
            ):
                aug = partition.replicate_datapath_leaves(
                    dtables, self.ntp, self.table_axis
                )
                dev, nbytes = self._full_place(aug)
                stats = DatapathPublishStats(
                    epoch=self.epoch, mode="full",
                    bytes_h2d=nbytes, seconds=0.0,
                )
                slot = {
                    "dev": dev, "host": aug, "geom": geom,
                    "digest": self.partition_digest,
                    "epoch": self.epoch,
                    "mesh": self.mesh, "ntp": self.ntp,
                }
            elif union is not None:
                dev, stats = self._publish_scoped(
                    dtables, spare, union
                )
                slot = dict(
                    spare, dev=dev, geom=geom, epoch=self.epoch
                )
            else:
                aug = partition.replicate_datapath_leaves(
                    dtables, self.ntp, self.table_axis
                )
                dev, stats = self._publish_delta(aug, spare)
                slot = {
                    "dev": dev, "host": aug, "geom": geom,
                    "digest": self.partition_digest,
                    "epoch": self.epoch,
                    "mesh": self.mesh, "ntp": self.ntp,
                }
            self._slots[spare_i] = slot
            self._cur = spare_i
            stats.seconds = time.perf_counter() - t0
            sp.attrs.update(
                mode=stats.mode, epoch=stats.epoch,
                bytes_h2d=stats.bytes_h2d,
                scattered_rows=stats.scattered_rows,
            )
            return dev, stats

    def _publish_relayout_locked(self, dtables, geom, sp):
        """Publish while a relayout window is open (caller holds the
        lock): the live slot absorbs the churn through the row-diff
        scatter WITHOUT donation (in-flight fused dispatches keep
        their buffers — the zero-drain seam); a geometry or digest
        change full-uploads into the live slot and marks the window
        broken (the plan's deterministic restart trigger)."""
        live_i = self._cur
        live = self._slots[live_i]
        if (
            live is None
            or geom != live["geom"]
            or live["digest"] != self.partition_digest
        ):
            aug = partition.replicate_datapath_leaves(
                dtables, self.ntp, self.table_axis
            )
            dev, nbytes = self._full_place(aug)
            stats = DatapathPublishStats(
                epoch=self.epoch, mode="full",
                bytes_h2d=nbytes, seconds=0.0,
            )
            slot = {
                "dev": dev, "host": aug, "geom": geom,
                "digest": self.partition_digest,
                "epoch": self.epoch,
                "mesh": self.mesh, "ntp": self.ntp,
            }
            self._relayout["broken"] = True
            sp.attrs["relayout_broken"] = True
        else:
            aug = partition.replicate_datapath_leaves(
                dtables, self.ntp, self.table_axis
            )
            dev, stats = self._publish_delta(
                aug, live, donate=False
            )
            slot = {
                "dev": dev, "host": aug, "geom": geom,
                "digest": self.partition_digest,
                "epoch": self.epoch,
                "mesh": self.mesh, "ntp": self.ntp,
            }
        self._slots[live_i] = slot
        sp.attrs.update(
            mode=stats.mode, epoch=stats.epoch,
            bytes_h2d=stats.bytes_h2d,
            scattered_rows=stats.scattered_rows, relayout=True,
        )
        return dev, stats

    def _union_changes(self, spare_epoch: int):
        """Union of the change records for every publish since the
        spare slot's epoch, or None when any of them is missing
        (record-less publish → the caller made no warranty and the
        full row-diff must run)."""
        union: Dict[str, Dict[str, object]] = {}
        for e in range(spare_epoch + 1, self.epoch + 1):
            rec = self._change_log.get(e)
            if rec is None:
                return None
            for fam, leafmap in rec.items():
                dst = union.setdefault(fam, {})
                for leaf, idx in leafmap.items():
                    prev = dst.get(leaf)
                    if idx is True or prev is True:
                        dst[leaf] = True
                    elif prev is None:
                        dst[leaf] = np.asarray(idx, np.int64)
                    else:
                        dst[leaf] = np.concatenate(
                            [prev, np.asarray(idx, np.int64)]
                        )
        return union

    def _publish_scoped(
        self, dtables: DatapathTables, spare: dict, changes
    ):
        """The O(change) publish: compare/scatter ONLY the rows the
        change records name, against (and into) the spare slot's
        retained augmented snapshot — no re-augmentation, no
        whole-world compare.  Sharded rows land at both their
        primary and backup augmented positions; `True` records
        re-place the whole leaf."""
        dev = spare["dev"]
        aug_host = spare["host"]
        rep_axes = partition.datapath_all_replica_axes(
            aug_host, self.ntp, self.table_axis
        )
        n_rows = 0
        bytes_h2d = 0
        replaced = 0
        fam_new: Dict[str, Dict[str, object]] = {}
        for fam, leafmap in changes.items():
            new_f = getattr(dtables, fam)
            host_f = getattr(aug_host, fam)
            dev_f = getattr(dev, fam)
            for leaf, rec in leafmap.items():
                new_arr = np.asarray(getattr(new_f, leaf))
                host_leaf = np.asarray(getattr(host_f, leaf))
                axis = rep_axes.get((fam, leaf))
                dev_leaf = getattr(dev_f, leaf)
                if axis is None or rec is True:
                    if axis is not None:
                        new_arr = partition.replicate_shard_axis(
                            new_arr, self.ntp, axis
                        )
                    if host_leaf.shape == new_arr.shape and (
                        np.array_equal(host_leaf, new_arr)
                    ):
                        continue
                    sharding = getattr(
                        getattr(self._shardings, fam), leaf, None
                    ) or NamedSharding(self.mesh, P())
                    fam_new.setdefault(fam, {})[leaf] = (
                        jax.device_put(new_arr, sharding)
                    )
                    setattr(host_f, leaf, new_arr)
                    bytes_h2d += int(new_arr.nbytes)
                    replaced += 1
                    continue
                idx = np.unique(np.asarray(rec, np.int64))
                nb = new_arr.shape[axis] // self.ntp
                primary, backup = partition.replica_positions(
                    idx, nb, self.ntp
                )
                rows = np.take(new_arr, idx, axis=axis)
                prev_rows = np.take(host_leaf, primary, axis=axis)
                moved = np.moveaxis(rows, axis, 0).reshape(
                    len(idx), -1
                ) != np.moveaxis(prev_rows, axis, 0).reshape(
                    len(idx), -1
                )
                chg = np.flatnonzero(np.any(moved, axis=1))
                if chg.size == 0:
                    continue
                rows = np.take(rows, chg, axis=axis)
                aug_idx = np.concatenate(
                    [primary[chg], backup[chg]]
                )
                aug_rows = np.concatenate([rows, rows], axis=axis)
                size = next_pow2(aug_idx.size)
                if size != aug_idx.size:
                    pad = size - aug_idx.size
                    aug_idx = np.concatenate(
                        [aug_idx, np.repeat(aug_idx[-1:], pad)]
                    )
                    aug_rows = np.concatenate(
                        [
                            aug_rows,
                            np.repeat(
                                np.take(
                                    aug_rows, [-1], axis=axis
                                ),
                                pad, axis=axis,
                            ),
                        ],
                        axis=axis,
                    )
                # keep the retained snapshot exact (the next diff
                # base + the chip-repair value source)
                host_index = (slice(None),) * axis + (aug_idx,)
                host_leaf[host_index] = aug_rows
                idx_dev = jax.device_put(
                    aug_idx, NamedSharding(self.mesh, P())
                )
                rows_dev = jax.device_put(
                    aug_rows, NamedSharding(self.mesh, P())
                )
                new_leaf = self._scatter_fn(
                    (fam, leaf, int(size), int(axis)), int(axis)
                )(dev_leaf, idx_dev, rows_dev)
                fam_new.setdefault(fam, {})[leaf] = new_leaf
                n_rows += int(chg.size)
                bytes_h2d += int(aug_rows.nbytes + aug_idx.nbytes)
        if fam_new:
            fam_objs = {
                fam: dataclasses.replace(getattr(dev, fam), **ups)
                for fam, ups in fam_new.items()
            }
            dev = dataclasses.replace(dev, **fam_objs)
            jax.block_until_ready(dev)
        return dev, DatapathPublishStats(
            epoch=self.epoch, mode="delta-scoped",
            bytes_h2d=bytes_h2d, seconds=0.0,
            scattered_rows=n_rows, replaced_leaves=replaced,
        )

    def _publish_delta(
        self, aug: DatapathTables, spare: dict, donate: bool = True
    ):
        prev = spare["host"]
        n_rows = 0
        bytes_h2d = 0
        replaced = 0
        dev = spare["dev"]
        fam_new: Dict[str, Dict[str, object]] = {}

        def leaf_path_iter():
            """((family, leaf, new_arr, prev_arr, dev_leaf) ...) for
            every array leaf, family-qualified — generic over the
            registered family dataclasses."""
            for fam in (
                "prefilter", "ipcache", "ct", "lb", "policy",
                "tunnel",
            ):
                new_f = getattr(aug, fam)
                prev_f = getattr(prev, fam)
                dev_f = getattr(dev, fam)
                if new_f is None:
                    continue
                new_ch, _ = new_f.tree_flatten()
                prev_ch, _ = prev_f.tree_flatten()
                dev_ch, _ = dev_f.tree_flatten()
                names = _family_leaf_names(new_f)
                for name, a, b, d in zip(
                    names, new_ch, prev_ch, dev_ch
                ):
                    yield fam, name, a, b, d

        rep_axes = partition.datapath_all_replica_axes(
            aug, self.ntp, self.table_axis
        )
        for fam, name, new_arr, prev_arr, dev_leaf in leaf_path_iter():
            if new_arr is None:
                continue
            new_np = np.asarray(new_arr)
            prev_np = np.asarray(prev_arr)
            axis = rep_axes.get((fam, name))
            if axis is not None and new_np.shape == prev_np.shape:
                # row diff along the sharded axis: only changed
                # index slices ship, in augmented coordinates (a
                # changed row lands at both its primary and backup
                # positions — replica copies stay bit-identical)
                moved_new = np.moveaxis(new_np, axis, 0)
                moved_prev = np.moveaxis(prev_np, axis, 0)
                changed = np.flatnonzero(
                    np.any(
                        moved_new.reshape(moved_new.shape[0], -1)
                        != moved_prev.reshape(
                            moved_prev.shape[0], -1
                        ),
                        axis=1,
                    )
                )
                if changed.size == 0:
                    continue
                size = next_pow2(changed.size)
                if size != changed.size:
                    changed = np.concatenate(
                        [
                            changed,
                            np.repeat(
                                changed[-1:], size - changed.size
                            ),
                        ]
                    )
                rows = np.take(new_np, changed, axis=axis)
                idx_dev = jax.device_put(
                    changed, NamedSharding(self.mesh, P())
                )
                rows_dev = jax.device_put(
                    rows, NamedSharding(self.mesh, P())
                )
                new_leaf = self._scatter_fn(
                    (fam, name, int(size), int(axis)), int(axis),
                    donate=donate,
                )(dev_leaf, idx_dev, rows_dev)
                fam_new.setdefault(fam, {})[name] = new_leaf
                n_rows += int(changed.size)
                bytes_h2d += int(rows.nbytes + changed.nbytes)
            else:
                if new_np.shape == prev_np.shape and np.array_equal(
                    new_np, prev_np
                ):
                    continue
                sharding = getattr(
                    getattr(self._shardings, fam), name, None
                )
                if sharding is None:
                    sharding = NamedSharding(self.mesh, P())
                fam_new.setdefault(fam, {})[name] = jax.device_put(
                    new_np, sharding
                )
                bytes_h2d += int(new_np.nbytes)
                replaced += 1
        if fam_new:
            fam_objs = {
                fam: dataclasses.replace(getattr(dev, fam), **ups)
                for fam, ups in fam_new.items()
            }
            dev = dataclasses.replace(dev, **fam_objs)
            jax.block_until_ready(dev)
        return dev, DatapathPublishStats(
            epoch=self.epoch, mode="delta", bytes_h2d=bytes_h2d,
            seconds=0.0, scattered_rows=n_rows,
            replaced_leaves=replaced,
        )

    def _repair_slot(self, slot: dict, col: int) -> int:
        aug = slot["host"]
        # a slot created under a DIFFERENT layout than the store's
        # current one (the pre-cutover source epoch, or the staged
        # reshard target) repairs in ITS OWN coordinates — column
        # arithmetic and payload placement follow the slot's mesh
        ntp = int(slot.get("ntp", self.ntp))
        mesh = slot.get("mesh", self.mesh)
        if col >= ntp:
            # the column does not exist under this slot's layout
            # (e.g. a grown mesh's new chip vs the source epoch)
            return 0
        rep_axes = partition.datapath_all_replica_axes(
            aug, ntp, self.table_axis
        )
        dev = slot["dev"]
        fam_new: Dict[str, Dict[str, object]] = {}
        bytes_h2d = 0
        for (fam, name), axis in rep_axes.items():
            host_leaf = np.asarray(
                getattr(getattr(aug, fam), name)
            )
            per = host_leaf.shape[axis] // ntp
            idx = np.arange(
                col * per, (col + 1) * per, dtype=np.int64
            )
            rows = np.take(host_leaf, idx, axis=axis)
            dev_leaf = getattr(getattr(dev, fam), name)
            idx_dev = jax.device_put(
                idx, NamedSharding(mesh, P())
            )
            rows_dev = jax.device_put(
                rows, NamedSharding(mesh, P())
            )
            new_leaf = self._scatter_fn(
                (fam, name, int(next_pow2(idx.size)), int(axis)),
                int(axis),
            )(dev_leaf, idx_dev, rows_dev)
            fam_new.setdefault(fam, {})[name] = new_leaf
            bytes_h2d += int(rows.nbytes + idx.nbytes)
        if fam_new:
            fam_objs = {
                fam: dataclasses.replace(getattr(dev, fam), **ups)
                for fam, ups in fam_new.items()
            }
            slot["dev"] = dataclasses.replace(dev, **fam_objs)
            jax.block_until_ready(slot["dev"])
        return bytes_h2d

    def repair_chip(self, col: int) -> int:
        """Re-scatter one table column's owned augmented regions of
        every sharded plane from each slot's retained host snapshot
        — the datapath half of the re-admission rebalance, applied
        to BOTH epochs (the chip missed publishes into both slots
        while out; repairing only the live one would leave the
        standby semantically stale on its slices, the spare_stale
        hazard the policy store's ledger handles).  Donates the
        repaired slots' buffers — the router calls this at a stream
        boundary, before the probe dispatch, same contract as
        DeviceTableStore.repair_rows.  On the virtual CPU mesh the
        SPMD publish scatter already landed everywhere, so this is
        semantically idempotent; what it models (and what the chaos
        storm bounds) is the repair TRAFFIC a physically absent chip
        would need: bytes proportional to its slices, never a full
        upload.  Returns bytes shipped."""
        with self._lock:
            bytes_h2d = 0
            for slot in self._slots:
                if slot is not None:
                    bytes_h2d += self._repair_slot(slot, col)
            return bytes_h2d

    # -- live elastic resharding (engine/reshard.py drives these) ------------

    def begin_relayout(self, dtables: DatapathTables, target_mesh):
        """Open a relayout window toward `target_mesh`: stage the
        fused datapath epoch re-augmented for the target table-axis
        size into the SPARE slot while the live epoch keeps serving.
        The staged device epoch is seeded with every MOVED augmented
        row (compiler.partition.datapath_reshard_moved_rows — rows
        not device-resident under the source column assignment)
        ZEROED; the migration scatters (`relayout_scatter`) stream
        them in, so the cutover's bit-identity proves the streamed
        bytes.  Returns the moved-row sets ({(family, leaf): (axis,
        index array)}) — the plan's work queue."""
        _check_fused_world(dtables)
        with self._lock, tracing.tracer.span(
            "datapath.begin_relayout", site="engine.datapath_mesh"
        ) as sp:
            if self._relayout is not None:
                raise RuntimeError(
                    "datapath relayout window already open"
                )
            live = self._slots[self._cur]
            if live is None:
                raise RuntimeError(
                    "no live datapath epoch to reshard from"
                )
            ntp_dst = int(target_mesh.shape[self.table_axis])
            aug = partition.replicate_datapath_leaves(
                dtables, ntp_dst, self.table_axis
            )
            moved = partition.datapath_reshard_moved_rows(
                dtables, self.ntp, ntp_dst, self.table_axis
            )
            digest = partition.datapath_partition_digest(
                self.table_axis, ntp=ntp_dst
            )
            shardings = partition.datapath_table_shardings(
                target_mesh, aug, self.table_axis
            )
            fam_zero: Dict[str, Dict[str, object]] = {}
            for (fam, name), (axis, idx) in moved.items():
                idx = np.asarray(idx, np.int64)
                if idx.size == 0:
                    continue
                arr = np.array(
                    np.asarray(getattr(getattr(aug, fam), name))
                )
                arr[(slice(None),) * int(axis) + (idx,)] = 0
                fam_zero.setdefault(fam, {})[name] = arr
            seed = aug
            if fam_zero:
                fam_objs = {
                    fam: dataclasses.replace(
                        getattr(aug, fam), **ups
                    )
                    for fam, ups in fam_zero.items()
                }
                seed = dataclasses.replace(aug, **fam_objs)
            dev = jax.tree.map(
                lambda leaf, s: jax.device_put(
                    np.asarray(leaf), s
                ),
                seed, shardings,
            )
            jax.block_until_ready(dev)
            self.epoch += 1
            spare_i = self._cur ^ 1
            self._slots[spare_i] = {
                "dev": dev, "host": aug,
                "geom": _geometry(dtables), "digest": digest,
                "epoch": self.epoch,
                "mesh": target_mesh, "ntp": ntp_dst,
            }
            self._relayout = {
                "epoch": self.epoch, "mesh": target_mesh,
                "ntp": ntp_dst, "digest": digest,
                "shardings": shardings, "broken": False,
            }
            sp.attrs.update(
                epoch=self.epoch, ntp_src=self.ntp,
                ntp_dst=ntp_dst,
            )
            return moved

    def relayout_state(self) -> Optional[Dict]:
        with self._lock:
            rel = self._relayout
            if rel is None:
                return None
            return {
                "epoch": rel["epoch"], "ntp": rel["ntp"],
                "broken": bool(rel.get("broken")),
            }

    def relayout_scatter(self, row_sets) -> int:
        """One bounded migration step: scatter `row_sets`
        ({(family, leaf): (axis, index array)}) of the STAGED target
        epoch from its retained augmented host — the datapath analog
        of DeviceTableStore.repair_rows(spare=True).  The staged
        buffers are donated (nothing serves from them until
        cutover).  Returns bytes shipped."""
        with self._lock:
            rel = self._relayout
            if rel is None or rel.get("broken"):
                raise RuntimeError(
                    "no open datapath relayout window; scatter "
                    "refused"
                )
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is None or slot["epoch"] != rel["epoch"]:
                raise RuntimeError(
                    "staged datapath relayout epoch is gone"
                )
            aug = slot["host"]
            dev = slot["dev"]
            mesh = rel["mesh"]
            fam_new: Dict[str, Dict[str, object]] = {}
            bytes_h2d = 0
            for fam, name in sorted(row_sets):
                axis, idx = row_sets[(fam, name)]
                idx = np.asarray(idx, np.int64)
                if idx.size == 0:
                    continue
                size = next_pow2(idx.size)
                if size != idx.size:
                    idx = np.concatenate(
                        [idx, np.repeat(idx[-1:], size - idx.size)]
                    )
                host_leaf = np.asarray(
                    getattr(getattr(aug, fam), name)
                )
                rows = np.take(host_leaf, idx, axis=axis)
                dev_leaf = getattr(getattr(dev, fam), name)
                idx_dev = jax.device_put(
                    idx, NamedSharding(mesh, P())
                )
                rows_dev = jax.device_put(
                    rows, NamedSharding(mesh, P())
                )
                new_leaf = self._scatter_fn(
                    (fam, name, int(size), int(axis)), int(axis)
                )(dev_leaf, idx_dev, rows_dev)
                fam_new.setdefault(fam, {})[name] = new_leaf
                bytes_h2d += int(rows.nbytes + idx.nbytes)
            if fam_new:
                fam_objs = {
                    fam: dataclasses.replace(
                        getattr(dev, fam), **ups
                    )
                    for fam, ups in fam_new.items()
                }
                slot["dev"] = dataclasses.replace(dev, **fam_objs)
                jax.block_until_ready(slot["dev"])
            return bytes_h2d

    def relayout_update(self, dtables: DatapathTables):
        """Churn dual-apply: fold a new fused world into the STAGED
        target epoch's retained host, returning the sharded row sets
        whose contents changed ({(family, leaf): (axis, augmented
        index array)}) so the plan can re-queue them (re-streaming
        an already-migrated row is always safe).  Changed REPLICATED
        leaves re-place on the staged device immediately.  A
        geometry change marks the window broken and returns None —
        the plan restarts as a full upload into the target."""
        _check_fused_world(dtables)
        with self._lock:
            rel = self._relayout
            if rel is None or rel.get("broken"):
                raise RuntimeError(
                    "no open datapath relayout window to update"
                )
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is None or slot["epoch"] != rel["epoch"]:
                raise RuntimeError(
                    "staged datapath relayout epoch is gone"
                )
            if _geometry(dtables) != slot["geom"]:
                rel["broken"] = True
                return None
            ntp = rel["ntp"]
            aug = partition.replicate_datapath_leaves(
                dtables, ntp, self.table_axis
            )
            prev = slot["host"]
            dev = slot["dev"]
            rep_axes = partition.datapath_all_replica_axes(
                aug, ntp, self.table_axis
            )
            changed_sets: Dict[tuple, tuple] = {}
            fam_new: Dict[str, Dict[str, object]] = {}
            for fam in (
                "prefilter", "ipcache", "ct", "lb", "policy",
                "tunnel",
            ):
                new_f = getattr(aug, fam)
                prev_f = getattr(prev, fam)
                if new_f is None:
                    continue
                new_ch, _ = new_f.tree_flatten()
                prev_ch, _ = prev_f.tree_flatten()
                names = _family_leaf_names(new_f)
                for name, a, b in zip(names, new_ch, prev_ch):
                    if a is None:
                        continue
                    new_np = np.asarray(a)
                    prev_np = np.asarray(b)
                    axis = rep_axes.get((fam, name))
                    if new_np.shape != prev_np.shape:
                        # shape drift outside the geometry
                        # signature — refuse into the restart path
                        rel["broken"] = True
                        return None
                    if axis is not None:
                        mn = np.moveaxis(new_np, axis, 0)
                        mp = np.moveaxis(prev_np, axis, 0)
                        chg = np.flatnonzero(
                            np.any(
                                mn.reshape(mn.shape[0], -1)
                                != mp.reshape(mp.shape[0], -1),
                                axis=1,
                            )
                        )
                        if chg.size:
                            changed_sets[(fam, name)] = (
                                int(axis), chg
                            )
                    elif not np.array_equal(new_np, prev_np):
                        sharding = getattr(
                            getattr(rel["shardings"], fam),
                            name, None,
                        ) or NamedSharding(rel["mesh"], P())
                        fam_new.setdefault(fam, {})[name] = (
                            jax.device_put(new_np, sharding)
                        )
            if fam_new:
                fam_objs = {
                    fam: dataclasses.replace(
                        getattr(dev, fam), **ups
                    )
                    for fam, ups in fam_new.items()
                }
                slot["dev"] = dataclasses.replace(dev, **fam_objs)
                jax.block_until_ready(slot["dev"])
            slot["host"] = aug
            return changed_sets

    def cutover_relayout(self) -> int:
        """Flip the staged target epoch live and rebind the store to
        the target mesh/ntp/digest.  The previous live epoch's
        buffers are untouched (zero drain); it remains as the
        source-layout spare, which the next publish full-uploads
        over (digest mismatch).  Refused while broken."""
        with self._lock, tracing.tracer.span(
            "datapath.cutover_relayout", site="engine.datapath_mesh"
        ) as sp:
            rel = self._relayout
            if rel is None:
                raise RuntimeError(
                    "no open datapath relayout window"
                )
            if rel.get("broken"):
                raise RuntimeError(
                    "datapath relayout window broken; cutover "
                    "refused — restart the migration"
                )
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is None or slot["epoch"] != rel["epoch"]:
                raise RuntimeError(
                    "staged datapath relayout epoch is gone; "
                    "cutover refused"
                )
            self._cur = spare_i
            self._relayout = None
            self.mesh = rel["mesh"]
            self.ntp = rel["ntp"]
            self.partition_digest = rel["digest"]
            self._shardings = rel["shardings"]
            # change records were keyed against source-layout
            # epochs; a scoped publish must not union across the
            # layout seam
            self._change_log.clear()
            sp.attrs.update(epoch=slot["epoch"], ntp=self.ntp)
            return slot["epoch"]

    def rollback_relayout(self) -> bool:
        """Abandon the staged target epoch (the live source layout
        was never touched — rollback is a pointer drop)."""
        with self._lock:
            rel = self._relayout
            if rel is None:
                return False
            spare_i = self._cur ^ 1
            slot = self._slots[spare_i]
            if slot is not None and slot["epoch"] == rel["epoch"]:
                self._slots[spare_i] = None
            self._relayout = None
            return True

    def current(self) -> Optional[DatapathTables]:
        with self._lock:
            slot = self._slots[self._cur]
            return None if slot is None else slot["dev"]

    def host_augmented(self) -> Optional[DatapathTables]:
        with self._lock:
            slot = self._slots[self._cur]
            return None if slot is None else slot["host"]

    def full_bytes(self) -> int:
        with self._lock:
            slot = self._slots[self._cur]
            return (
                0 if slot is None
                else self._tree_nbytes(slot["host"])
            )

    def chip_bytes(self) -> Dict[int, int]:
        """Measured per-chip resident bytes of the CURRENT datapath
        epoch (addressable shards) — the CT/ipcache/LB extension of
        DeviceTableStore.chip_bytes."""
        from cilium_tpu.engine.publish import _chip_resident_bytes

        with self._lock:
            slot = self._slots[self._cur]
            if slot is None:
                return {}
            return _chip_resident_bytes(slot["dev"])


def _family_leaf_names(obj) -> tuple:
    """tree_flatten child names of a registered family pytree —
    paired from the compiler.partition name tables (the pytrees
    flatten positionally)."""
    from cilium_tpu.ct.device import CTSnapshot
    from cilium_tpu.ipcache.lpm import IPCacheDevice, LPMTables
    from cilium_tpu.lb.device import LBInline, LBTables
    from cilium_tpu.prefilter import PrefilterRanges

    if isinstance(obj, CTSnapshot):
        return partition.CT_LEAF_NAMES
    if isinstance(obj, IPCacheDevice):
        return partition.IPCACHE_LEAF_NAMES
    if isinstance(obj, LBInline):
        return partition.LB_INLINE_LEAF_NAMES
    if isinstance(obj, LBTables):
        return partition.LB_CLASSIC_LEAF_NAMES
    if isinstance(obj, PrefilterRanges):
        return ("base", "mask")
    if isinstance(obj, LPMTables):
        return ("l1", "l2")
    from cilium_tpu.compiler.tables import PolicyTables

    if isinstance(obj, PolicyTables):
        return partition.POLICY_LEAF_NAMES
    children, _ = obj.tree_flatten()
    return tuple(f"leaf{i}" for i in range(len(children)))
