"""The batched verdict engine — TPU replacement of the eBPF datapath's
per-packet decision (`__policy_can_access`, bpf/lib/policy.h:46).

`oracle` is the host-side NumPy/dict reference evaluator (the
bit-exactness spec); `verdict` is the jitted JAX implementation,
shardable over a device mesh along the batch axis.
"""

from cilium_tpu.engine.oracle import policy_can_access, evaluate_batch_oracle
from cilium_tpu.engine.verdict import (
    TupleBatch,
    Verdicts,
    evaluate_batch,
    make_sharded_evaluator,
)

__all__ = [
    "policy_can_access",
    "evaluate_batch_oracle",
    "TupleBatch",
    "Verdicts",
    "evaluate_batch",
    "make_sharded_evaluator",
]
