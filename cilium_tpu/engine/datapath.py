"""Fused per-packet datapath: one jitted step over the whole pipeline.

The reference's hot path is a single BPF program per packet —
prefilter (bpf/bpf_xdp.c), LB service DNAT + conntrack + identity
derivation + policy verdict (`handle_ipv4_from_lxc`
bpf/bpf_lxc.c:440 egress, `ipv4_policy` bpf_lxc.c:899 ingress) — not
a chain of separately-invoked kernels.  This module is the TPU
equivalent: every stage is already a fixed number of gathers, so the
whole pipeline fuses into ONE jit (XLA overlaps the gathers; no
host↔device round trips between stages).

Stage order (mirrors the C):

  1. XDP prefilter on the remote (source) address — bpf_xdp.c,
     CIDR4_*_MAP deny sets.
  2. Egress only: LB service probe on the original (daddr, dport,
     proto), backend stickiness via the CT service-scope entry, DNAT
     rewrite — lb4_lookup_service/lb4_local (bpf_lxc.c:486-492).
  3. Conntrack lookup on the (possibly DNATed) tuple, reverse probe
     first — ct_lookup4 (bpf_lxc.c:933, :509).
  4. Identity derivation: ingress takes the ipcache LPM of saddr (what
     bpf_netdev.c derives before the policy tail-call), egress the
     ipcache of the post-DNAT daddr, falling back to WORLD_ID
     (bpf_lxc.c:520-531).
  5. Policy lattice — policy_can_access_ingress / policy_can_egress4
     3-probe verdict (lib/policy.h:46), *always* evaluated.
  6. Combine — REPLY/RELATED bypass a deny verdict; an ESTABLISHED
     flow that is now denied is dropped and its CT entry flagged for
     deletion; NEW+allowed flows are flagged for CT creation; a
     proxy_port verdict redirects only NEW/ESTABLISHED flows
     (bpf_lxc.c:962-985).

CT state mutation (create/delete) happens host-side after the batch
(`apply_ct_writeback`) — the same split as the agent reading/GC'ing
the kernel-owned CT map asynchronously.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.ct.device import (
    CTSnapshot,
    ct_fetch_rows,
    ct_lookup_batch,
    ct_probe_rows,
)
from cilium_tpu.ct.table import (
    CT_EGRESS,
    CT_ESTABLISHED,
    CT_INGRESS,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CT_SERVICE,
    CTMap,
    CTTuple,
    TUPLE_F_IN,
    TUPLE_F_OUT,
    TUPLE_F_SERVICE,
)
from cilium_tpu.engine.verdict import (
    TupleBatch,
    _accumulate_counters,
    _combine,
    _probes,
    _verdict_kernel_with_counters,
    make_counter_buffers,
    split_counters,
)
from cilium_tpu.identity import RESERVED_WORLD
from cilium_tpu.ipcache.lpm import LPMTables, _lookup_kernel
from cilium_tpu.lb.device import LBTables, lb_select_batch
from cilium_tpu.maps.policymap import EGRESS, INGRESS
from cilium_tpu.metrics import registry as metrics


def _register(cls):
    try:
        jax.tree_util.register_pytree_node(
            cls,
            lambda t: t.tree_flatten(),
            lambda aux, ch: cls.tree_unflatten(aux, ch),
        )
    except Exception:  # pragma: no cover
        pass
    return cls


@_register
@dataclass
class DatapathTables:
    """Everything the fused step consumes, as one pytree — the set of
    pinned maps a bpf_lxc program sees (lib/maps.h).  `tunnel` is the
    node-discovery-fed prefix→node-IP map (pkg/maps/tunnel); None
    compiles the no-overlay program (native routing mode)."""

    prefilter: object  # PrefilterRanges (broadcast) or LPMTables
    ipcache: LPMTables
    ct: CTSnapshot
    lb: LBTables
    policy: PolicyTables
    tunnel: object = None  # TunnelTables or None

    def tree_flatten(self):
        return (
            (
                self.prefilter, self.ipcache, self.ct, self.lb,
                self.policy, self.tunnel,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@_register
@dataclass
class FlowBatch:
    """Raw 5-tuple flows (pre identity resolution) — what arrives on
    the wire, as opposed to TupleBatch which is post-ipcache."""

    ep_index: jax.Array  # i32 [B]
    saddr: jax.Array  # u32 [B]
    daddr: jax.Array  # u32 [B]
    sport: jax.Array  # i32 [B]
    dport: jax.Array  # i32 [B]
    proto: jax.Array  # i32 [B]
    direction: jax.Array  # i32 [B] 0=ingress 1=egress
    is_fragment: jax.Array  # bool [B]

    def tree_flatten(self):
        return (
            (
                self.ep_index,
                self.saddr,
                self.daddr,
                self.sport,
                self.dport,
                self.proto,
                self.direction,
                self.is_fragment,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def from_numpy(
        ep_index, saddr, daddr, sport, dport, proto, direction,
        is_fragment=None,
    ) -> "FlowBatch":
        """Pack all eight columns into ONE [8, B] u32 host array and
        upload it as a single transfer — per-array device_put pays the
        transport's ~100 ms fixed round-trip latency EIGHT times per
        batch, which dominated the sustained-churn loop.  A tiny
        jitted splitter restores the typed columns on device."""
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        cols = dict(
            ep_index=ep_index, saddr=saddr, daddr=daddr, sport=sport,
            dport=dport, proto=proto, direction=direction,
            is_fragment=is_fragment,
        )
        packed = np.empty((len(FLOW_COLUMNS), b), dtype=np.uint32)
        for j, name in enumerate(FLOW_COLUMNS):
            packed[j] = np.asarray(cols[name]).astype(
                np.uint32, copy=False
            )
        return _unpack_flow_batch(jnp.asarray(packed))


# THE column-order contract for packed flow transfers: row j of a
# [8, B] u32 pack is FLOW_COLUMNS[j].  from_numpy's pack,
# flow_batch_from_packed, and replay.pack_flow_pool all derive from
# this one tuple — reorder here and nowhere else.
FLOW_COLUMNS = (
    "ep_index", "saddr", "daddr", "sport", "dport", "proto",
    "direction", "is_fragment",
)


# -- packed4: the narrow-dtype staging format ---------------------------------
# The eight u32 flow columns carry at most 16 meaningful bits each
# outside the two addresses, so the H2D staging pack halves to FOUR
# u32 rows (16 B/tuple instead of 32):
#   row 0  saddr
#   row 1  daddr
#   row 2  sport << 16 | dport
#   row 3  ep_index << 16 | proto << 8 | direction << 1 | is_fragment
# The unpack runs INSIDE the jitted program (host-visible semantics
# unchanged — bit-identity gated in bench and tests); ranges are the
# same invariants the tables already rely on (ports < 2^16, proto <
# 2^8, ep_index < 2^16 per the hashed-key endpoint cap).
def pack_flow_records4(
    ep_index, saddr, daddr, sport, dport, proto, direction,
    is_fragment=None,
) -> np.ndarray:
    """Host half of the packed4 staging format: [4, B] u32."""
    b = len(ep_index)
    if is_fragment is None:
        is_fragment = np.zeros(b, dtype=bool)
    ep = np.asarray(ep_index).astype(np.uint32)
    if b and int(ep.max()) >= 1 << 16:
        raise ValueError("ep_index exceeds the packed4 16-bit field")
    packed = np.empty((4, b), dtype=np.uint32)
    packed[0] = np.asarray(saddr).astype(np.uint32, copy=False)
    packed[1] = np.asarray(daddr).astype(np.uint32, copy=False)
    packed[2] = (
        (np.asarray(sport).astype(np.uint32) & 0xFFFF) << 16
    ) | (np.asarray(dport).astype(np.uint32) & 0xFFFF)
    packed[3] = (
        (ep << 16)
        | ((np.asarray(proto).astype(np.uint32) & 0xFF) << 8)
        | ((np.asarray(direction).astype(np.uint32) & 1) << 1)
        | np.asarray(is_fragment).astype(np.uint32)
    )
    return packed


def flow_batch_from_packed4(packed) -> "FlowBatch":
    """Device half of packed4 (traced: call from inside a jit)."""
    w3 = packed[3]
    return FlowBatch(
        ep_index=(w3 >> jnp.uint32(16)).astype(jnp.int32),
        saddr=packed[0],
        daddr=packed[1],
        sport=(packed[2] >> jnp.uint32(16)).astype(jnp.int32),
        dport=(packed[2] & jnp.uint32(0xFFFF)).astype(jnp.int32),
        proto=((w3 >> jnp.uint32(8)) & jnp.uint32(0xFF)).astype(
            jnp.int32
        ),
        direction=((w3 >> jnp.uint32(1)) & jnp.uint32(1)).astype(
            jnp.int32
        ),
        is_fragment=(w3 & jnp.uint32(1)).astype(bool),
    )


def flow_batch_from_packed(packed) -> "FlowBatch":
    """[8, B] u32 rows (FLOW_COLUMNS order) → typed FlowBatch columns.
    Traced helper: call from inside a jit (device-side half of the
    single-transfer pack; also the pool-mode gather's splitter)."""
    cols = dict(zip(FLOW_COLUMNS, packed))
    return FlowBatch(
        ep_index=cols["ep_index"].astype(jnp.int32),
        saddr=cols["saddr"],
        daddr=cols["daddr"],
        sport=cols["sport"].astype(jnp.int32),
        dport=cols["dport"].astype(jnp.int32),
        proto=cols["proto"].astype(jnp.int32),
        direction=cols["direction"].astype(jnp.int32),
        is_fragment=cols["is_fragment"].astype(bool),
    )


# jitted splitter (jax.jit is lazy — no trace until first call): the
# device-side half of FlowBatch.from_numpy's single-transfer pack
_unpack_flow_batch = jax.jit(flow_batch_from_packed)


@_register
@dataclass
class DatapathVerdicts:
    """Per-flow outcome of the fused step plus the CT writeback
    intents the host applies after the batch."""

    allowed: jax.Array  # u8 [B]
    proxy_port: jax.Array  # i32 [B]
    match_kind: jax.Array  # u8 [B] MATCH_* of the lattice
    ct_result: jax.Array  # u8 [B] CT_NEW/ESTABLISHED/REPLY/RELATED
    pre_dropped: jax.Array  # bool [B] killed by the XDP prefilter
    sec_id: jax.Array  # u32 [B] derived peer identity
    final_daddr: jax.Array  # u32 [B] post-DNAT dst address
    final_dport: jax.Array  # i32 [B] post-DNAT dst port
    rev_nat: jax.Array  # i32 [B] rev-NAT index for CT create
    lb_slave: jax.Array  # i32 [B] chosen backend (0 = not a service)
    ct_create: jax.Array  # bool [B] NEW + allowed → host CT create
    ct_delete: jax.Array  # bool [B] ESTABLISHED + denied → host delete
    # u32 [B] remote node IP to encapsulate to (0 = direct/local) —
    # bpf_overlay's encap decision; all-zero without a tunnel map
    tunnel_endpoint: jax.Array = None
    # i32 [B] global L4 slot of the matched entry (0 on L3/no match) —
    # keys the fleet L7 scope tables (l7/fleet.py) for redirected flows
    l4_slot: jax.Array = None
    # bool [B] identity derivation fell back to WORLD (ipcache miss) —
    # the telemetry plane's ipcache_world stage column
    ipcache_miss: jax.Array = None

    def tree_flatten(self):
        return (
            (
                self.allowed,
                self.proxy_port,
                self.match_kind,
                self.ct_result,
                self.pre_dropped,
                self.sec_id,
                self.final_daddr,
                self.final_dport,
                self.rev_nat,
                self.lb_slave,
                self.ct_create,
                self.ct_delete,
                self.tunnel_endpoint,
                self.l4_slot,
                self.ipcache_miss,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _datapath_core(
    tables: DatapathTables,
    flows: FlowBatch,
    with_counters: bool,
    acc=None,
    emit_sec_id: bool = True,
    static_direction=None,
    defer_counters: bool = False,
    collect_telemetry: bool = False,
    lattice_fn=None,
):
    """The fused per-packet pipeline.  With an idx-form ipcache
    (specialize_ipcache_to_idx) the identity lookup yields the dense
    lattice index directly and the id_direct gather disappears; with
    `emit_sec_id=False` (the streaming accum path) the sec output is
    that raw index — consumers translate through id_table host-side —
    saving the id_table gather too.

    `static_direction` compiles a direction-specialized program, the
    analog of bpf_lxc's separate from-container/to-container sections:
    the INGRESS program has no LB/service-CT stages at all (3 fewer
    gathers), exactly as ingress packets never traverse lb4_local."""
    if static_direction is None:
        ingress = flows.direction == INGRESS
    else:
        ingress = jnp.full(
            flows.direction.shape, static_direction == INGRESS
        )

    # -- 1. XDP prefilter (deny-by-CIDR before everything): small
    # deny lists are a broadcast compare — zero gathers ------------------
    from cilium_tpu.prefilter import prefilter_drop

    pre_drop = prefilter_drop(tables.prefilter, flows.saddr)

    # -- 2+3. ONE CT row gather serves both probes: the bucket row is
    # fetched by the ORIGINAL tuple's normalized hash; the
    # service-scope stickiness probe (lb4_local's ct lookup,
    # bpf_lxc.c:486) compares the original key, and after LB the flow
    # probe (ct_lookup4, bpf_lxc.c:509) compares the post-DNAT key
    # against the SAME row — DNATed entries are dual-homed there by
    # CTBucketIndex, so the second row gather the reference pays in
    # nanoseconds (and we'd pay ~7 ns/flow for) disappears.
    ct_rows = ct_fetch_rows(
        tables.ct, flows.daddr, flows.saddr, flows.dport, flows.sport,
        flows.proto,
    )
    if static_direction == INGRESS:
        zero = jnp.zeros(flows.dport.shape, jnp.int32)
        eff_daddr = flows.daddr.astype(jnp.uint32)
        eff_dport = flows.dport
        rev_nat = zero
        lb_slave = zero
    else:
        svc_dir = jnp.full_like(flows.direction, CT_SERVICE)
        _, _, svc_slave = ct_probe_rows(
            tables.ct,
            ct_rows,
            flows.daddr,
            flows.saddr,
            flows.dport,
            flows.sport,
            flows.proto,
            svc_dir,
        )
        svc_found, slave, lb_daddr, lb_dport, lb_rev = lb_select_batch(
            tables.lb,
            flows.saddr,
            flows.daddr,
            flows.sport,
            flows.dport,
            flows.proto,
            ct_slave=svc_slave,
        )
        do_lb = (~ingress) & svc_found
        eff_daddr = jnp.where(
            do_lb, lb_daddr, flows.daddr.astype(jnp.uint32)
        )
        eff_dport = jnp.where(do_lb, lb_dport, flows.dport)
        rev_nat = jnp.where(do_lb, lb_rev, 0)
        lb_slave = jnp.where(do_lb, slave, 0)

    ct_res, ct_rev, _ = ct_probe_rows(
        tables.ct,
        ct_rows,
        eff_daddr,
        flows.saddr,
        eff_dport,
        flows.sport,
        flows.proto,
        flows.direction,
    )

    # -- 4. identity derivation (ipcache LPM; WORLD fallback) ---------------
    from cilium_tpu.ipcache.lpm import (
        UNKNOWN_IDX,
        IPCacheDevice,
        ipcache_lookup_fused,
    )

    sec_ip = jnp.where(
        ingress, flows.saddr.astype(jnp.uint32), eff_daddr
    )
    idx_known = None
    if (
        isinstance(tables.ipcache, IPCacheDevice)
        and tables.ipcache.values_are_idx
    ):
        looked, l3_word = ipcache_lookup_fused(
            tables.ipcache, sec_ip, ingress=ingress
        )
        n = tables.policy.id_table.shape[0]
        miss = looked == 0
        ipc_miss = miss
        # UNKNOWN_IDX = ipcache entry whose identity is outside the
        # policy universe: present (no WORLD fallback) but not-known
        vp = jnp.where(
            miss, jnp.uint32(tables.ipcache.world_plus1), looked
        )
        known = (vp != 0) & (vp != jnp.uint32(UNKNOWN_IDX))
        idx = jnp.where(known, vp - 1, jnp.uint32(n - 1)).astype(
            jnp.int32
        )
        if l3_word is not None:
            # miss → WORLD's l3 bits, selected by direction
            l3_word = jnp.where(
                miss,
                jnp.where(
                    ingress,
                    jnp.uint32(tables.ipcache.world_l3_in),
                    jnp.uint32(tables.ipcache.world_l3_out),
                ),
                l3_word,
            )
            l3_bit = (
                (l3_word >> flows.ep_index.astype(jnp.uint32)) & 1
            ).astype(bool)
            idx_known = (idx, known, l3_bit)
        else:
            idx_known = (idx, known)
        if emit_sec_id:
            sec_id = tables.policy.id_table[idx]
        else:
            sec_id = idx.astype(jnp.uint32)  # sec_idx form
        lattice_identity = jnp.zeros_like(looked)  # unused
    else:
        looked = _lookup_kernel(tables.ipcache, sec_ip)
        ipc_miss = looked == 0
        sec_id = jnp.where(
            looked == 0, jnp.uint32(RESERVED_WORLD), looked
        ).astype(jnp.uint32)
        lattice_identity = sec_id

    # -- 5. policy lattice (always evaluated, bpf_lxc.c:959) ----------------
    resolved = TupleBatch(
        ep_index=flows.ep_index,
        identity=lattice_identity,
        dport=eff_dport,
        proto=flows.proto,
        direction=flows.direction,
        is_fragment=flows.is_fragment,
    )
    # `lattice_fn` swaps the probe chain for a memoized equivalent
    # (engine/memo.py: intra-batch dedup + device verdict cache) —
    # same (probe1, probe2, probe3, proxy, j, idx) contract, so the
    # combine / counter / telemetry stages below are shared code and
    # the bit-identity surface is the probe outputs alone
    if lattice_fn is None:
        probe1, probe2, probe3, proxy, j, idx = _probes(
            tables.policy, resolved, idx_known=idx_known
        )
    else:
        probe1, probe2, probe3, proxy, j, idx = lattice_fn(
            tables.policy, resolved, idx_known
        )
    v = _combine(probe1, probe2, probe3, proxy, resolved.is_fragment)
    deferred = None
    if with_counters:
        if defer_counters:
            # hand the scatter ingredients back to the caller: the
            # paired-dispatch program concatenates both directions'
            # columns and pays ONE scatter per pair instead of two
            # (scatter cost is near size-independent on this chip)
            deferred = (resolved, j, idx)
        else:
            if acc is None:
                acc = make_counter_buffers(tables.policy)
            acc = _accumulate_counters(
                v, resolved, j, idx, acc,
                tables.policy.l4_meta.shape[2],
            )

    # -- 6. combine (bpf_lxc.c:962-985) -------------------------------------
    pol_allow = v.allowed.astype(bool)
    pass_ct = (ct_res == CT_REPLY) | (ct_res == CT_RELATED)
    allowed = (~pre_drop) & (pass_ct | pol_allow)
    ct_delete = (
        (ct_res == CT_ESTABLISHED) & ~pol_allow & ~pass_ct & ~pre_drop
    )
    ct_create = (ct_res == CT_NEW) & allowed
    proxy = jnp.where(
        pol_allow
        & ((ct_res == CT_NEW) | (ct_res == CT_ESTABLISHED))
        & allowed,
        v.proxy_port,
        0,
    )

    # -- 7. overlay forwarding decision (encap_and_redirect,
    # bpf/lib/encap.h:26 via bpf_lxc's ipv4 tail): an ALLOWED egress
    # flow whose destination falls in a remote node's pod CIDR gets
    # the tunnel endpoint (the identity to carry rides in sec_id,
    # exactly as the reference stuffs seclabel into the tunnel key);
    # 0 = direct route / local delivery ---------------------------------
    if tables.tunnel is not None and static_direction != INGRESS:
        from cilium_tpu.tunnel import tunnel_select

        tunnel_ep = jnp.where(
            allowed & ~ingress,
            tunnel_select(tables.tunnel, eff_daddr),
            jnp.uint32(0),
        )
    else:
        tunnel_ep = jnp.zeros(eff_daddr.shape, jnp.uint32)

    out = DatapathVerdicts(
        allowed=allowed.astype(jnp.uint8),
        proxy_port=proxy,
        match_kind=v.match_kind,
        ct_result=ct_res,
        pre_dropped=pre_drop,
        sec_id=sec_id,
        final_daddr=eff_daddr,
        final_dport=eff_dport,
        rev_nat=rev_nat,
        lb_slave=lb_slave,
        ct_create=ct_create,
        ct_delete=ct_delete,
        tunnel_endpoint=tunnel_ep,
        l4_slot=j,
        ipcache_miss=ipc_miss,
    )
    trow = None
    if collect_telemetry:
        # [2, TELEM_COLS] u32 stage histogram of THIS batch: the same
        # shared mask definitions the host fold applies to per-tuple
        # outputs, reduced per direction inside the fused program —
        # ~20 masked sums ride the dispatch (no extra launch, no
        # per-tuple D2H)
        from cilium_tpu.engine.verdict import telemetry_masks

        masks = telemetry_masks(
            pre_drop, ct_res, v.match_kind, allowed, ct_delete,
            proxy, lb_slave, ipc_miss,
        )
        # one reduction pair per column: the egress row is the
        # column total minus the ingress row (direction partitions
        # the batch), so 2T sums become T+T-with-const-folding —
        # and in the direction-specialized programs `ingress` is a
        # constant, so XLA folds one of the two rows to zeros
        row_in = jnp.stack(
            [jnp.sum(m & ingress, dtype=jnp.uint32) for m in masks]
        )
        col_total = jnp.stack(
            [jnp.sum(m, dtype=jnp.uint32) for m in masks]
        )
        trow = jnp.stack([row_in, col_total - row_in])
    if with_counters:
        if defer_counters:
            tail = (v, *deferred)
            return (out, tail, trow) if collect_telemetry else (out, tail)
        return (out, acc, trow) if collect_telemetry else (out, acc)
    return (out, trow) if collect_telemetry else out


def _datapath_kernel(
    tables: DatapathTables, flows: FlowBatch
) -> DatapathVerdicts:
    return _datapath_core(tables, flows, with_counters=False)


def _datapath_kernel_with_counters(
    tables: DatapathTables, flows: FlowBatch
):
    """Fused step + per-entry packet counters (policy.h:66-68), same
    counter semantics as the lattice-only counters kernel: a counter
    bump per lattice hit, indexed in the published tables' slot and
    identity axes.  Returns (out, l4_counts, l3_counts)."""
    out, acc = _datapath_core(tables, flows, with_counters=True)
    l4_counts, l3_counts = split_counters(acc, tables.policy)
    return out, l4_counts, l3_counts


def _datapath_kernel_accum(
    tables: DatapathTables, flows: FlowBatch, acc
):
    """Streaming fused step: counters scatter into the CARRIED flat
    buffer the caller threads (and jit donates) across batches — no
    per-batch [E, 2, N] materialization and ONE scatter.  This is the
    headline-path kernel; the agent folds the buffer back into
    realized map states once per replay (the async kernel-map read of
    pkg/maps/policymap).  With an idx-form ipcache the sec output is
    the dense identity INDEX (translate via tables.policy.id_table
    host-side, as the monitor fold does)."""
    return _datapath_core(
        tables, flows, with_counters=True, acc=acc, emit_sec_id=False
    )


datapath_step = jax.jit(_datapath_kernel)
datapath_step_with_counters = jax.jit(_datapath_kernel_with_counters)
datapath_step_accum = jax.jit(_datapath_kernel_accum, donate_argnums=(2,))


def _accum_dir_kernel(direction):
    def kernel(tables, flows, acc):
        return _datapath_core(
            tables,
            flows,
            with_counters=True,
            acc=acc,
            emit_sec_id=False,
            static_direction=direction,
        )

    return kernel


# direction-specialized streaming programs (bpf_lxc's separate
# ingress/egress sections): callers that split their flow stream per
# direction — as the kernel datapath inherently does — dispatch these
datapath_step_accum_ingress = jax.jit(
    _accum_dir_kernel(INGRESS), donate_argnums=(2,)
)
datapath_step_accum_egress = jax.jit(
    _accum_dir_kernel(EGRESS), donate_argnums=(2,)
)


def _datapath_kernel_accum_pair(tables, flows_in, flows_eg, acc):
    """BOTH direction-specialized programs in ONE dispatch, with the
    two batches' counter hits concatenated into a SINGLE scatter.
    Per pair of half-batches this saves one dispatch floor and one
    scatter relative to alternating the per-direction programs —
    a measurable slice of the headline loop on v5e — while computing
    bit-identical verdicts and counters (scatter-adds commute)."""
    from cilium_tpu.engine.verdict import _counter_cols

    out_i, (v_i, res_i, j_i, idx_i) = _datapath_core(
        tables, flows_in, with_counters=True, emit_sec_id=False,
        static_direction=INGRESS, defer_counters=True,
    )
    out_e, (v_e, res_e, j_e, idx_e) = _datapath_core(
        tables, flows_eg, with_counters=True, emit_sec_id=False,
        static_direction=EGRESS, defer_counters=True,
    )
    kg = tables.policy.l4_meta.shape[2]
    ep_i, d_i, c_i, w_i = _counter_cols(v_i, res_i, j_i, idx_i, kg)
    ep_e, d_e, c_e, w_e = _counter_cols(v_e, res_e, j_e, idx_e, kg)
    acc = acc.at[
        jnp.concatenate([ep_i, ep_e]),
        jnp.concatenate([d_i, d_e]),
        jnp.concatenate([c_i, c_e]),
    ].add(jnp.concatenate([w_i, w_e]))
    return out_i, out_e, acc


# the headline streaming shape: one dispatch evaluates an ingress
# half-batch AND an egress half-batch with one merged counter scatter
datapath_step_accum_pair = jax.jit(
    _datapath_kernel_accum_pair, donate_argnums=(3,)
)


def _datapath_kernel_telem(tables: DatapathTables, flows: FlowBatch):
    """One-shot instrumented step: full verdicts + this batch's
    [2, TELEM_COLS] stage histogram (tests, trace tooling, smoke)."""
    return _datapath_core(
        tables, flows, with_counters=False, collect_telemetry=True
    )


def _datapath_kernel_accum_telem(
    tables: DatapathTables, flows: FlowBatch, acc, telem
):
    """Streaming fused step + telemetry: the counter scatter AND the
    stage-histogram reduction both ride the one dispatch; `telem` is
    a carried donated [2, TELEM_COLS] u32 buffer
    (verdict.make_telemetry_buffers)."""
    out, acc, trow = _datapath_core(
        tables, flows, with_counters=True, acc=acc,
        emit_sec_id=False, collect_telemetry=True,
    )
    return out, acc, telem + trow


def _datapath_kernel_accum_pair_telem(
    tables, flows_in, flows_eg, acc, telem
):
    """The instrumented headline shape: the paired-dispatch program
    (one dispatch, one merged counter scatter per direction pair)
    plus per-direction stage accounting folded into the carried
    telemetry buffer — bit-identical verdicts and counters to
    datapath_step_accum_pair, with the [2, TELEM_COLS] reductions
    fused into the same program."""
    from cilium_tpu.engine.verdict import _counter_cols

    out_i, (v_i, res_i, j_i, idx_i), trow_i = _datapath_core(
        tables, flows_in, with_counters=True, emit_sec_id=False,
        static_direction=INGRESS, defer_counters=True,
        collect_telemetry=True,
    )
    out_e, (v_e, res_e, j_e, idx_e), trow_e = _datapath_core(
        tables, flows_eg, with_counters=True, emit_sec_id=False,
        static_direction=EGRESS, defer_counters=True,
        collect_telemetry=True,
    )
    kg = tables.policy.l4_meta.shape[2]
    ep_i, d_i, c_i, w_i = _counter_cols(v_i, res_i, j_i, idx_i, kg)
    ep_e, d_e, c_e, w_e = _counter_cols(v_e, res_e, j_e, idx_e, kg)
    acc = acc.at[
        jnp.concatenate([ep_i, ep_e]),
        jnp.concatenate([d_i, d_e]),
        jnp.concatenate([c_i, c_e]),
    ].add(jnp.concatenate([w_i, w_e]))
    return out_i, out_e, acc, telem + trow_i + trow_e


datapath_step_telem = jax.jit(_datapath_kernel_telem)
datapath_step_accum_telem = jax.jit(
    _datapath_kernel_accum_telem, donate_argnums=(2, 3)
)
datapath_step_accum_pair_telem = jax.jit(
    _datapath_kernel_accum_pair_telem, donate_argnums=(3, 4)
)


def _datapath_kernel_accum_pair_telem_packed4(
    tables, packed_in, packed_eg, acc, telem
):
    """The async-dispatch headline shape: both half-batches arrive in
    the packed4 staging format ([4, B] u32, 16 B/tuple H2D) and
    unpack INSIDE the fused program — bit-identical verdicts,
    counters and telemetry to datapath_step_accum_pair_telem over the
    same flows (the unpack is exact; bench gates it)."""
    return _datapath_kernel_accum_pair_telem(
        tables,
        flow_batch_from_packed4(packed_in),
        flow_batch_from_packed4(packed_eg),
        acc,
        telem,
    )


datapath_step_accum_pair_telem_packed4 = jax.jit(
    _datapath_kernel_accum_pair_telem_packed4, donate_argnums=(3, 4)
)


def _datapath_kernel_accum_pair_telem_packed4_stacked(
    tables, pair, acc, telem
):
    """Both packed4 half-batches in ONE staged array ([2, 4, B] u32):
    the async staging pipeline pays a single device_put per batch
    pair — on latency-bound transports the second transfer's round
    trip is pure overhead — and the direction split happens inside
    the jit."""
    return _datapath_kernel_accum_pair_telem_packed4(
        tables, pair[0], pair[1], acc, telem
    )


datapath_step_accum_pair_telem_packed4_stacked = jax.jit(
    _datapath_kernel_accum_pair_telem_packed4_stacked,
    donate_argnums=(2, 3),
)


# ---------------------------------------------------------------------------
# Sub-word hot planes: the whole-datapath transform + layout stamp
# ---------------------------------------------------------------------------


def subword_datapath_tables(
    dtables: DatapathTables,
    l4_lanes: "int | None" = None,
    ct_lanes: "int | None" = None,
    strict: bool = False,
) -> Tuple[DatapathTables, dict]:
    """Apply every sub-word hot-lane transform the world's semantics
    allow — ONE entry point, ONE layout stamp: the compact 2-word
    hashed L4 pair (compiler.tables.repack_l4_subword), the 4-word
    CT bucket rows (ct.device.compact_ct_snapshot) and the packed
    idx/l3/prefix-class ipcache planes (ipcache.lpm.subword_ipcache).

    Each plane transforms independently; one whose ranges don't fit
    its compact fields keeps its wide layout (or raises when
    `strict`).  Returns (tables, report) — report maps plane ->
    "packed"/"kept: <why>" so bench/gatherprof can emit the
    per-width model honestly.  Verdicts are bit-identical by
    construction (each transform's contract), and every changed
    plane moves the layout stamp (datapath_layout_version) so
    delta publication refuses across the seam."""
    import dataclasses

    from cilium_tpu.compiler.tables import (
        L4C_LANES,
        repack_l4_subword,
    )
    from cilium_tpu.ct.device import (
        CT_COMPACT_LANES,
        compact_ct_snapshot,
    )
    from cilium_tpu.ipcache.lpm import IPCacheDevice, subword_ipcache

    report = {}
    out = dtables
    try:
        pol = repack_l4_subword(
            dtables.policy, lanes=l4_lanes or L4C_LANES
        )
        out = dataclasses.replace(out, policy=pol)
        report["l4_hash"] = "packed"
    except ValueError as exc:
        if strict:
            raise
        report["l4_hash"] = f"kept: {exc}"
    try:
        ct = compact_ct_snapshot(
            dtables.ct, lanes=ct_lanes or CT_COMPACT_LANES
        )
        out = dataclasses.replace(out, ct=ct)
        report["ct"] = "packed"
    except ValueError as exc:
        if strict:
            raise
        report["ct"] = f"kept: {exc}"
    ipc = dtables.ipcache
    if isinstance(ipc, IPCacheDevice) and ipc.values_are_idx:
        try:
            out = dataclasses.replace(
                out, ipcache=subword_ipcache(ipc)
            )
            report["ipcache"] = "packed"
        except ValueError as exc:
            if strict:
                raise
            report["ipcache"] = f"kept: {exc}"
    else:
        report["ipcache"] = "kept: not an idx-form IPCacheDevice"
    return out, report


def datapath_layout_version(dtables: DatapathTables) -> tuple:
    """The whole-datapath layout stamp: policy layout version (lane
    widths + coldness + compact bit) plus every sub-word marker of
    the CT/ipcache planes.  Joins the partition digest in
    DatapathStore's geometry check — a delta recorded under one
    layout can never scatter into an epoch holding another."""
    from cilium_tpu.compiler.tables import tables_layout_version
    from cilium_tpu.ipcache.lpm import IPCacheDevice

    ipc = dtables.ipcache
    return (
        tables_layout_version(dtables.policy),
        int(getattr(dtables.ct, "entry_words", 5)),
        int(np.asarray(dtables.ct.buckets).shape[1]),
        (
            int(getattr(ipc, "bucket_entries", 0)),
            int(getattr(ipc, "value_width", 32)),
            int(getattr(ipc, "l3_width", 32)),
            tuple(getattr(ipc, "range_widths", ()) or ()),
        )
        if isinstance(ipc, IPCacheDevice) else (),
    )


# ---------------------------------------------------------------------------
# Persistent fused-pair program: zero per-pair dispatch
# ---------------------------------------------------------------------------
# The headline loop's remaining host cost is the PER-PAIR dispatch
# floor: one launch + one drain round trip per pair batch, which the
# async overlap hides only partially (the host still touches the
# executable K times).  The persistent program evaluates K staged
# pairs in ONE launch: a lax.scan walks the [K, 2, 4, B] super-batch,
# the counter/telemetry carry is donated device-resident state woven
# through the scan, and the stacked verdict outputs stay on device
# until the caller drains — carry state commits once per drain, not
# once per pair.

_PERSISTENT_CACHE = {}


def persistent_pair_program(k_pairs: int):
    """Jitted persistent fused-pair program.

    fn(tables, pairs [K, 2, 4, B] u32, acc, telem) ->
        (out_i stacked [K, ...], out_e stacked [K, ...], acc', telem')

    acc/telem are donated; verdict columns for pair i sit at leading
    index i of every output leaf — bit-identical per pair to
    datapath_step_accum_pair_telem_packed4_stacked over the same
    pairs (scan order matches submission order, counter scatter adds
    commute)."""
    key = int(k_pairs)
    fn = _PERSISTENT_CACHE.get(key)
    if fn is not None:
        return fn

    def program(tables, pairs, acc, telem):
        def step(carry, pair):
            acc, telem = carry
            out_i, out_e, acc, telem = (
                _datapath_kernel_accum_pair_telem_packed4(
                    tables, pair[0], pair[1], acc, telem
                )
            )
            return (acc, telem), (out_i, out_e)

        (acc, telem), (outs_i, outs_e) = jax.lax.scan(
            step, (acc, telem), pairs
        )
        return outs_i, outs_e, acc, telem

    fn = jax.jit(program, donate_argnums=(2, 3))
    _PERSISTENT_CACHE[key] = fn
    return fn


class PersistentPairDispatcher:
    """Host driver of the persistent program: stages up to `k_pairs`
    packed4 pair batches, ships them as ONE [K, 2, 4, B] device_put
    and ONE launch, and keeps the counter/telemetry carry
    device-resident across launches (donated) — zero per-pair
    dispatch, zero per-pair host sync.  `submit(pair)` returns a
    list of drained (out_i, out_e) results (empty until a super-batch
    completes); `flush()` runs any staged remainder through the
    per-pair program (same jit class as the reference pair — padding
    the scan would corrupt the carried counters) and returns the
    final (results, acc, telem).

    The jit-tracking proof rides `site`: wrap-tracked launches land
    in cilium_jit_cache_*{site} so a test (or the bench) can assert
    K pairs cost exactly one executable call."""

    def __init__(
        self, tables, k_pairs: int, acc, telem,
        site: str = "datapath.persistent",
    ) -> None:
        from cilium_tpu import tracing

        self.tables = tables
        self.k = max(int(k_pairs), 1)
        self.acc = acc
        self.telem = telem
        self._staged = []
        self._program = tracing.track_jit(
            persistent_pair_program(self.k), site
        )
        self._pair_fallback = tracing.track_jit(
            datapath_step_accum_pair_telem_packed4_stacked,
            site + ".remainder",
        )
        self.launches = 0

    def submit(self, pair_host: np.ndarray):
        """Stage one [2, 4, B] host pair; when the K-th arrives the
        super-batch launches (one dispatch for all K).  Returns the
        drained per-pair (out_i, out_e) tuples, [] while staging."""
        self._staged.append(pair_host)
        if len(self._staged) < self.k:
            return []
        staged_n = len(self._staged)
        stacked = jax.device_put(
            np.stack(self._staged)
        )
        self._staged = []
        outs_i, outs_e, self.acc, self.telem = self._program(
            self.tables, stacked, self.acc, self.telem
        )
        self.launches += 1
        # persistent-program launch accounting for the perf plane:
        # pairs/launches = realized staging depth at scrape time
        metrics.datapath_persistent_launches.inc()
        metrics.datapath_persistent_pairs.inc(value=staged_n)
        return [
            (
                jax.tree.map(lambda a: a[i], outs_i),
                jax.tree.map(lambda a: a[i], outs_e),
            )
            for i in range(self.k)
        ]

    def flush(self):
        """Drain the staged remainder through the per-pair program
        (one launch per leftover pair — still no per-direction
        dispatch) and return (results, acc, telem).  This is the
        ONE carry commit point: callers host-read acc/telem here."""
        results = []
        for pair in self._staged:
            out_i, out_e, self.acc, self.telem = (
                self._pair_fallback(
                    self.tables, jax.device_put(pair),
                    self.acc, self.telem,
                )
            )
            results.append((out_i, out_e))
        self._staged = []
        return results, self.acc, self.telem


def _unique_rows(cols: list, sel: np.ndarray) -> np.ndarray:
    """Stack selected rows of the given columns and dedupe — the
    columns are packed into one u64-pair view so np.unique sorts a
    contiguous array instead of doing per-row tuple compares."""
    rows = np.stack(
        [np.asarray(c)[sel].astype(np.uint64) for c in cols], axis=1
    )
    if rows.shape[0] == 0:
        return rows
    return np.unique(rows, axis=0)


def apply_ct_writeback_host(
    ct: CTMap,
    create,
    delete,
    daddr,
    dport,
    saddr,
    sport,
    proto,
    direction,
    rev_nat,
    slave,
    now: int = 0,
    orig_daddr=None,
    orig_dport=None,
) -> tuple:
    """Host-side CT mutation after a batch (all inputs host arrays):
    create entries for NEW+allowed flows (ct_create4, bpf_lxc.c:978)
    and delete ESTABLISHED-but-now-denied entries (ct_delete4,
    bpf_lxc.c:968).  For load-balanced flows (rev_nat > 0 and the
    pre-DNAT columns provided) the SERVICE-scope entry is created
    alongside, carrying the selected backend for stickiness — exactly
    lb4_local's ct_create4 on the service tuple (bpf/lib/lb.h).
    Returns (created_keys, deleted_keys) — the key lists feed the
    incremental device-snapshot delta (ct.device.CTBucketIndex.apply).

    Vectorized: flagged rows are deduplicated with one np.unique over
    packed tuple columns, so host dict work is O(unique flows), not
    O(batch) — a 1M-tuple batch over a 64k-flow universe touches the
    dict at most 64k times regardless of batch size."""
    created_keys = []
    deleted_keys = []
    if orig_daddr is None:
        orig_daddr = daddr
        orig_dport = dport
    create_cols = [
        daddr, saddr, dport, sport, proto, direction, rev_nat, slave,
        orig_daddr, orig_dport,
    ]
    for row in _unique_rows(create_cols, create):
        (c_daddr, c_saddr, c_dport, c_sport, c_proto, c_dir,
         c_rev, c_slave, c_odaddr, c_odport) = (int(v) for v in row)
        flags = TUPLE_F_OUT if c_dir == CT_INGRESS else TUPLE_F_IN
        key = CTTuple(c_daddr, c_saddr, c_dport, c_sport, c_proto, flags)
        dnat = c_rev > 0 and (
            c_odaddr != c_daddr or c_odport != c_dport
        )
        if key not in ct.entries:
            if ct.create_best_effort(
                CTTuple(c_daddr, c_saddr, c_dport, c_sport, c_proto),
                c_dir, now=now, rev_nat_index=c_rev, slave=c_slave,
                orig_daddr=c_odaddr if dnat else 0,
                orig_dport=c_odport if dnat else 0,
            ):
                created_keys.append(key)
        if dnat:
            # the service-scope stickiness entry (lb4_local)
            svc_key = CTTuple(
                c_odaddr, c_saddr, c_odport, c_sport, c_proto,
                TUPLE_F_SERVICE,
            )
            if svc_key not in ct.entries:
                if ct.create_best_effort(
                    CTTuple(
                        c_odaddr, c_saddr, c_odport, c_sport, c_proto
                    ),
                    CT_SERVICE, now=now, rev_nat_index=c_rev,
                    slave=c_slave,
                ):
                    created_keys.append(svc_key)
    delete_cols = [daddr, saddr, dport, sport, proto, direction]
    for row in _unique_rows(delete_cols, delete):
        c_daddr, c_saddr, c_dport, c_sport, c_proto, c_dir = (
            int(v) for v in row
        )
        flags = TUPLE_F_OUT if c_dir == CT_INGRESS else TUPLE_F_IN
        key = CTTuple(c_daddr, c_saddr, c_dport, c_sport, c_proto, flags)
        if ct.entries.pop(key, None) is not None:
            deleted_keys.append(key)
    return created_keys, deleted_keys


def apply_ct_writeback(
    ct: CTMap, out: DatapathVerdicts, flows: FlowBatch, now: int = 0
) -> tuple:
    """Device-output convenience wrapper over apply_ct_writeback_host;
    returns (created, deleted) counts."""
    created_keys, deleted_keys = apply_ct_writeback_host(
        ct,
        np.asarray(out.ct_create),
        np.asarray(out.ct_delete),
        np.asarray(out.final_daddr),
        np.asarray(out.final_dport),
        np.asarray(flows.saddr),
        np.asarray(flows.sport),
        np.asarray(flows.proto),
        np.asarray(flows.direction),
        np.asarray(out.rev_nat),
        np.asarray(out.lb_slave),
        now=now,
        orig_daddr=np.asarray(flows.daddr),
        orig_dport=np.asarray(flows.dport),
    )
    return len(created_keys), len(deleted_keys)
