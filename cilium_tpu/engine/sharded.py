"""Mesh-sharded verdict evaluation (SPMD over batch × identity axes).

Two parallel axes, mirroring §2.9 of SURVEY.md:

  * `batch` — data parallelism over flow tuples (packets shard across
    nodes in the reference; zero-communication).
  * `table` — the identity (bit-word) axis of the allow tensors is
    sharded when the rule/identity tensors exceed a single chip's HBM
    (a 512k-identity universe × 16k L4 slots would not fit).  The
    small index tables (id_direct/port_slot) replicate
    and resolve a tuple's *global* identity index; each shard then
    tests only the bit-words it owns, and probe hits combine with a
    psum over the axis — the "verdict lattice psum" described in
    SURVEY.md §5 (0/1 hits, associative, order-safe).

The step also accumulates per-entry packet counters (policy_entry
packets, bpf/lib/policy.h:66-68): L4-slot counters replicate, L3
per-identity counters stay sharded along `table`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.engine.oracle import MATCH_L3, MATCH_L4, MATCH_L4_WILD
from cilium_tpu.engine.verdict import (
    TupleBatch,
    Verdicts,
    _combine,
    _index,
)

try:  # jax>=0.4.30 moved shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f=None, **kwargs):
    """shard_map with the replication-check knob spelled per the
    installed jax: newer releases renamed check_rep → check_vma, and
    passing the wrong name is a TypeError at decoration time."""
    import inspect

    params = inspect.signature(_shard_map).parameters
    if "check_vma" not in params and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" not in params and "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    if f is None:
        return partial(_shard_map, **kwargs)
    return _shard_map(f, **kwargs)


def table_specs(batch_axis: str, table_axis: str) -> PolicyTables:
    """PartitionSpecs for a PolicyTables pytree: allow-bit word axes
    sharded along `table_axis`, index tables replicated."""
    return PolicyTables(
        id_table=P(),
        id_direct=P(),
        id_lo_len=P(),
        port_slot=P(),
        l4_meta=P(),
        l4_allow_bits=P(None, None, None, table_axis),
        l3_allow_bits=P(None, None, table_axis),
        generation=P(),
        # the hashed entry table is a single-chip layout (row buckets
        # mix all identities); the table-sharded evaluator replicates
        # it untouched and probes the dense sharded bitmap instead
        l4_hash_rows=P(),
        l4_hash_stash=P(),
        l4_wild_rows=P(),
        l4_wild_stash=P(),
    )


def replicated_table_shardings(mesh: Mesh) -> PolicyTables:
    """NamedShardings replicating every PolicyTables leaf across the
    mesh — the layout make_sharded_evaluator consumes (tables
    replicate like per-node BPF maps)."""
    r = NamedSharding(mesh, P())
    return PolicyTables(
        id_table=r, id_direct=r, id_lo_len=r, port_slot=r,
        l4_meta=r, l4_allow_bits=r, l3_allow_bits=r, generation=r,
        l4_hash_rows=r, l4_hash_stash=r, l4_wild_rows=r,
        l4_wild_stash=r,
    )


def make_replicated_store(mesh: Mesh):
    """DeviceTableStore whose epochs replicate across `mesh`: one
    delta publish applies the same in-place scatter on EVERY chip
    (tables are replicated, so each chip's copy receives identical
    `.at[idx].set(rows)` updates inside one SPMD program)."""
    from cilium_tpu.engine.publish import DeviceTableStore

    return DeviceTableStore(
        shardings=replicated_table_shardings(mesh)
    )


def batch_specs(batch_axis: str) -> TupleBatch:
    s = P(batch_axis)
    return TupleBatch(
        ep_index=s, identity=s, dport=s, proto=s, direction=s, is_fragment=s
    )


def make_mesh_evaluator(
    mesh: Mesh,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """Jitted full datapath step over a 2D (batch × table) mesh.

    Returns fn(tables, batch) -> (Verdicts, l4_counts, l3_counts):
      l4_counts u32 [E, 2, Kg]       replicated
      l3_counts u32 [E, 2, N]        sharded along identity (table) axis

    With `collect_telemetry` the step additionally returns a
    PER-CHIP stage histogram u32 [n_batch_shards, 2, TELEM_COLS]:
    each batch shard reduces its own [2, T] rows inside the dispatch
    (the same ~20 masked sums the single-chip instrumented kernels
    fuse, from the SAME telemetry_masks definition set) and the rows
    all-gather along the batch axis — so ONE host fold
    (telemetry.fold_telemetry_per_chip) yields both the mesh-total
    counters and the `chip`-labeled per-chip rows of the ROADMAP's
    multi-chip aggregation item.  The lattice path carries no
    LB/CT/prefilter stages; their columns fold as zeros, exactly
    what they contribute on this path."""
    t_specs = table_specs(batch_axis, table_axis)
    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    out_specs = (v_specs, P(), P(None, None, table_axis))
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l: PolicyTables, batch_l: TupleBatch):
        # Index resolution uses only replicated tables → global values.
        idx, word, bit, known, j, has_port = _index(tables_l, batch_l)
        # slot metadata from the replicated l4_meta (the fused
        # single-chip path reads it from the hashed entry table)
        meta = tables_l.l4_meta[batch_l.ep_index, batch_l.direction, j]
        proxy = (meta >> 1).astype(jnp.int32)
        wild = (meta & 1).astype(bool)

        # This shard owns bit-words [off, off + w_local).
        w_local = tables_l.l3_allow_bits.shape[-1]
        off = jax.lax.axis_index(table_axis) * w_local
        wl = word - off
        in_shard = (wl >= 0) & (wl < w_local)
        wl = jnp.clip(wl, 0, w_local - 1)

        exact_words = tables_l.l4_allow_bits[
            batch_l.ep_index, batch_l.direction, j, wl
        ]
        p1 = (
            known
            & has_port
            & in_shard
            & ((exact_words >> bit) & 1).astype(bool)
        )
        l3_words = tables_l.l3_allow_bits[
            batch_l.ep_index, batch_l.direction, wl
        ]
        p2 = known & in_shard & ((l3_words >> bit) & 1).astype(bool)
        p3 = wild & has_port  # identity-independent: same in all shards

        # Combine probe hits across identity shards: each identity is
        # resident in exactly one shard, so the sums are 0/1.
        p1g = jax.lax.psum(p1.astype(jnp.int32), table_axis) > 0
        p2g = jax.lax.psum(p2.astype(jnp.int32), table_axis) > 0

        v = _combine(p1g, p2g, p3, proxy, batch_l.is_fragment)

        # Counters.  L4-slot hits are determined by globally-combined
        # bits, so every table shard computes the same array.
        e_count, _, kg = tables_l.l4_meta.shape
        hit_l4 = (v.match_kind == MATCH_L4) | (
            v.match_kind == MATCH_L4_WILD
        )
        l4_counts = jnp.zeros((e_count, 2, kg), jnp.uint32).at[
            batch_l.ep_index, batch_l.direction, j
        ].add(hit_l4.astype(jnp.uint32))
        # L3 hit whose identity bit-word lives in *this* shard.
        l3_hit_here = p2 & (v.match_kind == MATCH_L3)
        idx_l = jnp.clip(idx - off * 32, 0, w_local * 32 - 1)
        l3_counts = jnp.zeros((e_count, 2, w_local * 32), jnp.uint32).at[
            batch_l.ep_index, batch_l.direction, idx_l
        ].add(l3_hit_here.astype(jnp.uint32))

        l4_counts = jax.lax.psum(l4_counts, batch_axis)
        l3_counts = jax.lax.psum(l3_counts, batch_axis)
        if not collect_telemetry:
            return v, l4_counts, l3_counts

        # -- per-chip stage telemetry: this batch shard's [2, T] rows,
        # computed from the globally-combined verdict columns (v is
        # identical across the table axis after the psums above, so
        # every table shard of one batch shard emits the same rows)
        from cilium_tpu.engine.verdict import telemetry_masks

        zeros = jnp.zeros(v.allowed.shape, jnp.int32)
        masks = telemetry_masks(
            zeros, zeros, v.match_kind, v.allowed, zeros,
            v.proxy_port, zeros, zeros,
        )
        ingress = batch_l.direction == 0
        row_in = jnp.stack(
            [jnp.sum(m & ingress, dtype=jnp.uint32) for m in masks]
        )
        col_total = jnp.stack(
            [jnp.sum(m, dtype=jnp.uint32) for m in masks]
        )
        trow = jnp.stack([row_in, col_total - row_in])
        return v, l4_counts, l3_counts, trow[None]

    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), t_specs),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
    )
    return jax.jit(step, in_shardings=in_shardings)


def make_async_mesh_dispatcher(
    step, mesh, batch_axis: str = "batch", depth: int = 1
):
    """Double-buffered dispatch over a mesh evaluator
    (engine.publish.AsyncBatchDispatcher applied to SPMD batches):
    the host packs + shards batch N+1 across the mesh while the
    chips compute batch N.  `step` is a one-argument closure
    batch → result with the tables already bound (e.g.
    `partial(make_sharded_evaluator(mesh), dev_tables)`);
    `submit((ep_index, identity, dport, proto, direction[,
    is_fragment]), meta)` stages a TupleBatch with the batch axis
    sharded; results drain one batch behind in submission order.

    This is the mesh serving loop's missing overlap: the sharded
    device_put (scatter of the batch across chips) is exactly the
    host-side work the single-chip path hides behind compute."""
    import numpy as np

    from cilium_tpu.engine.publish import AsyncBatchDispatcher

    sharded = NamedSharding(mesh, P(batch_axis))

    def pack(ep_index, identity, dport, proto, direction,
             is_fragment=None):
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        put = lambda a, dt: jax.device_put(
            np.asarray(a).astype(dt, copy=False), sharded
        )
        return (
            TupleBatch(
                ep_index=put(ep_index, np.int32),
                identity=put(identity, np.uint32),
                dport=put(dport, np.int32),
                proto=put(proto, np.int32),
                direction=put(direction, np.int32),
                is_fragment=put(is_fragment, bool),
            ),
        )

    def dispatch(batch):
        return step(batch)

    return AsyncBatchDispatcher(pack, dispatch, depth=depth)


def traced_dispatch(step, mesh, site: str = "engine.sharded"):
    """Wrap a mesh evaluator with span-plane dispatch attribution:
    each call opens a `mesh.dispatch` span (blocking on the result so
    the span covers the device execution, not just the enqueue) and
    synthesizes per-chip `chip.dispatch` children — the SPMD program
    runs in lockstep, so the parent's window partitions evenly across
    chips and the children sum to the batch span.  Per-chip spans are
    what the ROADMAP's per-chip failover item needs to debug: which
    ordinal's dispatch latency is the outlier.  The wrapped step also
    counts jit cache hits/misses per call (site label `site`)."""
    from cilium_tpu import tracing

    n_chips = int(mesh.devices.size)
    tracked = tracing.track_jit(step, site)

    def dispatch(tables, batch, *rest):
        rows = int(batch.ep_index.shape[0])
        with tracing.tracer.span(
            "mesh.dispatch", site=site,
            attrs={"chips": n_chips, "rows": rows},
        ) as sp:
            out = tracked(tables, batch, *rest)
            jax.block_until_ready(out)
        tracing.record_chip_spans(
            tracing.tracer, sp, n_chips, rows, site
        )
        return out

    dispatch.__wrapped__ = step
    return dispatch
