"""Mesh-sharded verdict evaluation (SPMD over batch × identity axes).

Two parallel axes, mirroring §2.9 of SURVEY.md:

  * `batch` — data parallelism over flow tuples (packets shard across
    nodes in the reference; zero-communication).
  * `table` — the identity (bit-word) axis of the allow tensors is
    sharded when the rule/identity tensors exceed a single chip's HBM
    (a 512k-identity universe × 16k L4 slots would not fit).  The
    small index tables (id_direct/port_slot) replicate
    and resolve a tuple's *global* identity index; each shard then
    tests only the bit-words it owns, and probe hits combine with a
    psum over the axis — the "verdict lattice psum" described in
    SURVEY.md §5 (0/1 hits, associative, order-safe).

The step also accumulates per-entry packet counters (policy_entry
packets, bpf/lib/policy.h:66-68): L4-slot counters replicate, L3
per-identity counters stay sharded along `table`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.engine.oracle import MATCH_L3, MATCH_L4, MATCH_L4_WILD
from cilium_tpu.engine.verdict import (
    TupleBatch,
    Verdicts,
    _combine,
    _index,
)

try:  # jax>=0.4.30 moved shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f=None, **kwargs):
    """shard_map with the replication-check knob spelled per the
    installed jax: newer releases renamed check_rep → check_vma, and
    passing the wrong name is a TypeError at decoration time."""
    import inspect

    params = inspect.signature(_shard_map).parameters
    if "check_vma" not in params and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" not in params and "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    if f is None:
        return partial(_shard_map, **kwargs)
    return _shard_map(f, **kwargs)


def table_specs(batch_axis: str, table_axis: str) -> PolicyTables:
    """PartitionSpecs for a PolicyTables pytree: allow-bit word axes
    sharded along `table_axis`, index tables replicated."""
    return PolicyTables(
        id_table=P(),
        id_direct=P(),
        id_lo_len=P(),
        port_slot=P(),
        l4_meta=P(),
        l4_allow_bits=P(None, None, None, table_axis),
        l3_allow_bits=P(None, None, table_axis),
        generation=P(),
        # the hashed entry table is a single-chip layout (row buckets
        # mix all identities); the table-sharded evaluator replicates
        # it untouched and probes the dense sharded bitmap instead
        l4_hash_rows=P(),
        l4_hash_stash=P(),
        l4_wild_rows=P(),
        l4_wild_stash=P(),
    )


def replicated_table_shardings(mesh: Mesh) -> PolicyTables:
    """NamedShardings replicating every PolicyTables leaf across the
    mesh — the layout make_sharded_evaluator consumes (tables
    replicate like per-node BPF maps)."""
    r = NamedSharding(mesh, P())
    return PolicyTables(
        id_table=r, id_direct=r, id_lo_len=r, port_slot=r,
        l4_meta=r, l4_allow_bits=r, l3_allow_bits=r, generation=r,
        l4_hash_rows=r, l4_hash_stash=r, l4_wild_rows=r,
        l4_wild_stash=r,
    )


def make_replicated_store(mesh: Mesh):
    """DeviceTableStore whose epochs replicate across `mesh`: one
    delta publish applies the same in-place scatter on EVERY chip
    (tables are replicated, so each chip's copy receives identical
    `.at[idx].set(rows)` updates inside one SPMD program)."""
    from cilium_tpu.engine.publish import DeviceTableStore

    return DeviceTableStore(
        shardings=replicated_table_shardings(mesh)
    )


def batch_specs(batch_axis: str) -> TupleBatch:
    s = P(batch_axis)
    return TupleBatch(
        ep_index=s, identity=s, dport=s, proto=s, direction=s, is_fragment=s
    )


def _counts_and_telemetry(
    v,
    tables_l,
    batch_l,
    j,
    idx,
    p2_local,
    word_off,
    w_local,
    batch_axis,
    collect_telemetry,
):
    """Shared counter + per-chip telemetry epilogue of the mesh and
    partitioned evaluators.  The bit-identity contract across the
    fused kernel and both mesh evaluators depends on there being ONE
    copy of this logic: L4-slot hits come from globally-combined
    verdict columns (identical on every table shard), the L3 hit
    counter stays shard-local (`p2_local` true only on the identity
    word's owner, `word_off` that shard's first bit-word), and the
    [2, T] stage rows reduce from the same telemetry_masks set the
    single-chip instrumented kernels fuse."""
    e_count, _, kg = tables_l.l4_meta.shape
    hit_l4 = (v.match_kind == MATCH_L4) | (
        v.match_kind == MATCH_L4_WILD
    )
    l4_counts = jnp.zeros((e_count, 2, kg), jnp.uint32).at[
        batch_l.ep_index, batch_l.direction, j
    ].add(hit_l4.astype(jnp.uint32))
    l3_hit_here = p2_local & (v.match_kind == MATCH_L3)
    idx_l = jnp.clip(idx - word_off * 32, 0, w_local * 32 - 1)
    l3_counts = jnp.zeros(
        (e_count, 2, w_local * 32), jnp.uint32
    ).at[
        batch_l.ep_index, batch_l.direction, idx_l
    ].add(l3_hit_here.astype(jnp.uint32))
    l4_counts = jax.lax.psum(l4_counts, batch_axis)
    l3_counts = jax.lax.psum(l3_counts, batch_axis)
    if not collect_telemetry:
        return v, l4_counts, l3_counts

    from cilium_tpu.engine.verdict import telemetry_masks

    zeros = jnp.zeros(v.allowed.shape, jnp.int32)
    masks = telemetry_masks(
        zeros, zeros, v.match_kind, v.allowed, zeros,
        v.proxy_port, zeros, zeros,
    )
    ingress = batch_l.direction == 0
    row_in = jnp.stack(
        [jnp.sum(m & ingress, dtype=jnp.uint32) for m in masks]
    )
    col_total = jnp.stack(
        [jnp.sum(m, dtype=jnp.uint32) for m in masks]
    )
    trow = jnp.stack([row_in, col_total - row_in])
    return v, l4_counts, l3_counts, trow[None]


def make_mesh_evaluator(
    mesh: Mesh,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """Jitted full datapath step over a 2D (batch × table) mesh.

    Returns fn(tables, batch) -> (Verdicts, l4_counts, l3_counts):
      l4_counts u32 [E, 2, Kg]       replicated
      l3_counts u32 [E, 2, N]        sharded along identity (table) axis

    With `collect_telemetry` the step additionally returns a
    PER-CHIP stage histogram u32 [n_batch_shards, 2, TELEM_COLS]:
    each batch shard reduces its own [2, T] rows inside the dispatch
    (the same ~20 masked sums the single-chip instrumented kernels
    fuse, from the SAME telemetry_masks definition set) and the rows
    all-gather along the batch axis — so ONE host fold
    (telemetry.fold_telemetry_per_chip) yields both the mesh-total
    counters and the `chip`-labeled per-chip rows of the ROADMAP's
    multi-chip aggregation item.  The lattice path carries no
    LB/CT/prefilter stages; their columns fold as zeros, exactly
    what they contribute on this path."""
    t_specs = table_specs(batch_axis, table_axis)
    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    out_specs = (v_specs, P(), P(None, None, table_axis))
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l: PolicyTables, batch_l: TupleBatch):
        # Index resolution uses only replicated tables → global values.
        idx, word, bit, known, j, has_port = _index(tables_l, batch_l)
        # slot metadata from the replicated l4_meta (the fused
        # single-chip path reads it from the hashed entry table)
        meta = tables_l.l4_meta[batch_l.ep_index, batch_l.direction, j]
        proxy = (meta >> 1).astype(jnp.int32)
        wild = (meta & 1).astype(bool)

        # This shard owns bit-words [off, off + w_local).
        w_local = tables_l.l3_allow_bits.shape[-1]
        off = jax.lax.axis_index(table_axis) * w_local
        wl = word - off
        in_shard = (wl >= 0) & (wl < w_local)
        wl = jnp.clip(wl, 0, w_local - 1)

        exact_words = tables_l.l4_allow_bits[
            batch_l.ep_index, batch_l.direction, j, wl
        ]
        p1 = (
            known
            & has_port
            & in_shard
            & ((exact_words >> bit) & 1).astype(bool)
        )
        l3_words = tables_l.l3_allow_bits[
            batch_l.ep_index, batch_l.direction, wl
        ]
        p2 = known & in_shard & ((l3_words >> bit) & 1).astype(bool)
        p3 = wild & has_port  # identity-independent: same in all shards

        # Combine probe hits across identity shards: each identity is
        # resident in exactly one shard, so the sums are 0/1.
        p1g = jax.lax.psum(p1.astype(jnp.int32), table_axis) > 0
        p2g = jax.lax.psum(p2.astype(jnp.int32), table_axis) > 0

        v = _combine(p1g, p2g, p3, proxy, batch_l.is_fragment)

        return _counts_and_telemetry(
            v, tables_l, batch_l, j, idx, p2, off, w_local,
            batch_axis, collect_telemetry,
        )

    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), t_specs),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
    )
    return jax.jit(step, in_shardings=in_shardings)


def make_partitioned_store(
    mesh: Mesh,
    table_axis: str = "table",
    hot_only: bool = False,
):
    """DeviceTableStore whose epochs PARTITION across `mesh` under
    the declarative rule table (compiler/partition.py): the
    identity-major leaves — hashed L4 entry rows, L3/L4 allow-bit
    words — each live on exactly one chip's HBM slice, small leaves
    replicate, and a delta publish scatters each payload into the
    OWNING chip's shard only (the scatter runs over the sharded
    resident pytree, so XLA routes every row to the chip that holds
    it — no full-table re-upload, no cross-chip copies of unchanged
    rows).  The rule-table digest is folded into every epoch's
    layout stamp."""
    from cilium_tpu.compiler import partition
    from cilium_tpu.engine.publish import DeviceTableStore

    return DeviceTableStore(
        shardings_fn=lambda tables: partition.table_shardings(
            mesh, tables, table_axis
        ),
        partition_digest=partition.partition_digest(
            partition.default_table_rules(table_axis)
        ),
        hot_only=hot_only,
    )


def make_partitioned_evaluator(
    mesh: Mesh,
    tables: PolicyTables,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """Routed-gather evaluator over identity-SHARDED tables.

    Where make_mesh_evaluator shards only the dense bitmap word axis
    and replicates the hashed entry plane, this evaluator consumes
    the declarative rule table (compiler/partition.py): the hashed
    L4 entry rows shard along the bucket-row axis and the L3 words
    along the identity word axis, so per-chip HBM holds ~1/num_shards
    of the identity-major bytes — the refactor that lifts the
    universe cap past one chip.

    Routing: inside shard_map each tuple's global bucket/word index
    is offset into the local shard; the shard that OWNS the row
    gathers it (everyone else contributes a masked zero) and the
    verdict columns return to the originating batch shard through
    one integer psum per probe — bit-identical to the replicated
    evaluator at every mesh size because each key lives in exactly
    one shard, so the sums are exact 0/1 combinations (the same
    argument as make_mesh_evaluator's psum lattice).

    `tables` supplies the SHAPES the partition layout is derived
    from (which leaves divide evenly, bucket/word counts); the
    returned fn(tables, batch) is jitted against those shapes.
    Requires the hashed entry pair (FleetCompiler always builds it).

    Returns fn(tables, batch) -> (Verdicts, l4_counts, l3_counts
    [, per-chip telemetry rows]) with the same output contract as
    make_mesh_evaluator."""
    from cilium_tpu.compiler.partition import (
        divisible_partition_specs,
    )
    from cilium_tpu.compiler.tables import L4H_WILD_IDX
    from cilium_tpu.engine.hashtable import fnv1a_device
    from cilium_tpu.engine.verdict import (
        MATCH_L3,
        _index_identity,
        _l4hash_probe,
        l4hash_probe_keys,
        l4hash_row_parts,
        l4hash_stash_parts,
        l4hash_value_decode,
    )

    if tables.l4_hash_rows is None:
        raise ValueError(
            "partitioned evaluator requires the hashed L4 entry "
            "tables (hand-built dense tables: use "
            "make_mesh_evaluator)"
        )
    ntp = int(mesh.shape[table_axis])
    t_specs = divisible_partition_specs(tables, ntp, table_axis)
    # static layout facts the kernel routes by (closure, not traced)
    rows_sharded = table_axis in tuple(
        ax for ax in t_specs.l4_hash_rows
    )
    l3_sharded = table_axis in tuple(
        ax for ax in t_specs.l3_allow_bits
    )
    n_rows_global = int(tables.l4_hash_rows.shape[0])

    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    l3c_spec = P(None, None, table_axis) if l3_sharded else P()
    out_specs = (v_specs, P(), l3c_spec)
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l: PolicyTables, batch_l: TupleBatch):
        # identity index from the replicated direct table (global)
        idx, known = _index_identity(tables_l, batch_l)
        proto = jnp.clip(batch_l.proto, 0, 255).astype(jnp.int32)
        dport = jnp.clip(batch_l.dport, 0, 65535).astype(jnp.int32)

        # -- routed exact probe: the bucket row lives on ONE shard ------
        from cilium_tpu.compiler.tables import l4_entry_words

        entry_words = l4_entry_words(tables_l)
        w0, w1 = l4hash_probe_keys(
            entry_words, batch_l.ep_index, batch_l.direction,
            idx.astype(jnp.uint32), dport, proto,
        )
        h = fnv1a_device(jnp.stack([w0, w1], axis=1))
        bucket = (h & jnp.uint32(n_rows_global - 1)).astype(jnp.int32)
        rows_l = tables_l.l4_hash_rows
        n_local = rows_l.shape[0]
        if rows_sharded:
            off = jax.lax.axis_index(table_axis) * n_local
            bl = bucket - off
            owns = (bl >= 0) & (bl < n_local)
            bl = jnp.clip(bl, 0, n_local - 1)
        else:
            owns = jnp.ones(bucket.shape, bool)
            bl = bucket
        # local gather: only the owning shard's hit
        found_local, val_local = l4hash_row_parts(
            rows_l[bl], w0, w1, entry_words, owns=owns
        )
        if rows_sharded:
            # return the verdict column to the originating shard:
            # the key lives in exactly one shard, so the sums are
            # exact (this psum pair is the alltoall_bytes_per_tuple
            # the bench models)
            val1 = jax.lax.psum(val_local, table_axis)
            found1 = (
                jax.lax.psum(
                    found_local.astype(jnp.int32), table_axis
                )
                > 0
            )
        else:
            val1, found1 = val_local, found_local
        # overflow stash replicates (≤64 rows): same on every shard
        s_found, s_val = l4hash_stash_parts(
            tables_l.l4_hash_stash, w0, w1, entry_words
        )
        val1 = val1 + s_val
        found1 = found1 | s_found

        # -- wildcard probe: identity-free, tiny, replicated ------------
        wild_idx = jnp.full(
            idx.shape, jnp.uint32(L4H_WILD_IDX), jnp.uint32
        )
        hit3, val3 = _l4hash_probe(
            tables_l.l4_wild_rows, tables_l.l4_wild_stash,
            batch_l.ep_index, batch_l.direction, wild_idx,
            dport, proto,
        )
        probe1 = known & found1
        probe3 = hit3
        proxy, j = l4hash_value_decode(
            tables_l, batch_l.ep_index, batch_l.direction,
            probe1, val1, hit3, val3, entry_words,
        )

        # -- routed L3 probe: the identity's bit-word has one owner -----
        word = idx >> 5
        bit = (idx & 31).astype(jnp.uint32)
        w_local = tables_l.l3_allow_bits.shape[-1]
        if l3_sharded:
            offw = jax.lax.axis_index(table_axis) * w_local
            wl = word - offw
            owns_w = (wl >= 0) & (wl < w_local)
            wl = jnp.clip(wl, 0, w_local - 1)
        else:
            offw = 0
            owns_w = jnp.ones(word.shape, bool)
            wl = word
        l3_words = tables_l.l3_allow_bits[
            batch_l.ep_index, batch_l.direction, wl
        ]
        p2_local = (
            known & owns_w & ((l3_words >> bit) & 1).astype(bool)
        )
        if l3_sharded:
            probe2 = (
                jax.lax.psum(p2_local.astype(jnp.int32), table_axis)
                > 0
            )
        else:
            probe2 = p2_local

        v = _combine(probe1, probe2, probe3, proxy,
                     batch_l.is_fragment)

        return _counts_and_telemetry(
            v, tables_l, batch_l, j, idx, p2_local, offw, w_local,
            batch_axis, collect_telemetry,
        )

    in_shardings = (
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), t_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)
    # the routing mask (n_rows_global) and shard flags are closure
    # constants derived from the build-time shapes; a retrace on
    # different shapes would route buckets with a stale mask and
    # silently mis-verdict, so refuse loudly instead
    built_geom = (
        tuple(tables.l4_hash_rows.shape),
        tuple(tables.l3_allow_bits.shape),
    )

    def run(tables_in: PolicyTables, batch: TupleBatch):
        if tables_in.l4_hash_rows is None:
            raise ValueError(
                "partitioned evaluator requires the hashed L4 "
                "entry tables"
            )
        got = (
            tuple(tables_in.l4_hash_rows.shape),
            tuple(tables_in.l3_allow_bits.shape),
        )
        if got != built_geom:
            raise ValueError(
                "partitioned evaluator was built for table geometry "
                f"{built_geom} but called with {got}; rebuild with "
                "make_partitioned_evaluator"
            )
        return jitted(tables_in, batch)

    return run


def make_partitioned_cache(
    mesh: Mesh,
    n_rows_local: int = 1 << 10,
    entries: int = 8,
    batch_axis: str = "batch",
    table_axis: str = "table",
):
    """VerdictCache (engine/memo.py) laid out for the partitioned
    memo evaluator: rows [dp, tp, n_rows_local + 1, 5 * entries]
    sharded P(batch, table) — each chip owns its batch row's slice of
    the bucket-row space (co-located with the table shard that owns
    the same hashed rows), plus its private scratch row.  Batch rows
    warm independent copies (their tuple streams differ), so capacity
    scales with the mesh in both axes."""
    from cilium_tpu.engine.memo import (
        CACHE_WORDS,
        EMPTY,
        VerdictCache,
    )

    if n_rows_local & (n_rows_local - 1):
        raise ValueError(
            f"cache rows per shard must be a power of two: "
            f"{n_rows_local}"
        )
    dp = int(mesh.shape[batch_axis])
    tp = int(mesh.shape[table_axis])

    def factory():
        import numpy as np

        rows = np.full(
            (dp, tp, n_rows_local + 1, CACHE_WORDS * entries + 1),
            EMPTY, np.uint32,
        )
        # trailing hit-rank word (engine/memo.py layout): zeroed;
        # the partitioned kernel keeps the rotation eviction today
        # (rank maintenance is single-chip), so the word stays cold
        rows[..., -1] = 0
        return rows

    sharding = NamedSharding(mesh, P(batch_axis, table_axis))
    return VerdictCache(rows_factory=factory, sharding=sharding)


def make_partitioned_memo_evaluator(
    mesh: Mesh,
    tables: PolicyTables,
    cache_rows,
    rep_cap: int,
    miss_cap: int = None,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """make_partitioned_evaluator with the verdict-memoization plane
    in front (engine/memo.py): each batch shard dedups its own tuple
    stream in-jit, the representatives probe a cache whose bucket
    rows shard along the table axis exactly like l4_hash_rows (the
    owning chip gathers, one psum pair returns the hit + value
    words), and only the missed representatives run the routed
    lattice gathers.  Cache inserts land on the owning chip only.

    `cache_rows` fixes the cache geometry (a make_partitioned_cache
    rows array: [dp, tp, R_local + 1, 5e]); `rep_cap`/`miss_cap` are
    the per-batch-shard compaction capacities.  All tp chips of a
    mesh row compute identical dedup/probe decisions from identical
    replicated inputs, so the routing stays SPMD-uniform.

    Returns fn(tables, batch, cache_rows) -> (Verdicts, l4_counts,
    l3_counts, cache_rows', hit bool [B], stats u32 [STATS]
    [, per-chip telemetry rows]) — same counter/telemetry contract
    as make_partitioned_evaluator; when stats[STAT_OVERFLOW] != 0
    every output except cache_rows' (returned unchanged) is
    unspecified and the caller must re-dispatch through the uncached
    evaluator."""
    from cilium_tpu.compiler.partition import (
        divisible_partition_specs,
    )
    from cilium_tpu.compiler.tables import L4H_WILD_IDX
    from cilium_tpu.engine.hashtable import fnv1a_device
    from cilium_tpu.engine import memo as vm
    from cilium_tpu.engine.verdict import (
        _index_identity,
        _l4hash_probe,
        l4hash_probe_keys,
        l4hash_row_parts,
        l4hash_stash_parts,
        l4hash_value_decode,
    )

    if tables.l4_hash_rows is None:
        raise ValueError(
            "partitioned memo evaluator requires the hashed L4 "
            "entry tables"
        )
    if miss_cap is None:
        miss_cap = rep_cap
    ntp = int(mesh.shape[table_axis])
    ndp = int(mesh.shape[batch_axis])
    t_specs = divisible_partition_specs(tables, ntp, table_axis)
    rows_sharded = table_axis in tuple(
        ax for ax in t_specs.l4_hash_rows
    )
    l3_sharded = table_axis in tuple(
        ax for ax in t_specs.l3_allow_bits
    )
    n_rows_global = int(tables.l4_hash_rows.shape[0])
    cshape = tuple(cache_rows.shape)
    if cshape[0] != ndp or cshape[1] != ntp:
        raise ValueError(
            f"cache rows {cshape} do not match the mesh "
            f"({ndp}, {ntp})"
        )
    c_local = int(cshape[2]) - 1  # per-chip bucket rows (last=scratch)
    c_global = c_local * ntp
    entries = int(cshape[3]) // vm.CACHE_WORDS

    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    l3c_spec = P(None, None, table_axis) if l3_sharded else P()
    cache_spec = P(batch_axis, table_axis)
    out_specs = (
        v_specs, P(), l3c_spec, cache_spec, P(batch_axis), P(),
    )
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs, cache_spec),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l: PolicyTables, batch_l: TupleBatch, cache_l):
        cache2 = cache_l[0, 0]  # [R_local + 1, 5e]
        my_col = jax.lax.axis_index(table_axis)
        idx, known = _index_identity(tables_l, batch_l)
        proto = jnp.clip(batch_l.proto, 0, 255).astype(jnp.int32)
        dport = jnp.clip(batch_l.dport, 0, 65535).astype(jnp.int32)

        # -- Level A: per-batch-shard dedup (identical on every
        # table chip of the row: same replicated inputs) --------------
        k0, k1, k2 = vm.memo_key_words(
            idx, known, None, batch_l.ep_index, batch_l.direction,
            dport, proto,
        )
        g = vm.dedup_groups(k0, k1, k2, rep_cap)
        rep_orig = g["rep_orig"]
        r = rep_orig[:rep_cap]
        rk0, rk1, rk2 = k0[r], k1[r], k2[r]

        # -- Level B: routed cache probe (bucket rows shard along
        # the table axis like l4_hash_rows) ---------------------------
        h = fnv1a_device(jnp.stack([rk0, rk1, rk2], axis=1))
        bucket = (h & jnp.uint32(c_global - 1)).astype(jnp.int32)
        if ntp > 1:
            pc = bucket // c_local
            owns_c = pc == my_col
            cl = jnp.clip(bucket - pc * c_local, 0, c_local - 1)
        else:
            pc = jnp.zeros(bucket.shape, jnp.int32)
            owns_c = jnp.ones(bucket.shape, bool)
            cl = bucket
        crow = cache2[cl]  # [U, 5e] local gather
        e = entries
        lane_hit = (
            (crow[:, :e] == rk0[:, None])
            & (crow[:, e : 2 * e] == rk1[:, None])
            & (crow[:, 2 * e : 3 * e] == rk2[:, None])
            & owns_c[:, None]
        )
        hit_local = jnp.any(lane_hit, axis=1)
        cv0_l = jnp.sum(
            jnp.where(lane_hit, crow[:, 3 * e : 4 * e], 0),
            axis=1, dtype=jnp.uint32,
        )
        cv1_l = jnp.sum(
            jnp.where(lane_hit, crow[:, 4 * e : 5 * e], 0),
            axis=1, dtype=jnp.uint32,
        )
        if ntp > 1:
            hit = (
                jax.lax.psum(
                    hit_local.astype(jnp.int32), table_axis
                )
                > 0
            )
            cv0 = jax.lax.psum(cv0_l, table_axis)
            cv1 = jax.lax.psum(cv1_l, table_axis)
        else:
            hit, cv0, cv1 = hit_local, cv0_l, cv1_l
        hit = hit & g["rep_valid"]
        # owner-local insert-lane choice (only the owner's is used);
        # bucket_insert_lanes guarantees distinct (bucket, lane)
        # targets per batch — the duplicate-index scatter atomicity
        # argument lives in ONE place (engine/memo.py)
        ins_lane, ins_ok = vm.bucket_insert_lanes(
            (crow[:, :e] == vm.EMPTY) & owns_c[:, None], bucket, e
        )

        # -- miss compaction + routed lattice on missed reps ----------
        miss = g["rep_valid"] & ~hit
        n_miss = jnp.sum(miss.astype(jnp.int32))
        (miss_pos,) = jnp.nonzero(
            miss, size=miss_cap, fill_value=rep_cap
        )
        m_orig = rep_orig[miss_pos]
        m_idx = idx[m_orig]
        m_known = known[m_orig]
        m_ep = batch_l.ep_index[m_orig]
        m_dir = batch_l.direction[m_orig]
        m_dport = dport[m_orig]
        m_proto = proto[m_orig]

        from cilium_tpu.compiler.tables import l4_entry_words

        entry_words = l4_entry_words(tables_l)
        w0, w1 = l4hash_probe_keys(
            entry_words, m_ep, m_dir, m_idx.astype(jnp.uint32),
            m_dport, m_proto,
        )
        hh = fnv1a_device(jnp.stack([w0, w1], axis=1))
        hb = (hh & jnp.uint32(n_rows_global - 1)).astype(jnp.int32)
        rows_l = tables_l.l4_hash_rows
        n_local = rows_l.shape[0]
        if rows_sharded:
            off = jax.lax.axis_index(table_axis) * n_local
            bl = hb - off
            owns = (bl >= 0) & (bl < n_local)
            bl = jnp.clip(bl, 0, n_local - 1)
        else:
            owns = jnp.ones(hb.shape, bool)
            bl = hb
        found_local, val_local = l4hash_row_parts(
            rows_l[bl], w0, w1, entry_words, owns=owns
        )
        if rows_sharded:
            val1 = jax.lax.psum(val_local, table_axis)
            found1 = (
                jax.lax.psum(
                    found_local.astype(jnp.int32), table_axis
                )
                > 0
            )
        else:
            val1, found1 = val_local, found_local
        s_found, s_val = l4hash_stash_parts(
            tables_l.l4_hash_stash, w0, w1, entry_words
        )
        val1 = val1 + s_val
        found1 = found1 | s_found
        wild_idx = jnp.full(
            m_idx.shape, jnp.uint32(L4H_WILD_IDX), jnp.uint32
        )
        hit3, val3 = _l4hash_probe(
            tables_l.l4_wild_rows, tables_l.l4_wild_stash,
            m_ep, m_dir, wild_idx, m_dport, m_proto,
        )
        p1m = m_known & found1
        p3m = hit3
        m_proxy, m_j = l4hash_value_decode(
            tables_l, m_ep, m_dir, p1m, val1, hit3, val3,
            entry_words,
        )
        # routed L3 probe for the missed reps
        m_word = m_idx >> 5
        m_bit = (m_idx & 31).astype(jnp.uint32)
        w_local = tables_l.l3_allow_bits.shape[-1]
        if l3_sharded:
            offw = jax.lax.axis_index(table_axis) * w_local
            wl = m_word - offw
            owns_w = (wl >= 0) & (wl < w_local)
            wl = jnp.clip(wl, 0, w_local - 1)
        else:
            offw = 0
            owns_w = jnp.ones(m_word.shape, bool)
            wl = m_word
        l3_words = tables_l.l3_allow_bits[m_ep, m_dir, wl]
        p2m_local = (
            m_known & owns_w & ((l3_words >> m_bit) & 1).astype(bool)
        )
        if l3_sharded:
            p2m = (
                jax.lax.psum(
                    p2m_local.astype(jnp.int32), table_axis
                )
                > 0
            )
        else:
            p2m = p2m_local
        mv0, mv1 = vm.pack_value_words(p1m, p2m, p3m, m_proxy, m_j)

        # -- rep values -> per-tuple scatter-back (shared helper:
        # the bit-identity index arithmetic lives in engine/memo.py)
        bsz = k0.shape[0]
        v0, v1, tuple_hit = vm.scatter_back(
            g, rep_cap, hit, cv0, cv1, miss_pos, mv0, mv1
        )

        overflow = g["overflow"] + jnp.maximum(n_miss - miss_cap, 0)
        ok = overflow == 0
        # -- owner-local insert of missed reps ------------------------
        do_ins = (jnp.arange(miss_cap) < n_miss) & ok
        mp = miss_pos
        pc_p = vm.pad_rep(pc, mp)
        cl_p = vm.pad_rep(cl, mp)
        lane_p = vm.pad_rep(ins_lane, mp)
        ok_p = vm.pad_rep(ins_ok, mp)
        own_ins = do_ins & ok_p & (pc_p == my_col)
        k0_p = vm.pad_rep(rk0, mp)
        k1_p = vm.pad_rep(rk1, mp)
        k2_p = vm.pad_rep(rk2, mp)
        ins_row = jnp.where(own_ins, cl_p, c_local)
        rows_idx = jnp.concatenate([ins_row] * vm.CACHE_WORDS)
        lanes_idx = jnp.concatenate(
            [lane_p + c * e for c in range(vm.CACHE_WORDS)]
        )
        vals = jnp.concatenate([k0_p, k1_p, k2_p, mv0, mv1])
        cache_out = cache2.at[rows_idx, lanes_idx].set(vals)
        cache_out = jnp.where(ok, cache_out, cache2)[None, None]

        # -- combine + the shared counter/telemetry epilogue ----------
        probe1, probe2, probe3, t_proxy, t_j = vm.unpack_value_words(
            v0, v1
        )
        v = _combine(
            probe1, probe2, probe3, t_proxy, batch_l.is_fragment
        )
        # p2_local for the shard-local L3 counter: each identity
        # word has ONE owner, so the global probe2 restricted to the
        # owned word range IS the local hit (no gather needed)
        t_word = idx >> 5
        if l3_sharded:
            t_offw = jax.lax.axis_index(table_axis) * w_local
            t_owns = ((t_word - t_offw) >= 0) & (
                (t_word - t_offw) < w_local
            )
        else:
            t_offw = 0
            t_owns = jnp.ones(t_word.shape, bool)
        p2_local_t = probe2 & t_owns
        stats = jnp.stack(
            [
                g["n_unique"].astype(jnp.uint32),
                jnp.sum(tuple_hit, dtype=jnp.uint32),
                jnp.sum((do_ins & ok_p).astype(jnp.uint32)),
                overflow.astype(jnp.uint32),
                jnp.uint32(bsz),
            ]
        )
        stats = jax.lax.psum(stats, batch_axis)
        epilogue = _counts_and_telemetry(
            v, tables_l, batch_l, t_j, idx, p2_local_t, t_offw,
            w_local, batch_axis, collect_telemetry,
        )
        if collect_telemetry:
            v, l4c, l3c, trow = epilogue
            return (
                v, l4c, l3c, cache_out, tuple_hit, stats, trow,
            )
        v, l4c, l3c = epilogue
        return v, l4c, l3c, cache_out, tuple_hit, stats

    in_shardings = (
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), t_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
        NamedSharding(mesh, cache_spec),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)
    built_geom = (
        tuple(tables.l4_hash_rows.shape),
        tuple(tables.l3_allow_bits.shape),
        cshape,
    )

    def run(tables_in: PolicyTables, batch: TupleBatch, cache_in):
        got = (
            tuple(tables_in.l4_hash_rows.shape),
            tuple(tables_in.l3_allow_bits.shape),
            tuple(cache_in.shape),
        )
        if got != built_geom:
            raise ValueError(
                "partitioned memo evaluator was built for geometry "
                f"{built_geom} but called with {got}; rebuild with "
                "make_partitioned_memo_evaluator"
            )
        return jitted(tables_in, batch, cache_in)

    return run


def failover_lattice_probes(
    tables_l: PolicyTables,
    ep_index,
    direction,
    dport,
    proto,
    idx,
    known,
    alive_row,
    my_col,
    ntp: int,
    rows_sharded: bool,
    l3_sharded: bool,
    n_rows_global: int,
    n_row_shard: int,
    wn: int,
    table_axis: str,
):
    """The replica-aware routed 3-probe lattice — the kernel body
    shared by make_failover_evaluator (post-ipcache TupleBatch form)
    and the fused datapath evaluator (engine/datapath_mesh.py, which
    derives `idx`/`known` from the routed ipcache lookup instead of
    id_direct).  Consumes the N+1 AUGMENTED l4_hash_rows /
    l3_allow_bits planes plus the mesh row's `alive_row` health
    vector; a dead primary's bucket/word routes to the backup owner
    next shard over.

    Returns a dict: probe1/probe2/probe3/proxy/j (the _combine
    inputs + counter slot), p2_local (this chip's L3 hit — feeds the
    shard-local counter scatter), wp/apw (the L3 word's primary
    owner + its liveness; None on a replicated L3 plane) and
    `replica` (bool [B]: the tuple was served from a backup
    region)."""
    from cilium_tpu.compiler import partition
    from cilium_tpu.compiler.tables import L4H_WILD_IDX
    from cilium_tpu.engine.hashtable import fnv1a_device
    from cilium_tpu.engine.verdict import (
        _l4hash_probe,
        l4hash_probe_keys,
        l4hash_row_parts,
        l4hash_stash_parts,
        l4hash_value_decode,
    )

    # -- routed exact probe with replica fallback (layout-generic:
    # the 3-word and the sub-word compact entry forms share one
    # compare/psum body — the stash width is the marker) ------------
    from cilium_tpu.compiler.tables import l4_entry_words as _l4ew

    entry_words = _l4ew(tables_l)
    w0, w1 = l4hash_probe_keys(
        entry_words, ep_index, direction, idx.astype(jnp.uint32),
        dport, proto,
    )
    h = fnv1a_device(jnp.stack([w0, w1], axis=1))
    bucket = (h & jnp.uint32(n_rows_global - 1)).astype(jnp.int32)
    rows_l = tables_l.l4_hash_rows
    replica_exact = jnp.zeros(bucket.shape, bool)
    if rows_sharded:
        n = n_row_shard
        p = bucket // n
        ap = alive_row[p]
        owner = jnp.where(
            ap, p, (p + partition.REPLICA_BACKUP_OFFSET) % ntp
        )
        owns = owner == my_col
        # serving chip's local row: primary region [0, n) when
        # the owner IS the primary, backup region [n, 2n) when
        # the next shard over serves its neighbour's copy
        bl = (bucket - p * n) + jnp.where(ap, 0, n)
        bl = jnp.clip(bl, 0, 2 * n - 1)
        replica_exact = owns & ~ap
    else:
        owns = jnp.ones(bucket.shape, bool)
        bl = bucket
    row = rows_l[bl]
    found_local, val_local = l4hash_row_parts(
        row, w0, w1, entry_words, owns=owns
    )
    if rows_sharded:
        val1 = jax.lax.psum(val_local, table_axis)
        found1 = (
            jax.lax.psum(found_local.astype(jnp.int32), table_axis)
            > 0
        )
    else:
        val1, found1 = val_local, found_local
    s_found, s_val = l4hash_stash_parts(
        tables_l.l4_hash_stash, w0, w1, entry_words
    )
    val1 = val1 + s_val
    found1 = found1 | s_found

    wild_idx = jnp.full(
        idx.shape, jnp.uint32(L4H_WILD_IDX), jnp.uint32
    )
    hit3, val3 = _l4hash_probe(
        tables_l.l4_wild_rows, tables_l.l4_wild_stash,
        ep_index, direction, wild_idx, dport, proto,
    )
    probe1 = known & found1
    probe3 = hit3
    proxy, j = l4hash_value_decode(
        tables_l, ep_index, direction, probe1, val1, hit3, val3,
        entry_words,
    )

    # -- routed L3 probe with replica fallback ----------------------
    word = idx >> 5
    bit = (idx & 31).astype(jnp.uint32)
    replica_l3 = jnp.zeros(word.shape, bool)
    wp = apw = None
    if l3_sharded:
        wp = word // wn
        apw = alive_row[wp]
        owner_w = jnp.where(
            apw, wp, (wp + partition.REPLICA_BACKUP_OFFSET) % ntp
        )
        owns_w = owner_w == my_col
        wl = (word - wp * wn) + jnp.where(apw, 0, wn)
        wl = jnp.clip(wl, 0, 2 * wn - 1)
        replica_l3 = owns_w & ~apw
    else:
        owns_w = jnp.ones(word.shape, bool)
        wl = word
    l3_words = tables_l.l3_allow_bits[ep_index, direction, wl]
    p2_local = known & owns_w & ((l3_words >> bit) & 1).astype(bool)
    if l3_sharded:
        probe2 = (
            jax.lax.psum(p2_local.astype(jnp.int32), table_axis) > 0
        )
    else:
        probe2 = p2_local
    return {
        "probe1": probe1,
        "probe2": probe2,
        "probe3": probe3,
        "proxy": proxy,
        "j": j,
        "p2_local": p2_local,
        "wp": wp,
        "apw": apw,
        "replica": replica_exact | replica_l3,
    }


def failover_counts(
    tables_l: PolicyTables,
    ep_index,
    direction,
    match_kind,
    j,
    idx,
    p2_local,
    valid_l,
    l3_sharded: bool,
    wn: int,
    wp,
    apw,
    n_ids: int,
    batch_axis: str,
):
    """Valid-masked counter epilogue of the failover kernels: L4-slot
    hits from the globally-combined verdict columns; L3 hits
    shard-LOCAL at the augmented local identity index (primary region
    [0, g), backup region [g, 2g) — the same routing as the word
    gather), folded back to the global [E, 2, N] surface host-side
    by fold_l3_aug.  Padding positions (valid=False) are excluded
    everywhere — a re-split batch counts exactly its real tuples."""
    e_count, _, kg = tables_l.l4_meta.shape
    hit_l4 = (
        (match_kind == MATCH_L4) | (match_kind == MATCH_L4_WILD)
    ) & valid_l
    l4_counts = jnp.zeros((e_count, 2, kg), jnp.uint32).at[
        ep_index, direction, j
    ].add(hit_l4.astype(jnp.uint32))
    l4_counts = jax.lax.psum(l4_counts, batch_axis)
    l3_hit_here = p2_local & (match_kind == MATCH_L3) & valid_l
    if l3_sharded:
        # shard-LOCAL counters at the augmented local identity
        # index: each hit lands exactly once on its serving chip, so
        # the global [E, 2, N] tensor is never materialized on
        # device (it would be 32x the bit plane, replicated per
        # chip — defeating the HBM sharding this plane exists for).
        g = wn * 32
        lid = jnp.clip(idx - wp * g, 0, g - 1) + jnp.where(
            apw, 0, g
        )
        l3_counts = jnp.zeros(
            (e_count, 2, 2 * g), jnp.uint32
        ).at[
            ep_index, direction, lid
        ].add(l3_hit_here.astype(jnp.uint32))
    else:
        # replicated fallback plane: p2_local is IDENTICAL on
        # every table chip — count at the global index and take
        # one copy (a table-axis psum would inflate every hit
        # by tp)
        l3_counts = jnp.zeros(
            (e_count, 2, n_ids), jnp.uint32
        ).at[
            ep_index, direction, jnp.clip(idx, 0, n_ids - 1),
        ].add(l3_hit_here.astype(jnp.uint32))
    l3_counts = jax.lax.psum(l3_counts, batch_axis)
    return l4_counts, l3_counts


def fold_l3_aug(l3_aug, ntp: int):
    """[E, 2, ntp*2g] chip-major (primary region then backup region
    per chip) → global [E, 2, N]: slice p reassembles from chip p's
    primary region + chip (p+offset)'s backup region.  Rows whose
    owner moved were counted in the backup region, so summing both
    regions is exact whatever mix each mesh row's survivor set
    routed."""
    import numpy as np

    from cilium_tpu.compiler import partition

    a = np.asarray(l3_aug)
    g = a.shape[-1] // (2 * ntp)
    blocks = a.reshape(a.shape[0], a.shape[1], ntp, 2 * g)
    back = np.roll(
        blocks[..., g:],
        -partition.REPLICA_BACKUP_OFFSET,
        axis=2,
    )
    return np.ascontiguousarray(
        (blocks[..., :g] + back).reshape(
            a.shape[0], a.shape[1], ntp * g
        )
    )


def make_replica_store(
    mesh: Mesh,
    table_axis: str = "table",
    hot_only: bool = False,
):
    """make_partitioned_store under the N+1 replica placement rule:
    every published epoch carries the AUGMENTED layout
    (compiler.partition.replicate_table_leaves — each sharded
    replica-rule leaf's shard also holds its left neighbour's slice),
    and every delta publish scatters each changed row into BOTH its
    primary and backup positions (partition.replica_delta), so the
    two copies stay bit-identical through churn.  The replica
    placement digest is folded into the epoch layout stamp — a delta
    recorded under plain sharding can never scatter into a replica
    epoch, and vice versa."""
    from cilium_tpu.compiler import partition
    from cilium_tpu.engine.publish import DeviceTableStore

    ntp = int(mesh.shape[table_axis])
    return DeviceTableStore(
        shardings_fn=lambda aug: partition.table_shardings(
            mesh, aug, table_axis
        ),
        partition_digest=partition.replica_partition_digest(
            table_axis, ntp=ntp
        ),
        transform_fn=lambda t: partition.replicate_table_leaves(
            t, ntp, table_axis
        ),
        delta_transform_fn=lambda d, pre: partition.replica_delta(
            d, pre, ntp, table_axis
        ),
        hot_only=hot_only,
    )


def make_failover_evaluator(
    mesh: Mesh,
    tables: PolicyTables,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """Replica-aware routed-gather evaluator — the per-chip failure
    domain's kernel half.  Consumes the N+1 AUGMENTED tables
    (compiler.partition.replicate_table_leaves: each sharded leaf's
    shard also carries a copy of its left neighbour's slice) plus two
    routing inputs:

      * `alive` bool [dp, tp] (replicated) — per-(mesh row, table
        column) chip health from the ChipBreakerBank.  A tuple whose
        bucket/word's primary owner is dead routes to the BACKUP
        owner (next shard over), which gathers from its backup
        region — the gathered rows are bit-identical copies, so
        verdicts never depend on the dead chip's table slice.
      * `valid` bool [B] (batch-sharded) — real-tuple mask from the
        shard router's batch re-split: positions padding a dead
        row's shard are excluded from counters and telemetry, so the
        full observable surface equals the healthy mesh's.

    Returns fn(tables_aug, batch, alive, valid) ->
    (Verdicts, l4_counts [E,2,Kg] replicated, l3_counts [E,2,N]
    replicated (N = the GLOBAL identity pad — unlike the partitioned
    evaluator's shard-local slices, so comparators need no reassembly
    under a changing survivor set), replica_hits u32 scalar (valid
    tuples served from a backup region — the replica_gather_total
    feed) [, per-chip telemetry rows [dp, 2, TELEM_COLS]]).

    Verdict columns for INVALID positions are unspecified when their
    row hosts a dead chip (the router discards them); everything the
    valid mask covers is bit-identical to the healthy mesh and the
    host oracle — the acceptance contract of the per-chip failover
    plane."""
    from cilium_tpu.compiler import partition
    from cilium_tpu.engine.verdict import (
        _index_identity,
        telemetry_masks,
    )

    if tables.l4_hash_rows is None:
        raise ValueError(
            "failover evaluator requires the hashed L4 entry tables"
        )
    ntp = int(mesh.shape[table_axis])
    rep_axes = partition.replica_axes(tables, ntp, table_axis)
    rows_sharded = "l4_hash_rows" in rep_axes
    l3_sharded = "l3_allow_bits" in rep_axes
    # geometry of the UN-augmented layout (hash masks / owner maps
    # are functions of the original shapes; the augmentation only
    # doubles the resident axis)
    n_rows_global = int(tables.l4_hash_rows.shape[0])
    n_row_shard = n_rows_global // ntp if rows_sharded else 0
    w_global = int(tables.l3_allow_bits.shape[-1])
    wn = w_global // ntp if l3_sharded else 0
    n_ids = w_global * 32

    t_specs = partition.divisible_partition_specs(
        tables, ntp, table_axis
    )
    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    # a sharded L3 plane keeps its counters shard-local too — the
    # stitched last axis is chip-major [ntp, 2*wn*32] regions the
    # host wrapper folds back into the global counter
    l3_spec = P(None, None, table_axis) if l3_sharded else P()
    out_specs = (v_specs, P(), l3_spec, P())
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs, P(), P(batch_axis)),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l: PolicyTables, batch_l: TupleBatch,
             alive_l, valid_l):
        idx, known = _index_identity(tables_l, batch_l)
        proto = jnp.clip(batch_l.proto, 0, 255).astype(jnp.int32)
        dport = jnp.clip(batch_l.dport, 0, 65535).astype(jnp.int32)
        # this chip's coordinates + its mesh row's health vector
        # (tuples on batch row r only ever touch row r's chips — the
        # table-axis psum reduces within the row subgroup)
        alive_row = alive_l[jax.lax.axis_index(batch_axis)]
        my_col = jax.lax.axis_index(table_axis)

        lat = failover_lattice_probes(
            tables_l, batch_l.ep_index, batch_l.direction, dport,
            proto, idx, known, alive_row, my_col, ntp,
            rows_sharded, l3_sharded, n_rows_global, n_row_shard,
            wn, table_axis,
        )
        v = _combine(
            lat["probe1"], lat["probe2"], lat["probe3"],
            lat["proxy"], batch_l.is_fragment,
        )
        l4_counts, l3_counts = failover_counts(
            tables_l, batch_l.ep_index, batch_l.direction,
            v.match_kind, lat["j"], idx, lat["p2_local"], valid_l,
            l3_sharded, wn, lat["wp"], lat["apw"], n_ids,
            batch_axis,
        )
        served_backup = (lat["replica"] & valid_l).astype(jnp.uint32)
        replica_hits = jax.lax.psum(
            jax.lax.psum(jnp.sum(served_backup), batch_axis),
            table_axis,
        )
        out = (v, l4_counts, l3_counts, replica_hits)
        if not collect_telemetry:
            return out
        zeros = jnp.zeros(v.allowed.shape, jnp.int32)
        masks = telemetry_masks(
            zeros, zeros, v.match_kind, v.allowed, zeros,
            v.proxy_port, zeros, zeros,
        )
        ingress = (batch_l.direction == 0) & valid_l
        row_in = jnp.stack(
            [
                jnp.sum(m & ingress, dtype=jnp.uint32)
                for m in masks
            ]
        )
        col_total = jnp.stack(
            [
                jnp.sum(m & valid_l, dtype=jnp.uint32)
                for m in masks
            ]
        )
        trow = jnp.stack([row_in, col_total - row_in])
        return out + (trow[None],)

    in_shardings = (
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), t_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
        NamedSharding(mesh, P()),
        NamedSharding(mesh, P(batch_axis)),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)
    built_geom = (
        tuple(tables.l4_hash_rows.shape),
        tuple(tables.l3_allow_bits.shape),
    )
    aug_rows = (
        n_rows_global * 2 if rows_sharded else n_rows_global
    )
    aug_words = w_global * 2 if l3_sharded else w_global

    def run(tables_aug: PolicyTables, batch: TupleBatch, alive,
            valid):
        if tables_aug.l4_hash_rows is None:
            raise ValueError(
                "failover evaluator requires the hashed L4 entry "
                "tables"
            )
        got = (
            int(tables_aug.l4_hash_rows.shape[0]),
            int(tables_aug.l3_allow_bits.shape[-1]),
        )
        if got != (aug_rows, aug_words):
            raise ValueError(
                "failover evaluator was built for augmented table "
                f"geometry {(aug_rows, aug_words)} (from un-augmented "
                f"{built_geom}) but called with {got}; rebuild with "
                "make_failover_evaluator"
            )
        out = jitted(tables_aug, batch, alive, valid)
        if l3_sharded:
            out = (out[0], out[1], fold_l3_aug(out[2], ntp)) + tuple(
                out[3:]
            )
        return out

    run.replica_axes = rep_axes
    return run


def make_failover_memo_evaluator(
    mesh: Mesh,
    tables: PolicyTables,
    cache_rows,
    rep_cap: int,
    miss_cap: int = None,
    batch_axis: str = "batch",
    table_axis: str = "table",
    collect_telemetry: bool = False,
):
    """make_failover_evaluator with the verdict-memoization plane in
    front — the serving-plane memo carried onto the PRODUCTION
    router path (ChipFailoverRouter.dispatch).  Each batch shard
    dedups its tuple stream in-jit; representatives probe a cache
    whose bucket rows shard along the table axis (the owning chip
    gathers, one psum pair returns hit + value words) with the
    ALIVE mask folded into ownership — a dead chip's cache slice
    contributes nothing (those keys just miss) and its inserts
    route to the scratch row, so cache routing can never depend on
    a dead chip; only the MISSED representatives run the
    replica-aware routed lattice (failover_lattice_probes).

    Returns run(tables_aug, batch, alive, valid, cache_rows) ->
    (Verdicts, l4_counts, l3_counts GLOBAL, replica_hits, cache',
    hit bool [B], stats u32 [STATS] [, per-chip telemetry rows]) —
    the failover evaluator's counter/telemetry contract plus the
    memo plane's.  On stats[STAT_OVERFLOW] != 0 every output except
    cache' (returned unchanged) is unspecified: the caller
    re-dispatches through the uncached failover evaluator.
    replica_hits counts backup-region gathers on the missed-rep
    lattice path (cache hits gather no table rows at all)."""
    from cilium_tpu.compiler import partition
    from cilium_tpu.engine import memo as vm
    from cilium_tpu.engine.hashtable import fnv1a_device
    from cilium_tpu.engine.verdict import (
        _index_identity,
        telemetry_masks,
    )

    if tables.l4_hash_rows is None:
        raise ValueError(
            "failover memo evaluator requires the hashed L4 entry "
            "tables"
        )
    if miss_cap is None:
        miss_cap = rep_cap
    ntp = int(mesh.shape[table_axis])
    ndp = int(mesh.shape[batch_axis])
    rep_axes = partition.replica_axes(tables, ntp, table_axis)
    rows_sharded = "l4_hash_rows" in rep_axes
    l3_sharded = "l3_allow_bits" in rep_axes
    n_rows_global = int(tables.l4_hash_rows.shape[0])
    n_row_shard = n_rows_global // ntp if rows_sharded else 0
    w_global = int(tables.l3_allow_bits.shape[-1])
    wn = w_global // ntp if l3_sharded else 0
    n_ids = w_global * 32
    t_specs = partition.divisible_partition_specs(
        tables, ntp, table_axis
    )
    cshape = tuple(cache_rows.shape)
    if cshape[0] != ndp or cshape[1] != ntp:
        raise ValueError(
            f"cache rows {cshape} do not match the mesh "
            f"({ndp}, {ntp})"
        )
    c_local = int(cshape[2]) - 1
    c_global = c_local * ntp
    entries = int(cshape[3]) // vm.CACHE_WORDS

    b_specs = batch_specs(batch_axis)
    v_specs = Verdicts(
        allowed=P(batch_axis),
        proxy_port=P(batch_axis),
        match_kind=P(batch_axis),
    )
    l3_spec = P(None, None, table_axis) if l3_sharded else P()
    cache_spec = P(batch_axis, table_axis)
    out_specs = (
        v_specs, P(), l3_spec, P(), cache_spec, P(batch_axis), P(),
    )
    if collect_telemetry:
        out_specs = out_specs + (P(batch_axis, None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(t_specs, b_specs, P(), P(batch_axis), cache_spec),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(tables_l, batch_l, alive_l, valid_l, cache_l):
        cache2 = cache_l[0, 0]  # [R_local + 1, 5e]
        alive_row = alive_l[jax.lax.axis_index(batch_axis)]
        my_col = jax.lax.axis_index(table_axis)
        idx, known = _index_identity(tables_l, batch_l)
        proto = jnp.clip(batch_l.proto, 0, 255).astype(jnp.int32)
        dport = jnp.clip(batch_l.dport, 0, 65535).astype(jnp.int32)

        # -- Level A: per-batch-shard dedup ---------------------------
        k0, k1, k2 = vm.memo_key_words(
            idx, known, None, batch_l.ep_index, batch_l.direction,
            dport, proto,
        )
        g = vm.dedup_groups(k0, k1, k2, rep_cap)
        rep_orig = g["rep_orig"]
        r = rep_orig[:rep_cap]
        rk0, rk1, rk2 = k0[r], k1[r], k2[r]

        # -- Level B: alive-masked routed cache probe -----------------
        h = fnv1a_device(jnp.stack([rk0, rk1, rk2], axis=1))
        bucket = (h & jnp.uint32(c_global - 1)).astype(jnp.int32)
        if ntp > 1:
            pc = bucket // c_local
            owns_c = (pc == my_col) & alive_row[pc]
            cl = jnp.clip(bucket - pc * c_local, 0, c_local - 1)
        else:
            pc = jnp.zeros(bucket.shape, jnp.int32)
            owns_c = jnp.ones(bucket.shape, bool) & alive_row[0]
            cl = bucket
        crow = cache2[cl]
        e = entries
        lane_hit = (
            (crow[:, :e] == rk0[:, None])
            & (crow[:, e : 2 * e] == rk1[:, None])
            & (crow[:, 2 * e : 3 * e] == rk2[:, None])
            & owns_c[:, None]
        )
        hit_local = jnp.any(lane_hit, axis=1)
        cv0_l = jnp.sum(
            jnp.where(lane_hit, crow[:, 3 * e : 4 * e], 0),
            axis=1, dtype=jnp.uint32,
        )
        cv1_l = jnp.sum(
            jnp.where(lane_hit, crow[:, 4 * e : 5 * e], 0),
            axis=1, dtype=jnp.uint32,
        )
        if ntp > 1:
            hit = (
                jax.lax.psum(
                    hit_local.astype(jnp.int32), table_axis
                )
                > 0
            )
            cv0 = jax.lax.psum(cv0_l, table_axis)
            cv1 = jax.lax.psum(cv1_l, table_axis)
        else:
            hit, cv0, cv1 = hit_local, cv0_l, cv1_l
        hit = hit & g["rep_valid"]
        ins_lane, ins_ok = vm.bucket_insert_lanes(
            (crow[:, :e] == vm.EMPTY) & owns_c[:, None], bucket, e
        )

        # -- miss compaction + replica-aware routed lattice -----------
        miss = g["rep_valid"] & ~hit
        n_miss = jnp.sum(miss.astype(jnp.int32))
        (miss_pos,) = jnp.nonzero(
            miss, size=miss_cap, fill_value=rep_cap
        )
        m_orig = rep_orig[miss_pos]
        lat = failover_lattice_probes(
            tables_l, batch_l.ep_index[m_orig],
            batch_l.direction[m_orig], dport[m_orig], proto[m_orig],
            idx[m_orig], known[m_orig], alive_row, my_col, ntp,
            rows_sharded, l3_sharded, n_rows_global, n_row_shard,
            wn, table_axis,
        )
        mv0, mv1 = vm.pack_value_words(
            lat["probe1"], lat["probe2"], lat["probe3"],
            lat["proxy"], lat["j"],
        )

        v0, v1, tuple_hit = vm.scatter_back(
            g, rep_cap, hit, cv0, cv1, miss_pos, mv0, mv1
        )
        overflow = g["overflow"] + jnp.maximum(n_miss - miss_cap, 0)
        ok = overflow == 0

        # -- owner-local insert of missed reps ------------------------
        do_ins = (jnp.arange(miss_cap) < n_miss) & ok
        mp = miss_pos
        pc_p = vm.pad_rep(pc, mp)
        cl_p = vm.pad_rep(cl, mp)
        lane_p = vm.pad_rep(ins_lane, mp)
        ok_p = vm.pad_rep(ins_ok, mp)
        own_alive = alive_row[jnp.clip(pc_p, 0, ntp - 1)]
        own_ins = (
            do_ins & ok_p & (pc_p == my_col) & own_alive
        )
        ins_row = jnp.where(own_ins, cl_p, c_local)
        rows_idx = jnp.concatenate([ins_row] * vm.CACHE_WORDS)
        lanes_idx = jnp.concatenate(
            [lane_p + c * e for c in range(vm.CACHE_WORDS)]
        )
        vals = jnp.concatenate(
            [
                vm.pad_rep(rk0, mp), vm.pad_rep(rk1, mp),
                vm.pad_rep(rk2, mp), mv0, mv1,
            ]
        )
        cache_out = cache2.at[rows_idx, lanes_idx].set(vals)
        cache_out = jnp.where(ok, cache_out, cache2)[None, None]

        # -- combine + the failover counter epilogue ------------------
        probe1, probe2, probe3, t_proxy, t_j = (
            vm.unpack_value_words(v0, v1)
        )
        v = _combine(
            probe1, probe2, probe3, t_proxy, batch_l.is_fragment
        )
        # full-batch L3 ownership under the alive routing: each
        # identity word has exactly one SERVING owner (backup when
        # the primary is dead), so restricting the global probe2 to
        # the owned words reproduces the shard-local hit without a
        # gather
        word = idx >> 5
        if l3_sharded:
            wp = word // wn
            apw = alive_row[wp]
            owner_w = jnp.where(
                apw, wp,
                (wp + partition.REPLICA_BACKUP_OFFSET) % ntp,
            )
            owns_w = owner_w == my_col
        else:
            wp = apw = None
            owns_w = jnp.ones(word.shape, bool)
        p2_local = probe2 & owns_w
        l4_counts, l3_counts = failover_counts(
            tables_l, batch_l.ep_index, batch_l.direction,
            v.match_kind, t_j, idx, p2_local, valid_l,
            l3_sharded, wn, wp, apw, n_ids, batch_axis,
        )
        miss_live = jnp.arange(miss_cap) < n_miss
        replica_hits = jax.lax.psum(
            jax.lax.psum(
                jnp.sum(
                    (lat["replica"] & miss_live).astype(jnp.uint32)
                ),
                batch_axis,
            ),
            table_axis,
        )
        stats = jnp.stack(
            [
                g["n_unique"].astype(jnp.uint32),
                jnp.sum(
                    (tuple_hit & valid_l).astype(jnp.uint32)
                ),
                jnp.sum((do_ins & ok_p).astype(jnp.uint32)),
                overflow.astype(jnp.uint32),
                jnp.sum(valid_l.astype(jnp.uint32)),
            ]
        )
        stats = jax.lax.psum(stats, batch_axis)
        out = (
            v, l4_counts, l3_counts, replica_hits, cache_out,
            tuple_hit, stats,
        )
        if not collect_telemetry:
            return out
        zeros = jnp.zeros(v.allowed.shape, jnp.int32)
        masks = telemetry_masks(
            zeros, zeros, v.match_kind, v.allowed, zeros,
            v.proxy_port, zeros, zeros,
        )
        ingress = (batch_l.direction == 0) & valid_l
        row_in = jnp.stack(
            [jnp.sum(m & ingress, dtype=jnp.uint32) for m in masks]
        )
        col_total = jnp.stack(
            [jnp.sum(m & valid_l, dtype=jnp.uint32) for m in masks]
        )
        trow = jnp.stack([row_in, col_total - row_in])
        return out + (trow[None],)

    in_shardings = (
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), t_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        jax.tree.map(lambda s: NamedSharding(mesh, s), b_specs),
        NamedSharding(mesh, P()),
        NamedSharding(mesh, P(batch_axis)),
        NamedSharding(mesh, cache_spec),
    )
    jitted = jax.jit(step, in_shardings=in_shardings)
    aug_rows = n_rows_global * 2 if rows_sharded else n_rows_global
    aug_words = w_global * 2 if l3_sharded else w_global

    def run(tables_aug, batch, alive, valid, cache_in):
        got = (
            int(tables_aug.l4_hash_rows.shape[0]),
            int(tables_aug.l3_allow_bits.shape[-1]),
        )
        if got != (aug_rows, aug_words) or tuple(
            cache_in.shape
        ) != cshape:
            raise ValueError(
                "failover memo evaluator geometry mismatch; rebuild "
                "with make_failover_memo_evaluator"
            )
        out = jitted(tables_aug, batch, alive, valid, cache_in)
        if l3_sharded:
            out = (out[0], out[1], fold_l3_aug(out[2], ntp)) + tuple(
                out[3:]
            )
        return out

    return run


def make_async_mesh_dispatcher(
    step, mesh, batch_axis: str = "batch", depth: int = 1
):
    """Double-buffered dispatch over a mesh evaluator
    (engine.publish.AsyncBatchDispatcher applied to SPMD batches):
    the host packs + shards batch N+1 across the mesh while the
    chips compute batch N.  `step` is a one-argument closure
    batch → result with the tables already bound (e.g.
    `partial(make_sharded_evaluator(mesh), dev_tables)`);
    `submit((ep_index, identity, dport, proto, direction[,
    is_fragment]), meta)` stages a TupleBatch with the batch axis
    sharded; results drain one batch behind in submission order.

    This is the mesh serving loop's missing overlap: the sharded
    device_put (scatter of the batch across chips) is exactly the
    host-side work the single-chip path hides behind compute."""
    import numpy as np

    from cilium_tpu.engine.publish import AsyncBatchDispatcher

    sharded = NamedSharding(mesh, P(batch_axis))

    def pack(ep_index, identity, dport, proto, direction,
             is_fragment=None):
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        put = lambda a, dt: jax.device_put(
            np.asarray(a).astype(dt, copy=False), sharded
        )
        return (
            TupleBatch(
                ep_index=put(ep_index, np.int32),
                identity=put(identity, np.uint32),
                dport=put(dport, np.int32),
                proto=put(proto, np.int32),
                direction=put(direction, np.int32),
                is_fragment=put(is_fragment, bool),
            ),
        )

    def dispatch(batch):
        return step(batch)

    return AsyncBatchDispatcher(pack, dispatch, depth=depth)


def traced_dispatch(step, mesh, site: str = "engine.sharded"):
    """Wrap a mesh evaluator with span-plane dispatch attribution:
    each call opens a `mesh.dispatch` span (blocking on the result so
    the span covers the device execution, not just the enqueue) and
    synthesizes per-chip `chip.dispatch` children — the SPMD program
    runs in lockstep, so the parent's window partitions evenly across
    chips and the children sum to the batch span.  Per-chip spans are
    what the ROADMAP's per-chip failover item needs to debug: which
    ordinal's dispatch latency is the outlier.  The wrapped step also
    counts jit cache hits/misses per call (site label `site`)."""
    from cilium_tpu import tracing

    n_chips = int(mesh.devices.size)
    tracked = tracing.track_jit(step, site)

    def dispatch(tables, batch, *rest):
        rows = int(batch.ep_index.shape[0])
        with tracing.tracer.span(
            "mesh.dispatch", site=site,
            attrs={"chips": n_chips, "rows": rows},
        ) as sp:
            out = tracked(tables, batch, *rest)
            jax.block_until_ready(out)
        tracing.record_chip_spans(
            tracing.tracer, sp, n_chips, rows, site
        )
        return out

    dispatch.__wrapped__ = step
    return dispatch
