"""Per-chip failover: the shard router in front of the mesh
evaluators.

PR 2's resilience plane trips ONE process-wide breaker and fails the
whole mesh over to the host fold; with the tables identity-sharded
(PR 7) a single sick chip would take its table rows down with it.
This module builds the per-chip failure domain on top of three
pieces:

  * a ChipBreakerBank (cilium_tpu.resilience) — one closed/open/
    half-open breaker per device ordinal, fed by per-chip failure
    attribution: before every launch the router probes the
    `engine.dispatch` fault seam once PER ORDINAL (chip-scoped
    selectors, faultinject `chip=` param), so a chaos schedule can
    kill exactly one chip;
  * the N+1 replica placement (compiler.partition.REPLICA_LEAVES +
    engine.sharded.make_replica_store): each sharded leaf's rows
    also live on a backup owner, the next shard over, and
    make_failover_evaluator routes a dead primary's gathers to the
    backup region — verdicts never read the sick chip's slice;
  * batch re-splitting: a mesh row none of whose chips can serve a
    slice (primary AND backup dead) is routed around — its tuple
    shard re-splits across surviving rows, padding the dead row with
    valid-masked filler so counters/telemetry count exactly the real
    tuples.  The host lattice fold remains the TERMINAL fallback,
    taken only when no row survives.

Re-admission is a REBALANCE: a half-open probe first replays the
rows the chip missed while out (the store's outage ledger, applied
through the DeviceTableStore delta-scatter path — bytes proportional
to the missed change, never a full upload), then the probe dispatch
includes the chip; success closes its breaker.

Simulation boundary: on the virtual CPU mesh the SPMD program still
executes on a "dead" chip — what this layer proves (and the chaos
storm asserts) is that no verdict, counter or telemetry bit DEPENDS
on the dead chip's table slice (its primary regions can be garbage)
and that the observable stream is bit-identical to the healthy mesh
and the host oracle.  Re-forming the physical mesh around a truly
absent device is the runtime's job on real hardware.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from cilium_tpu import faultinject, tracing
from cilium_tpu.engine.publish import next_pow2
from cilium_tpu.logging import get_logger
from cilium_tpu.metrics import registry as metrics
from cilium_tpu.resilience import (
    HALF_OPEN,
    STATE_CODES,
    ChipBreakerBank,
)

log = get_logger("failover")


@dataclass
class FailoverResult:
    """One batch through the router, stream order restored."""

    verdicts: object  # engine.verdict.Verdicts (host numpy columns)
    l4_counts: Optional[np.ndarray] = None
    l3_counts: Optional[np.ndarray] = None
    telemetry: Optional[np.ndarray] = None  # [dp, 2, T] or None
    replica_hits: int = 0
    rerouted: bool = False  # batch shard re-split across survivors
    degraded: bool = False  # served by the terminal host fold
    alive: Optional[np.ndarray] = None  # [dp, tp] snapshot
    rebalanced_chips: Tuple[int, ...] = ()
    rebalance_bytes: int = 0
    rebalance_ms: float = 0.0
    # routed memo plane (attach_memo): per-tuple cache-hit flags in
    # stream order, None when the batch was served uncached
    cache_hit: Optional[np.ndarray] = None
    # shadow policy rollout (cilium_tpu.shadow): the SHADOW world's
    # verdict columns for the same batch, stream order — None when
    # the batch was not sampled or the shadow leg refused
    shadow_verdicts: Optional[object] = None


@dataclass
class RouterStats:
    batches: int = 0
    tuples: int = 0
    rerouted_batches: int = 0
    degraded_batches: int = 0
    replica_hits: int = 0
    rebalances: int = 0
    rebalance_bytes: int = 0
    last_rebalance_ms: float = 0.0
    chip_failures: Dict[int, int] = field(default_factory=dict)


class ChipFailoverRouter:
    """Shard router in front of the mesh evaluators: consults the
    ChipBreakerBank per dispatch, re-splits dead rows' batch shards
    across survivors, routes dead primaries' table gathers to their
    N+1 replicas, rebalances re-admitted chips through the store's
    delta-scatter path, and falls back to the host lattice fold only
    when no row survives.

    `tables` (un-augmented host PolicyTables) fixes the evaluator
    geometry; publish() installs epochs through the replica store.
    `host_fold(ep_index, identity, dport, proto, direction,
    is_fragment)` is the terminal fallback (e.g.
    engine.hostpath.lattice_fold_host bound to the map states) —
    without one, a mesh-wide outage raises instead of degrading.
    """

    def __init__(
        self,
        mesh,
        tables,
        store=None,
        bank: Optional[ChipBreakerBank] = None,
        collect_telemetry: bool = False,
        host_fold=None,
        batch_axis: str = "batch",
        table_axis: str = "table",
        site: str = "engine.dispatch",
        on_chip_transition=None,
    ) -> None:
        from cilium_tpu.engine.sharded import (
            make_failover_evaluator,
            make_replica_store,
        )

        self.mesh = mesh
        self.batch_axis = batch_axis
        self.table_axis = table_axis
        self.site = site
        self.collect_telemetry = collect_telemetry
        self.host_fold = host_fold
        self._on_chip_transition = on_chip_transition
        # mesh geometry: ordinal grid [dp, tp] of device ids
        axes = list(mesh.axis_names)
        self.dp = int(mesh.shape[batch_axis])
        self.tp = int(mesh.shape[table_axis])
        grid = np.empty((self.dp, self.tp), np.int64)
        for idx, dev in np.ndenumerate(mesh.devices):
            coord = dict(zip(axes, idx))
            grid[coord[batch_axis], coord[table_axis]] = int(dev.id)
        self.ordinals = grid
        self.store = store or make_replica_store(mesh, table_axis)
        if bank is None:
            bank = ChipBreakerBank(
                name=site, on_transition=self._chip_event
            )
        elif bank.on_transition is None:
            bank.on_transition = self._chip_event
        else:
            # the router's own wiring (outage ledger, gauge, span
            # events) is load-bearing — chain it ahead of the
            # caller's listener rather than dropping either
            caller = bank.on_transition

            def chained(ordinal, old, new, reason, _caller=caller):
                self._chip_event(ordinal, old, new, reason)
                _caller(ordinal, old, new, reason)

            bank.on_transition = chained
        self.bank = bank
        self._tables = tables
        self._ev = make_failover_evaluator(
            mesh, tables, batch_axis=batch_axis,
            table_axis=table_axis,
            collect_telemetry=collect_telemetry,
        )
        self._geom = (
            tuple(tables.l4_hash_rows.shape),
            tuple(tables.l3_allow_bits.shape),
        )
        self.stats = RouterStats()
        # batch re-split plans keyed on the survivor set: the
        # steady-state degraded loop re-splits the SAME survivor
        # layout every dispatch, so the routing plan (usable rows,
        # shard size, valid mask, stream positions) computes once
        # per alive-matrix change instead of per batch
        self._pack_plans: Dict[tuple, tuple] = {}
        # attached verdict cache (engine/memo.py): flushed on every
        # chip breaker transition — a kill or readmission changes
        # routing (and readmission rewrites the live epoch in place
        # through the repair scatter), so the flush keeps the
        # cached-verdict staleness argument airtight
        self._verdict_cache = None
        # routed memo plane (attach_memo): sharded verdict cache +
        # alive-masked memo evaluator on the dispatch path
        self._memo = None
        # fused-datapath plane (engine/datapath_mesh.py): attached
        # via attach_datapath — the router then serves the FULL
        # pipeline (prefilter + LB/DNAT + CT + ipcache + lattice)
        # through dispatch_flows over the same admit/re-split/replica
        # machinery
        self.dp_store = None
        self._dp_ev = None
        self._dp_geom = None
        self._host_datapath_fold = None
        # chips whose breaker opened while a fused epoch is resident:
        # their datapath slices repair at readmission (bytes ∝ one
        # chip's owned rows, not a full upload)
        self._dp_out = set()

    # -- breaker plumbing ----------------------------------------------------

    def attach_verdict_cache(self, cache) -> None:
        """Bind a VerdictCache (engine/memo.py): any chip breaker
        transition — kill OR readmission — flushes it, so no cached
        verdict can outlive a routing/repair event."""
        self._verdict_cache = cache

    def attach_memo(
        self,
        n_rows_local: int = 1 << 10,
        entries: int = 8,
        rep_shift: int = 2,
    ) -> None:
        """Put the PARTITIONED verdict-memoization plane on the
        router's dispatch path: a sharded verdict cache
        (make_partitioned_cache — bucket rows co-located with the
        table shards) probed/inserted by an ALIVE-masked memo
        evaluator (make_failover_memo_evaluator), so routed lattice
        dispatch serves repeated policy keys from the cache and runs
        the replica-aware gathers only for missed representatives.

        Bit-identity is unconditional: a compaction overflow REFUSES
        the batch (carried cache unchanged) and dispatch re-runs it
        through the uncached failover evaluator; breaker transitions
        still flush (attach_verdict_cache wiring); the cache is
        epoch-stamped against the replica store, so a publish or
        repair can never serve a stale verdict."""
        from cilium_tpu.engine.sharded import make_partitioned_cache

        cache = make_partitioned_cache(
            self.mesh, n_rows_local, entries,
            batch_axis=self.batch_axis, table_axis=self.table_axis,
        )
        self.attach_verdict_cache(cache)
        self._memo = {
            "cache": cache,
            # construction params retained so a mesh reshard can
            # rebuild the (dp, tp)-shaped cache on the target mesh
            "n_rows_local": int(n_rows_local),
            "entries": int(entries),
            "rep_shift": int(rep_shift),
            "evs": {},  # (geom, rep_cap) -> evaluator
            "hits": 0,
            "misses": 0,
            "overflow_redispatches": 0,
            "insert_faults": 0,
        }

    def _memo_evaluator(self, rep_cap: int):
        """The alive-masked memo evaluator for the CURRENT table
        geometry at a given per-shard compaction capacity (cached;
        rebuilt when publish() crosses a shape class)."""
        from cilium_tpu.engine.sharded import (
            make_failover_memo_evaluator,
        )

        key = (self._geom, rep_cap)
        ev = self._memo["evs"].get(key)
        if ev is None:
            # evict evaluators of OTHER geometries (their jit
            # executables are stale), but keep every rep_cap class
            # of the current one — a stream alternating batch-size
            # classes must not retrace per dispatch
            self._memo["evs"] = {
                k: v
                for k, v in self._memo["evs"].items()
                if k[0] == self._geom
            }
            ev = make_failover_memo_evaluator(
                self.mesh, self._tables,
                np.asarray(self._memo["cache"].rows), rep_cap,
                batch_axis=self.batch_axis,
                table_axis=self.table_axis,
                collect_telemetry=self.collect_telemetry,
            )
            self._memo["evs"][key] = ev
        return ev

    def _chip_event(self, ordinal, old, new, reason) -> None:
        """Per-chip breaker transition: gauge + span event + the
        store's outage ledger (an OPEN chip starts missing
        publishes) + verdict-cache flush + re-split plan reset."""
        metrics.chip_breaker_state.set(
            str(ordinal), value=STATE_CODES[new]
        )
        self._pack_plans.clear()
        if self._verdict_cache is not None:
            self._verdict_cache.flush(
                reason=f"chip {int(ordinal)} {old}->{new}"
            )
        tracing.add_event(
            "chip.breaker", chip=int(ordinal), old=old, new=new,
            reason=reason,
        )
        if new == "open":
            self.store.mark_chip_out(ordinal)
            if self.dp_store is not None:
                self._dp_out.add(int(ordinal))
        log.warning(
            "chip breaker transition",
            extra={"fields": {
                "chip": int(ordinal), "from": old, "to": new,
                "reason": reason,
            }},
        )
        if self._on_chip_transition is not None:
            self._on_chip_transition(ordinal, old, new, reason)

    # -- publication ---------------------------------------------------------

    def publish(self, tables, delta=None):
        """Install host tables as the serving epoch (replica store:
        augmentation + per-copy delta scatter happen inside).  A
        changed table GEOMETRY (hash-plane regrow, identity-pad
        growth) rebuilds the failover evaluator in place — the daemon
        auto-publish hook must survive a regenerate that crosses a
        shape class, and the store's layout stamp already forces the
        full upload such a publish needs."""
        from cilium_tpu.engine.sharded import make_failover_evaluator

        got = (
            tuple(tables.l4_hash_rows.shape),
            tuple(tables.l3_allow_bits.shape),
        )
        if got != self._geom:
            log.warning(
                "table geometry changed; rebuilding failover "
                "evaluator",
                extra={"fields": {
                    "from": str(self._geom), "to": str(got),
                }},
            )
            self._ev = make_failover_evaluator(
                self.mesh, tables, batch_axis=self.batch_axis,
                table_axis=self.table_axis,
                collect_telemetry=self.collect_telemetry,
            )
            self._geom = got
            self._pack_plans.clear()
        self._tables = tables
        return self.store.publish(tables, delta)

    # -- the fused datapath plane (engine/datapath_mesh.py) ------------------

    def attach_datapath(self, dtables, host_fold=None) -> None:
        """Adopt the FULL fused pipeline: build the DatapathStore
        and the fused failover evaluator, and publish `dtables` as
        the serving datapath epoch.  dispatch_flows then serves raw
        5-tuple flows through prefilter + LB/DNAT + CT + ipcache +
        lattice over the partitioned N+1 tables, with the same
        per-chip breakers / survivor re-split / replica gathers as
        the lattice path.  `host_fold(ep_index, saddr, daddr, sport,
        dport, proto, direction, is_fragment)` is the optional
        terminal fallback when no mesh row can serve."""
        from cilium_tpu.engine.datapath_mesh import DatapathStore

        self.dp_store = DatapathStore(self.mesh, self.table_axis)
        self._dp_ev = None
        self._dp_geom = None
        self._host_datapath_fold = host_fold
        # prime BOTH epoch slots (the policy-store idiom) so the
        # very next churn publish rides the row-diff delta path
        self.publish_datapath(dtables)
        self.publish_datapath(dtables)

    def publish_datapath(self, dtables, changes=None):
        """Install a fused-datapath world (host, un-augmented) as
        the serving epoch: steady-state churn rides the store's
        row-diff delta scatter — or, with a per-subsystem change
        record (`changes`, see DatapathStore.publish), the O(change)
        scoped scatter; a geometry change rebuilds the fused
        evaluator and full-uploads."""
        from cilium_tpu.engine.datapath_mesh import (
            _geometry,
            make_failover_datapath_evaluator,
        )

        if self.dp_store is None:
            raise RuntimeError(
                "no datapath plane attached: call attach_datapath"
            )
        geom = _geometry(dtables)
        if self._dp_ev is None or geom != self._dp_geom:
            self._dp_ev = make_failover_datapath_evaluator(
                self.mesh, dtables, batch_axis=self.batch_axis,
                table_axis=self.table_axis,
                collect_telemetry=self.collect_telemetry,
            )
            self._dp_geom = geom
        return self.dp_store.publish(dtables, changes=changes)

    # -- elastic resharding adoption (engine/reshard.py cutover) -------------

    def adopt_reshard(self, target_mesh, dtables=None) -> None:
        """Adopt a resharded mesh at cutover: rebuild every router
        structure that closes over the mesh geometry — the ordinal
        grid, the failover evaluator, the re-split plan cache, the
        partitioned memo plane (its cache rows are (dp, tp)-shaped)
        and the fused-datapath evaluator (`dtables` is the
        un-augmented fused world; without it the evaluator rebuilds
        lazily on the next publish_datapath).  The stores' own
        cutover (DeviceTableStore / DatapathStore .cutover_relayout)
        is the plan's job — this verb only re-aims the router.  Must
        run between dispatches (the plan cuts over at a batch
        boundary); in-flight batches completed on the source epoch,
        which is never touched."""
        from cilium_tpu.engine.sharded import (
            make_failover_evaluator,
        )

        axes = list(target_mesh.axis_names)
        self.mesh = target_mesh
        self.dp = int(target_mesh.shape[self.batch_axis])
        self.tp = int(target_mesh.shape[self.table_axis])
        grid = np.empty((self.dp, self.tp), np.int64)
        for idx, dev in np.ndenumerate(target_mesh.devices):
            coord = dict(zip(axes, idx))
            grid[
                coord[self.batch_axis], coord[self.table_axis]
            ] = int(dev.id)
        self.ordinals = grid
        self._ev = make_failover_evaluator(
            target_mesh, self._tables, batch_axis=self.batch_axis,
            table_axis=self.table_axis,
            collect_telemetry=self.collect_telemetry,
        )
        self._pack_plans.clear()
        if self._memo is not None:
            # the sharded cache's rows are laid out per (dp, tp)
            # chip — rebuild it empty on the target mesh (a flush
            # with a layout change), carrying the counters across
            carried = self._memo
            self.attach_memo(
                n_rows_local=carried["n_rows_local"],
                entries=carried["entries"],
                rep_shift=carried["rep_shift"],
            )
            for k in (
                "hits", "misses", "overflow_redispatches",
                "insert_faults",
            ):
                self._memo[k] = carried[k]
        elif self._verdict_cache is not None:
            self._verdict_cache.flush(reason="mesh reshard cutover")
        if self.dp_store is not None:
            self._dp_ev = None
            self._dp_geom = None
            if dtables is not None:
                from cilium_tpu.engine.datapath_mesh import (
                    _geometry,
                    make_failover_datapath_evaluator,
                )

                self._dp_ev = make_failover_datapath_evaluator(
                    target_mesh, dtables,
                    batch_axis=self.batch_axis,
                    table_axis=self.table_axis,
                    collect_telemetry=self.collect_telemetry,
                )
                self._dp_geom = _geometry(dtables)
        tracing.add_event(
            "reshard.adopt", dp=self.dp, tp=self.tp,
        )

    def dispatch_flows(
        self,
        ep_index,
        saddr,
        daddr,
        sport,
        dport,
        proto,
        direction,
        is_fragment=None,
    ) -> FailoverResult:
        """One raw-flow batch through the FULL fused pipeline on the
        mesh.  Returns a FailoverResult whose `verdicts` is an
        engine.datapath.DatapathVerdicts of host columns in STREAM
        ORDER — bit-identical to the single-device fused program
        whatever the survivor set, as long as one owner of every
        table slice survives."""
        import jax

        from cilium_tpu.engine.datapath import (
            DatapathVerdicts,
            FlowBatch,
        )

        if self.dp_store is None:
            raise RuntimeError(
                "no datapath plane attached: call attach_datapath"
            )
        cols = {
            "ep_index": np.asarray(ep_index, np.int32),
            "saddr": np.asarray(saddr, np.uint32),
            "daddr": np.asarray(daddr, np.uint32),
            "sport": np.asarray(sport, np.int32),
            "dport": np.asarray(dport, np.int32),
            "proto": np.asarray(proto, np.int32),
            "direction": np.asarray(direction, np.int32),
            "is_fragment": (
                np.zeros(len(ep_index), bool)
                if is_fragment is None
                else np.asarray(is_fragment, bool)
            ),
        }
        b = len(cols["ep_index"])
        if b == 0:
            zero = lambda dt: np.zeros(0, dt)  # noqa: E731
            return FailoverResult(
                verdicts=DatapathVerdicts(
                    allowed=zero(np.uint8),
                    proxy_port=zero(np.int32),
                    match_kind=zero(np.uint8),
                    ct_result=zero(np.uint8),
                    pre_dropped=zero(bool),
                    sec_id=zero(np.uint32),
                    final_daddr=zero(np.uint32),
                    final_dport=zero(np.int32),
                    rev_nat=zero(np.int32),
                    lb_slave=zero(np.int32),
                    ct_create=zero(bool),
                    ct_delete=zero(bool),
                    tunnel_endpoint=zero(np.uint32),
                    l4_slot=zero(np.int32),
                    ipcache_miss=zero(bool),
                ),
            )
        plan, fold_args = self._plan_batch(cols)
        if plan is None:
            return self._terminal_flow_fold(
                cols, *fold_args,
                reason="no mesh row can serve every table slice",
            )
        alive = plan["alive"]
        dev = self.dp_store.current()
        if dev is None:
            raise RuntimeError(
                "no published datapath epoch: call publish_datapath"
            )
        batch = FlowBatch(**plan["padded"])
        with tracing.tracer.span(
            "mesh.dispatch", site=self.site,
            attrs={
                "chips": int(alive.sum()), "rows": b,
                "rerouted": plan["rerouted"], "fused": True,
            },
        ) as sp:
            try:
                out = self._dp_ev(dev, batch, alive, plan["valid"])
                jax.block_until_ready(out)
            except Exception as exc:  # noqa: BLE001
                sp.status = "error"
                sp.attrs["error"] = str(exc)
                self._blame_alive(alive, exc)
                return self._terminal_flow_fold(
                    cols, alive, plan["rebalanced"],
                    plan["reb_bytes"], plan["reb_ms"],
                    reason=str(exc),
                )
        self._credit_alive(alive)
        if self.collect_telemetry:
            v, l4c, l3c, replica_hits, trow = out
            telemetry = np.asarray(trow)
        else:
            v, l4c, l3c, replica_hits = out
            telemetry = None
        replica_hits = self._count_replica_hits(replica_hits)
        positions = plan["positions"]

        def col(x):
            a = np.asarray(x)
            return a if positions is None else a[positions]

        verdicts = DatapathVerdicts(
            **{
                f: col(getattr(v, f))
                for f in (
                    "allowed", "proxy_port", "match_kind",
                    "ct_result", "pre_dropped", "sec_id",
                    "final_daddr", "final_dport", "rev_nat",
                    "lb_slave", "ct_create", "ct_delete",
                    "tunnel_endpoint", "l4_slot", "ipcache_miss",
                )
            }
        )
        return FailoverResult(
            verdicts=verdicts,
            l4_counts=np.asarray(l4c),
            l3_counts=np.asarray(l3c),
            telemetry=telemetry,
            replica_hits=replica_hits,
            rerouted=plan["rerouted"],
            degraded=False,
            alive=alive,
            rebalanced_chips=plan["rebalanced"],
            rebalance_bytes=plan["reb_bytes"],
            rebalance_ms=plan["reb_ms"],
        )

    def _terminal_flow_fold(
        self, cols, alive, rebalanced, reb_bytes, reb_ms, reason=""
    ) -> FailoverResult:
        """Host composed-pipeline fold for the fused path — taken
        only when no owner of some slice survives (or the SPMD
        launch failed); raises without a configured host_fold."""
        if self._host_datapath_fold is None:
            raise RuntimeError(
                f"fused mesh unserviceable ({reason}) and no "
                f"host datapath fold configured"
            )
        with tracing.tracer.span(
            "engine.hostpath", site="engine.hostpath",
            attrs={"failover": True, "fused": True,
                   "reason": reason},
        ):
            v = self._host_datapath_fold(
                cols["ep_index"], cols["saddr"], cols["daddr"],
                cols["sport"], cols["dport"], cols["proto"],
                cols["direction"], cols["is_fragment"],
            )
        metrics.degraded_batches_total.inc()
        self.stats.degraded_batches += 1
        log.warning(
            "fused mesh batch served by terminal host fold",
            extra={"fields": {"reason": reason}},
        )
        return FailoverResult(
            verdicts=v,
            degraded=True,
            alive=alive,
            rebalanced_chips=rebalanced,
            rebalance_bytes=reb_bytes,
            rebalance_ms=reb_ms,
        )

    # -- re-admission rebalance ----------------------------------------------

    def _owned_row_sets(self, ordinal: int, outage) -> Dict:
        """{leaf: (axis, aug index array)} a re-admitted chip must
        replay: the union of the missed deltas' scatter rows
        restricted to the chip's owned regions (primary + backup),
        or the whole owned slice when a full upload / ledger
        overflow happened while it was out."""
        from cilium_tpu.compiler import partition

        col = None
        rows_r, cols_c = np.where(self.ordinals == int(ordinal))
        if cols_c.size:
            col = int(cols_c[0])
        if col is None:
            return {}
        axes = partition.replica_axes(
            self._tables, self.tp, self.table_axis
        )
        out = {}
        for name, axis in axes.items():
            n = getattr(self._tables, name).shape[axis] // self.tp
            lo, hi = col * 2 * n, (col + 1) * 2 * n
            whole_region = outage["needs_full"] or any(
                name in d.replace for d in outage["missed"]
            )
            touched = []
            for d in outage["missed"]:
                up = d.updates.get(name)
                if up is None:
                    continue
                if axis < len(up.idx):
                    # the ledger's deltas are already in augmented
                    # coordinates (the store records what it applied)
                    touched.append(np.asarray(up.idx[axis], np.int64))
                else:
                    # slab-shaped update (values span the sharded
                    # axis): it wrote into the chip's whole region
                    whole_region = True
            if whole_region:
                idx = np.arange(lo, hi, dtype=np.int64)
            else:
                if not touched:
                    continue
                idx = np.unique(np.concatenate(touched))
                idx = idx[(idx >= lo) & (idx < hi)]
            if idx.size:
                out[name] = (axis, idx)
        return out

    def _whole_owned_row_sets(self, ordinal: int) -> Dict:
        """{leaf: (axis, aug index array)} covering a chip's ENTIRE
        owned regions (primary + backup) — the spare-epoch repair's
        row set: the standby missed an unknown mix of scatters
        recorded against alternating slots, so the safe replay is
        the whole owned slice from the spare's retained host.
        Delegates to _owned_row_sets' needs_full branch so the
        owned-region layout arithmetic lives in one place."""
        return self._owned_row_sets(
            ordinal, {"needs_full": True, "missed": []}
        )

    def _rebalance(self, ordinal: int) -> Tuple[int, float]:
        """Replay the rows a chip missed while out, through the
        store's repair scatter — the LIVE epoch from the outage
        ledger, and (when publishes landed during the outage) the
        SPARE epoch's whole owned slice from its retained host
        snapshot, so the next publish stays on the delta path
        instead of paying a full upload for a de-registered
        standby.  Returns (bytes, ms)."""
        outage = self.store.readmit_chip(ordinal)
        if outage is None:
            return 0, 0.0
        t0 = time.perf_counter()
        try:
            # the row arithmetic below (_owned_row_sets) runs under
            # the ROUTER's serving layout (self.tp / self._tables);
            # each repair must land on an epoch laid out under the
            # SAME partition digest, or the scatter would plant rows
            # computed under one column assignment into an epoch
            # keyed by another — the readmit-races-reshard hazard: a
            # mid-migration readmission sees the staged TARGET
            # epoch in the spare slot and must refuse (the chip
            # stays out; post-cutover readmission replays its whole
            # owned regions under the new layout instead)
            serving_digest = int(self.store.partition_digest)
            for which in ("live_layout", "spare_layout"):
                lay = outage.get(which)
                if lay is not None and (lay >> 32) != serving_digest:
                    raise RuntimeError(
                        f"chip {int(ordinal)} readmission races a "
                        f"mesh relayout ({which} digest "
                        f"{lay >> 32:#x} != serving "
                        f"{serving_digest:#x}); repair refused"
                    )
            row_sets = self._owned_row_sets(ordinal, outage)
            bytes_h2d = (
                self.store.repair_rows(
                    row_sets,
                    expect_layout=outage.get("live_layout"),
                )
                if row_sets else 0
            )
            if outage.get("spare_stale"):
                spare_sets = self._whole_owned_row_sets(ordinal)
                if spare_sets:
                    bytes_h2d += self.store.repair_rows(
                        spare_sets, spare=True,
                        expect_epoch=outage.get("spare_epoch"),
                        expect_layout=outage.get("spare_layout"),
                    )
        except Exception:
            # the scatter may have partially landed — put the popped
            # ledger back (downgraded to needs_full) so the NEXT
            # readmission replays the whole owned regions instead of
            # finding an empty fresh record and replaying nothing
            self.store.restore_outage(ordinal, outage)
            raise
        ms = (time.perf_counter() - t0) * 1000.0
        self.stats.rebalances += 1
        self.stats.rebalance_bytes += bytes_h2d
        self.stats.last_rebalance_ms = ms
        tracing.add_event(
            "chip.rebalance", chip=int(ordinal),
            bytes_h2d=bytes_h2d, ms=round(ms, 3),
            missed_deltas=len(outage["missed"]),
            needs_full=outage["needs_full"],
            spare_repaired=bool(outage.get("spare_stale")),
        )
        log.info(
            "chip re-admission rebalance",
            extra={"fields": {
                "chip": int(ordinal), "bytes_h2d": bytes_h2d,
                "ms": round(ms, 3),
            }},
        )
        return bytes_h2d, ms

    # -- routing -------------------------------------------------------------

    def _admit(self):
        """One admission round: per-chip fault probes (attribution),
        per-chip breaker questions, and pre-probe rebalances for
        half-open chips with an open outage ledger.  Returns (alive
        [dp, tp] bool, rebalanced ordinals, bytes, ms, probed
        ordinals whose admission consumed a half-open probe slot —
        a dispatch that never launches must release those)."""
        alive = np.zeros((self.dp, self.tp), bool)
        rebalanced = []
        probed = []
        reb_bytes = 0
        reb_ms = 0.0
        for r in range(self.dp):
            for c in range(self.tp):
                ordinal = int(self.ordinals[r, c])
                try:
                    faultinject.fire(self.site, chip=ordinal)
                except faultinject.FaultInjected as exc:
                    self.bank.record_failure(ordinal, str(exc))
                    self.stats.chip_failures[ordinal] = (
                        self.stats.chip_failures.get(ordinal, 0) + 1
                    )
                    continue
                was_half_open = (
                    self.bank.state(ordinal) == HALF_OPEN
                )
                ok = self.bank.allow(ordinal)
                if ok and self.store.chip_outage(ordinal) is not None:
                    # the half-open probe may not trust the chip's
                    # slice until the rows it missed are back — the
                    # rebalance precedes the probe dispatch
                    try:
                        b, ms = self._rebalance(ordinal)
                        rebalanced.append(ordinal)
                        reb_bytes += b
                        reb_ms += ms
                    except Exception as exc:  # noqa: BLE001
                        # record_failure releases the probe slot too
                        self.bank.record_failure(
                            ordinal, f"rebalance failed: {exc}"
                        )
                        ok = False
                if ok and ordinal in self._dp_out:
                    # the fused-datapath half of the rebalance: the
                    # chip's owned CT/ipcache/LB/policy slices of
                    # the datapath epoch replay from the store's
                    # retained host snapshot (bytes ∝ one chip's
                    # slice, never a full upload)
                    try:
                        t0 = time.perf_counter()
                        db = self.dp_store.repair_chip(c)
                        reb_ms += (time.perf_counter() - t0) * 1e3
                        reb_bytes += db
                        self._dp_out.discard(ordinal)
                        if ordinal not in rebalanced:
                            rebalanced.append(ordinal)
                        self.stats.rebalance_bytes += db
                        tracing.add_event(
                            "chip.rebalance", chip=ordinal,
                            bytes_h2d=db, datapath=True,
                        )
                    except Exception as exc:  # noqa: BLE001
                        self.bank.record_failure(
                            ordinal,
                            f"datapath repair failed: {exc}",
                        )
                        ok = False
                if ok and was_half_open:
                    probed.append(ordinal)
                alive[r, c] = ok
        return (
            alive, tuple(rebalanced), reb_bytes, reb_ms,
            tuple(probed),
        )

    def _usable_rows(self, alive: np.ndarray) -> np.ndarray:
        """A mesh row serves tuples iff every table slice has a live
        owner within it: the primary column, or its backup (next
        shard over).  tp == 1 degenerates to 'the row's chip is
        alive'."""
        if self.tp == 1:
            return alive[:, 0].copy()
        from cilium_tpu.compiler.partition import (
            REPLICA_BACKUP_OFFSET,
        )

        ok = np.ones(self.dp, bool)
        for c in range(self.tp):
            backup = (c + REPLICA_BACKUP_OFFSET) % self.tp
            ok &= alive[:, c] | alive[:, backup]
        return ok

    def _pack_plan(self, b: int, usable: np.ndarray):
        """Routing plan for a (batch length, survivor set) pair:
        shard size, valid mask, stream-order positions and the
        per-row copy chunks.  Cached — the steady-state degraded
        loop re-splits the same survivor layout every dispatch, and
        replanning (flatnonzero + per-row position arithmetic) was
        a measurable slice of degraded_verdicts_per_sec_per_chip.
        The cache clears on every breaker transition."""
        key = (b, usable.tobytes())
        plan = self._pack_plans.get(key)
        if plan is not None:
            return plan
        rows = np.flatnonzero(usable)
        per = -(-b // len(rows))  # ceil
        s = max(next_pow2(per), 1)
        if len(rows) == self.dp and self.dp * s == b:
            plan = None, None, None, None  # identity pass-through
        else:
            total = self.dp * s
            valid = np.zeros(total, bool)
            positions = np.empty(b, np.int64)
            chunks = []  # (dst slice, src slice)
            off = 0
            for r in rows:
                take = min(s, b - off)
                if take <= 0:
                    break
                sl = slice(r * s, r * s + take)
                chunks.append((sl, slice(off, off + take)))
                valid[sl] = True
                positions[off : off + take] = np.arange(
                    r * s, r * s + take
                )
                off += take
            assert off == b, "batch re-split lost tuples"
            plan = total, valid, positions, tuple(chunks)
        self._pack_plans[key] = plan
        return plan

    def _pack(self, cols: Dict[str, np.ndarray], usable: np.ndarray):
        """Re-split the tuple stream over the usable rows: each gets
        a contiguous chunk of the real stream; unusable rows carry
        valid-masked filler (copies of tuple 0).  Returns (padded
        cols, valid [dp*s], positions of the real tuples in stream
        order — None for the identity).  The fully-healthy,
        already-aligned steady state (every row usable, shard size
        already a power of two) hands the batch straight through:
        no column copies, no output gather.  The routing plan is
        cached per survivor set (_pack_plan); only the column
        copies run per batch."""
        b = len(cols["ep_index"])
        total, valid, positions, chunks = self._pack_plan(b, usable)
        if total is None:
            return cols, np.ones(b, bool), None
        padded = {
            k: np.repeat(v[:1], total, axis=0).astype(v.dtype)
            for k, v in cols.items()
        }
        for dst, src in chunks:
            for key, v in cols.items():
                padded[key][dst] = v[src]
        return padded, valid, positions

    def _plan_batch(self, cols: Dict[str, np.ndarray]):
        """The admission + re-split front half SHARED by dispatch
        (lattice) and dispatch_flows (fused): stats, per-chip fault
        probes/breaker questions/rebalances, the usable-row rule
        (with probe-slot release when nothing can serve), reroute
        accounting and the batch re-split.  Returns (plan, None) on
        a servable mesh — plan carries alive/padded/valid/positions/
        rerouted + the rebalance record — or (None, fold_args) when
        no row can serve and the caller must take its terminal
        fold."""
        self.stats.batches += 1
        self.stats.tuples += len(cols["ep_index"])
        alive, rebalanced, reb_bytes, reb_ms, probed = self._admit()
        usable = self._usable_rows(alive)
        if not usable.any():
            # the dispatch never launches, so admitted half-open
            # chips earn neither a success nor a failure — give
            # their probe slots back instead of pinning them until
            # the TTL (a healthy, already-rebalanced chip must not
            # be locked out for probe_ttl by OTHER rows' deaths)
            for ordinal in probed:
                self.bank.release_probe(ordinal)
            return None, (alive, rebalanced, reb_bytes, reb_ms)
        rerouted = not usable.all()
        if rerouted:
            metrics.rerouted_batches_total.inc()
            self.stats.rerouted_batches += 1
            tracing.add_event(
                "chip.reroute",
                dead_rows=int((~usable).sum()),
                survivors=int(usable.sum()),
            )
        padded, valid, positions = self._pack(cols, usable)
        return {
            "alive": alive,
            "rebalanced": rebalanced,
            "reb_bytes": reb_bytes,
            "reb_ms": reb_ms,
            "rerouted": rerouted,
            "padded": padded,
            "valid": valid,
            "positions": positions,
        }, None

    def _blame_alive(self, alive, exc) -> None:
        """Unattributed launch failure: every participating chip is
        suspect (a mesh-wide SPMD launch has no smaller blame unit
        without the fault seam's attribution)."""
        for r in range(self.dp):
            for c in range(self.tp):
                if alive[r, c]:
                    self.bank.record_failure(
                        int(self.ordinals[r, c]), str(exc)
                    )

    def _credit_alive(self, alive) -> None:
        for r in range(self.dp):
            for c in range(self.tp):
                if alive[r, c]:
                    self.bank.record_success(
                        int(self.ordinals[r, c])
                    )

    def _count_replica_hits(self, replica_hits) -> int:
        replica_hits = int(np.asarray(replica_hits))
        if replica_hits:
            metrics.replica_gather_total.inc(value=replica_hits)
            self.stats.replica_hits += replica_hits
        return replica_hits

    def dispatch(
        self,
        ep_index,
        identity,
        dport,
        proto,
        direction,
        is_fragment=None,
        shadow=None,
    ) -> FailoverResult:
        """One batch through the per-chip failure domain.  Returns a
        FailoverResult with the verdict columns in STREAM ORDER —
        bit-identical to the healthy mesh whatever the survivor set,
        as long as at least one owner of every slice survives; the
        host fold serves the batch beyond that.

        ``shadow`` is an optional (evaluator, device tables) pair
        (cilium_tpu.shadow.ShadowPlane.routed_args): the SAME
        re-split, alive-masked, valid-padded batch additionally
        gathers through the shadow epoch — the second gather rides
        the staged batch through the routed evaluators — and the
        shadow verdict columns come back on
        ``FailoverResult.shadow_verdicts``.  A shadow-leg failure
        never degrades the live batch (shadow_verdicts stays None)."""
        cols = {
            "ep_index": np.asarray(ep_index, np.int32),
            "identity": np.asarray(identity, np.uint32),
            "dport": np.asarray(dport, np.int32),
            "proto": np.asarray(proto, np.int32),
            "direction": np.asarray(direction, np.int32),
            "is_fragment": (
                np.zeros(len(ep_index), bool)
                if is_fragment is None
                else np.asarray(is_fragment, bool)
            ),
        }
        if len(cols["ep_index"]) == 0:
            # nothing to route: _pack cannot size shards for an
            # empty stream, and consuming fault schedules / probe
            # slots for zero tuples would skew attribution
            from cilium_tpu.engine.verdict import Verdicts

            return FailoverResult(
                verdicts=Verdicts(
                    allowed=np.zeros(0, np.uint8),
                    proxy_port=np.zeros(0, np.int32),
                    match_kind=np.zeros(0, np.uint8),
                ),
            )
        plan, fold_args = self._plan_batch(cols)
        if plan is None:
            return self._terminal_fold(
                cols, *fold_args,
                reason="no mesh row can serve every table slice",
            )
        alive = plan["alive"]
        current = self.store.current()
        if current is None:
            raise RuntimeError(
                "no published epoch: call router.publish first"
            )
        _, dev_tables = current
        from cilium_tpu.engine.verdict import TupleBatch

        batch = TupleBatch(**plan["padded"])
        with tracing.tracer.span(
            "mesh.dispatch", site=self.site,
            attrs={
                "chips": int(alive.sum()),
                "rows": len(cols["ep_index"]),
                "rerouted": plan["rerouted"],
            },
        ) as sp:
            out = hit_padded = None
            if self._memo is not None:
                out, hit_padded = self._memo_dispatch(
                    current[0], dev_tables, batch, alive,
                    plan["valid"], sp,
                )
            if out is None:
                try:
                    out = self._ev(
                        dev_tables, batch, alive, plan["valid"]
                    )
                    import jax

                    jax.block_until_ready(out)
                except Exception as exc:  # noqa: BLE001
                    sp.status = "error"
                    sp.attrs["error"] = str(exc)
                    self._blame_alive(alive, exc)
                    return self._terminal_fold(
                        cols, alive, plan["rebalanced"],
                        plan["reb_bytes"], plan["reb_ms"],
                        reason=str(exc),
                    )
            shadow_v = None
            if shadow is not None:
                # the shadow leg: the same staged/padded batch, the
                # same alive mask, the shadow epoch's tables — its
                # gathers route through replicas exactly like the
                # live ones.  Replica/telemetry accounting is NOT
                # repeated (the live leg owns the observables); a
                # shadow failure refuses the sample, never the batch.
                shadow_ev, shadow_dev = shadow
                with tracing.tracer.span(
                    "shadow.dispatch", site="shadow.dispatch",
                    attrs={
                        "rows": len(cols["ep_index"]),
                        "routed": True,
                        "chips": int(alive.sum()),
                    },
                ) as ssp:
                    try:
                        sout = shadow_ev(
                            shadow_dev, batch, alive, plan["valid"]
                        )
                        import jax

                        jax.block_until_ready(sout)
                        shadow_v = sout[0]
                    except Exception as exc:  # noqa: BLE001
                        ssp.status = "error"
                        ssp.attrs["error"] = str(exc)
                        shadow_v = None
        self._credit_alive(alive)
        if self.collect_telemetry:
            v, l4c, l3c, replica_hits, trow = out
            telemetry = np.asarray(trow)
        else:
            v, l4c, l3c, replica_hits = out
            telemetry = None
        replica_hits = self._count_replica_hits(replica_hits)
        from cilium_tpu.engine.verdict import Verdicts

        positions = plan["positions"]
        if positions is None:
            verdicts = Verdicts(
                allowed=np.asarray(v.allowed),
                proxy_port=np.asarray(v.proxy_port),
                match_kind=np.asarray(v.match_kind),
            )
            cache_hit = hit_padded
        else:
            verdicts = Verdicts(
                allowed=np.asarray(v.allowed)[positions],
                proxy_port=np.asarray(v.proxy_port)[positions],
                match_kind=np.asarray(v.match_kind)[positions],
            )
            cache_hit = (
                None if hit_padded is None
                else hit_padded[positions]
            )
        shadow_verdicts = None
        if shadow_v is not None:
            take = (
                (lambda a: np.asarray(a))
                if positions is None
                else (lambda a: np.asarray(a)[positions])
            )
            shadow_verdicts = Verdicts(
                allowed=take(shadow_v.allowed),
                proxy_port=take(shadow_v.proxy_port),
                match_kind=take(shadow_v.match_kind),
            )
        return FailoverResult(
            verdicts=verdicts,
            shadow_verdicts=shadow_verdicts,
            l4_counts=np.asarray(l4c),
            l3_counts=np.asarray(l3c),
            telemetry=telemetry,
            replica_hits=replica_hits,
            rerouted=plan["rerouted"],
            degraded=False,
            alive=alive,
            rebalanced_chips=plan["rebalanced"],
            rebalance_bytes=plan["reb_bytes"],
            rebalance_ms=plan["reb_ms"],
            cache_hit=cache_hit,
        )

    def _memo_dispatch(
        self, stamp, dev_tables, batch, alive, valid, sp
    ):
        """One attempt through the alive-masked memo evaluator.
        Returns (out, hit) with `out` shaped exactly like the
        uncached evaluator's result tuple, or (None, None) when the
        batch must be served uncached: stamp raced a publish,
        compaction overflow (the kernel refused — carried cache
        provably unchanged), or a launch failure (the uncached path
        re-runs under its own blame/terminal-fold machinery)."""
        import jax

        from cilium_tpu.engine import memo as vm

        cache = self._memo["cache"]
        cache.ensure(stamp)
        cur_stamp, rows_in = cache.acquire()
        if cur_stamp != stamp:
            return None, None
        local_b = int(batch.ep_index.shape[0]) // self.dp
        rep_cap = max(
            local_b >> self._memo["rep_shift"], min(local_b, 256)
        )
        try:
            ev = self._memo_evaluator(rep_cap)
            out = ev(dev_tables, batch, alive, valid, rows_in)
            jax.block_until_ready(out)
        except faultinject.FaultInjected as exc:
            # never swallow an injected fault as a generic memo
            # error: surface it to the breaker plane — blame the
            # chip the seam named (if any) — then serve the batch
            # through the UNCACHED path, whose own failure handling
            # (per-chip blame, terminal fold) applies from here
            sp.attrs["memo_fault"] = str(exc)
            if exc.chip is not None:
                self.bank.record_failure(int(exc.chip), str(exc))
            cache.flush(reason="memo-dispatch-fault")
            return None, None
        except Exception as exc:  # noqa: BLE001
            sp.attrs["memo_error"] = str(exc)
            cache.flush(reason="memo-dispatch-failure")
            return None, None
        if self.collect_telemetry:
            v, l4c, l3c, hits, cache2, hit, stats, trow = out
            rest = (trow,)
        else:
            v, l4c, l3c, hits, cache2, hit, stats = out
            rest = ()
        s = np.asarray(stats)
        if int(s[vm.STAT_OVERFLOW]):
            # the kernel refused: more distinct keys than the
            # compaction capacity — re-dispatch uncached (exactly
            # once; bit-identity is unconditional)
            self._memo["overflow_redispatches"] += 1
            cache.account(s)
            return None, None
        # memo.insert fault seam, probed once per ALIVE ordinal
        # before the commit (chip-scoped schedules poison only
        # batches their chip participated in).  A fired fault drops
        # the write-back — the carried cache is provably unchanged,
        # exactly the overflow-refusal shape — and the batch
        # re-dispatches through the uncached failover evaluator:
        # surfaced (metric + span attr + per-router counter), never
        # silently swallowed.  The per-ordinal probe loop gates on
        # the lock-free nothing-armed read (production pays no
        # per-dispatch grid walk).
        if faultinject.any_armed():
            try:
                for r in range(self.dp):
                    for c in range(self.tp):
                        if alive[r, c]:
                            faultinject.fire(
                                "memo.insert",
                                chip=int(self.ordinals[r, c]),
                            )
            except faultinject.FaultInjected as exc:
                metrics.memo_insert_faults_total.inc()
                sp.attrs["memo_insert_fault"] = str(exc)
                self._memo["insert_faults"] = (
                    self._memo.get("insert_faults", 0) + 1
                )
                return None, None
        cache.commit(stamp, cache2)
        row = cache.account(s)
        self._memo["hits"] += row["hits"]
        self._memo["misses"] += row["tuples"] - row["hits"]
        sp.attrs["cache_hits"] = row["hits"]
        return (v, l4c, l3c, hits) + rest, np.asarray(hit)

    def _terminal_fold(
        self, cols, alive, rebalanced, reb_bytes, reb_ms, reason=""
    ) -> FailoverResult:
        """The host lattice fold — taken only when no owner of some
        slice survives (or the SPMD launch itself failed)."""
        if self.host_fold is None:
            raise RuntimeError(
                f"mesh unserviceable ({reason}) and no host_fold "
                f"terminal fallback configured"
            )
        with tracing.tracer.span(
            "engine.hostpath", site="engine.hostpath",
            attrs={"failover": True, "reason": reason},
        ):
            v = self.host_fold(
                cols["ep_index"], cols["identity"], cols["dport"],
                cols["proto"], cols["direction"],
                cols["is_fragment"],
            )
        metrics.degraded_batches_total.inc()
        self.stats.degraded_batches += 1
        log.warning(
            "mesh batch served by terminal host fold",
            extra={"fields": {"reason": reason}},
        )
        from cilium_tpu.engine.verdict import Verdicts

        verdicts = Verdicts(
            allowed=np.asarray(v.allowed),
            proxy_port=np.asarray(v.proxy_port),
            match_kind=np.asarray(v.match_kind),
        )
        return FailoverResult(
            verdicts=verdicts,
            degraded=True,
            alive=alive,
            rebalanced_chips=rebalanced,
            rebalance_bytes=reb_bytes,
            rebalance_ms=reb_ms,
        )

    # -- introspection -------------------------------------------------------

    def chip_states(self) -> Dict[int, str]:
        return self.bank.states()

    def snapshot(self) -> Dict:
        return {
            "chips": {
                str(o): s for o, s in self.bank.states().items()
            },
            "stats": {
                "batches": self.stats.batches,
                "tuples": self.stats.tuples,
                "rerouted_batches": self.stats.rerouted_batches,
                "degraded_batches": self.stats.degraded_batches,
                "replica_hits": self.stats.replica_hits,
                "rebalances": self.stats.rebalances,
                "rebalance_bytes": self.stats.rebalance_bytes,
                "last_rebalance_ms": self.stats.last_rebalance_ms,
            },
        }
