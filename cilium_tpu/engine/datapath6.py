"""The fused IPv6 datapath program.

The reference datapath is dual-stack with SEPARATE per-family
programs (bpf_lxc.c:754 ipv6_policy beside ipv4_policy; eps.h:70
ipcache_lookup6; conntrack.h ct_lookup6) — this module is the v6
sibling of engine/datapath.py, sharing the policy lattice and the
bucket-row design:

  * prefilter6: broadcast limb-masked range compare (zero gathers);
  * CT6: direction-normalized bucket rows — entries carry 4-limb
    address pairs (11 × u32 stride, 11 entries per 128-lane row),
    one row gather answers forward+reverse probes;
  * ipcache6: ipcache/lpm6.IPCache6Device (bucketized /128s +
    broadcast ranges);
  * the SAME policy lattice tables as v4 (identities are
    family-agnostic, as in the reference's shared policymap).

Service LB for v6 (lb6_local, bpf/lib/lb.h lb6_*) IS lowered:
lb/device6.py's inline single-gather layout resolves the v6 service
and backend, with CT6 service-scope stickiness probed first exactly
as the v4 program does.

Mixed v4/v6 batches run each family through its own program, exactly
as packets hit one of the reference's two program sections.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.ct.table import (
    CT_EGRESS,
    CT_ESTABLISHED,
    CT_INGRESS,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CT_SERVICE,
    CTMap,
    CTTuple,
    TUPLE_F_IN,
    TUPLE_F_OUT,
    TUPLE_F_RELATED,
    TUPLE_F_SERVICE,
)
from cilium_tpu.engine.hashtable import _fnv1a_host, fnv1a_device
from cilium_tpu.engine.verdict import TupleBatch, _combine, _probes
from cilium_tpu.identity import RESERVED_WORLD
from cilium_tpu.ipcache.lpm6 import (
    IPCache6Device,
    build_limb_ranges,
    ipcache6_lookup,
    limbs_of_int,
    match_limb_ranges,
)
from cilium_tpu.maps.policymap import INGRESS

CT6_ENTRY_WORDS = 11
CT6_PER_BUCKET = 128 // CT6_ENTRY_WORDS  # 11
CT6_BUCKET_LOAD = 2
CT6_STASH = 128
_SWAPPED_BIT = 1 << 7
_EMPTY_W = np.uint32(0xFFFFFFFF)  # marker in the proto|flags plane


_limbs = limbs_of_int


@jax.tree_util.register_pytree_node_class
@dataclass
class FlowBatch6:
    """Raw v6 5-tuples: addresses as u32 [B, 4] limb arrays."""

    ep_index: jax.Array  # i32 [B]
    saddr: jax.Array  # u32 [B, 4]
    daddr: jax.Array  # u32 [B, 4]
    sport: jax.Array  # i32 [B]
    dport: jax.Array  # i32 [B]
    proto: jax.Array  # i32 [B]
    direction: jax.Array  # i32 [B]
    is_fragment: jax.Array  # bool [B]

    def tree_flatten(self):
        return (
            (
                self.ep_index,
                self.saddr,
                self.daddr,
                self.sport,
                self.dport,
                self.proto,
                self.direction,
                self.is_fragment,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def from_numpy(
        ep_index, saddr, daddr, sport, dport, proto, direction,
        is_fragment=None,
    ) -> "FlowBatch6":
        b = len(ep_index)
        if is_fragment is None:
            is_fragment = np.zeros(b, dtype=bool)
        return FlowBatch6(
            ep_index=jnp.asarray(ep_index, dtype=jnp.int32),
            saddr=jnp.asarray(saddr, dtype=jnp.uint32),
            daddr=jnp.asarray(daddr, dtype=jnp.uint32),
            sport=jnp.asarray(sport, dtype=jnp.int32),
            dport=jnp.asarray(dport, dtype=jnp.int32),
            proto=jnp.asarray(proto, dtype=jnp.int32),
            direction=jnp.asarray(direction, dtype=jnp.int32),
            is_fragment=jnp.asarray(is_fragment, dtype=bool),
        )


@dataclass
class Prefilter6:
    """Broadcast limb ranges (the v6 face of prefilter.py)."""

    base: np.ndarray  # u32 [P, 4]
    mask: np.ndarray  # u32 [P, 4]

    def tree_flatten(self):
        return ((self.base, self.mask), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@dataclass
class CT6Snapshot:
    """v6 conntrack bucket rows (pytree; planar 11-entry stride)."""

    buckets: np.ndarray  # u32 [Cb, 128]
    stash: np.ndarray  # u32 [S, CT6_ENTRY_WORDS]
    n_buckets: int

    def tree_flatten(self):
        return ((self.buckets, self.stash), self.n_buckets)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


jax.tree_util.register_pytree_node(
    Prefilter6,
    lambda t: t.tree_flatten(),
    lambda aux, ch: Prefilter6.tree_unflatten(aux, ch),
)
jax.tree_util.register_pytree_node(
    CT6Snapshot,
    lambda t: t.tree_flatten(),
    lambda aux, ch: CT6Snapshot.tree_unflatten(aux, ch),
)


def build_prefilter6(cidrs) -> Prefilter6:
    import ipaddress

    from cilium_tpu.ipcache.lpm6 import _mask_limbs, ip6_limbs

    nets = []
    for c in sorted(cidrs):
        net = ipaddress.ip_network(c, strict=False)
        if net.version != 6:
            continue
        nets.append(
            (
                ip6_limbs(str(net.network_address)),
                _mask_limbs(net.prefixlen),
            )
        )
    base, mask = build_limb_ranges(nets)
    return Prefilter6(base=base, mask=mask)


def prefilter6_drop(pf: Prefilter6, limbs) -> "jax.Array":
    return jnp.any(match_limb_ranges(pf.base, pf.mask, limbs), axis=1)


# -- CT6 ---------------------------------------------------------------------


def _normalize_host6(daddr: int, saddr: int, dport: int, sport: int):
    if (daddr, dport) > (saddr, sport):
        return saddr, daddr, sport, dport, 1
    return daddr, saddr, dport, sport, 0


def compile_ct6(ct: CTMap) -> CT6Snapshot:
    """Host CT (CTTuple addresses as 128-bit ints) → v6 bucket rows.
    Shapes pinned by ct.max_entries like the v4 compile."""
    per = CT6_PER_BUCKET
    # load 2 of 11 lanes ≈ the v4 envelope's 4-of-25 fill ratio, so
    # the Poisson spill into the fixed stash stays negligible at the
    # full max_entries envelope
    nb = 16
    while nb * CT6_BUCKET_LOAD < max(ct.max_entries, 1):
        nb *= 2
    buckets = np.zeros((nb, 128), dtype=np.uint32)
    buckets[:, 9 * per : 10 * per] = _EMPTY_W  # proto|flags plane
    stash = np.zeros((CT6_STASH, CT6_ENTRY_WORDS), dtype=np.uint32)
    stash[:, 9] = _EMPTY_W
    fill = [0] * nb
    sfill = 0
    for key, entry in ct.entries.items():
        lo_a, hi_a, lo_p, hi_p, swapped = _normalize_host6(
            key.daddr, key.saddr, key.dport, key.sport
        )
        lo = _limbs(lo_a)
        hi = _limbs(hi_a)
        words = np.array(
            [[*lo, *hi, ((lo_p & 0xFFFF) << 16) | (hi_p & 0xFFFF),
              key.nexthdr & 0xFF]],
            dtype=np.uint32,
        )
        h = int(_fnv1a_host(words)[0])
        packed = (
            *lo,
            *hi,
            ((lo_p & 0xFFFF) << 16) | (hi_p & 0xFFFF),
            ((key.nexthdr & 0xFF) << 8)
            | (swapped * _SWAPPED_BIT)
            | (key.flags & 0x7F),
            ((entry.rev_nat_index & 0xFFFF) << 16)
            | (entry.slave & 0xFFFF),
        )
        b = h & (nb - 1)
        if fill[b] < per:
            i = fill[b]
            for k in range(CT6_ENTRY_WORDS):
                buckets[b, k * per + i] = packed[k]
            fill[b] += 1
        elif sfill < CT6_STASH:
            stash[sfill] = packed
            sfill += 1
        else:
            raise ValueError("CT6 bucket and stash overflow")
    # ship the stash at its occupied pow2 prefix: every probe
    # broadcast-compares every stash lane with ELEVEN word compares
    # here, so an empty stash at the 128-row capacity is pure wasted
    # hot-path compute; trimmed lanes can never match
    from cilium_tpu.engine.hashtable import trim_pow2_prefix

    return CT6Snapshot(
        buckets=buckets,
        stash=trim_pow2_prefix(stash, sfill),
        n_buckets=nb,
    )


def ct6_lookup_batch(
    snapshot: CT6Snapshot,
    daddr,  # u32 [B, 4]
    saddr,
    dport,
    sport,
    proto,
    direction,
    related_icmp=None,
):
    """ct_lookup6: one bucket row gather, forward+reverse lane
    compares (the v4 kernel generalized limb-for-limb)."""
    base_flags = jnp.where(
        direction == CT_INGRESS,
        TUPLE_F_OUT,
        jnp.where(direction == CT_EGRESS, TUPLE_F_IN, TUPLE_F_SERVICE),
    ).astype(jnp.uint32)
    if related_icmp is not None:
        base_flags = base_flags | jnp.where(
            jnp.asarray(related_icmp), jnp.uint32(TUPLE_F_RELATED), 0
        ).astype(jnp.uint32)
    rev_flags = base_flags ^ jnp.uint32(TUPLE_F_IN)

    daddr = daddr.astype(jnp.uint32)
    saddr = saddr.astype(jnp.uint32)
    dport_u = dport.astype(jnp.uint32) & 0xFFFF
    sport_u = sport.astype(jnp.uint32) & 0xFFFF

    # lexicographic address-pair normalization over limbs, then port
    d_gt = jnp.zeros(daddr.shape[0], bool)
    d_eq = jnp.ones(daddr.shape[0], bool)
    for k in range(4):
        d_gt = d_gt | (d_eq & (daddr[:, k] > saddr[:, k]))
        d_eq = d_eq & (daddr[:, k] == saddr[:, k])
    swapped = d_gt | (d_eq & (dport_u > sport_u))
    pairs_equal = d_eq & (dport_u == sport_u)

    lo = jnp.where(swapped[:, None], saddr, daddr)
    hi = jnp.where(swapped[:, None], daddr, saddr)
    lo_p = jnp.where(swapped, sport_u, dport_u)
    hi_p = jnp.where(swapped, dport_u, sport_u)
    proto_u = proto.astype(jnp.uint32) & 0xFF

    h = fnv1a_device(
        jnp.concatenate(
            [lo, hi, ((lo_p << 16) | hi_p)[:, None], proto_u[:, None]],
            axis=1,
        )
    )
    bucket = (h & jnp.uint32(snapshot.n_buckets - 1)).astype(jnp.int32)
    rows = jnp.asarray(snapshot.buckets)[bucket]  # [B, 128]
    per = CT6_PER_BUCKET

    def plane(k):
        return rows[:, k * per : (k + 1) * per]

    key_eq = jnp.ones((daddr.shape[0], per), bool)
    for k in range(4):
        key_eq = key_eq & (plane(k) == lo[:, k : k + 1])
        key_eq = key_eq & (plane(4 + k) == hi[:, k : k + 1])
    key_eq = key_eq & (plane(8) == ((lo_p << 16) | hi_p)[:, None])

    fwd_sw = swapped & ~pairs_equal
    rev_sw = ~swapped & ~pairs_equal
    w9_fwd = (
        (proto_u << 8)
        | (fwd_sw.astype(jnp.uint32) * _SWAPPED_BIT)
        | base_flags
    )
    w9_rev = (
        (proto_u << 8)
        | (rev_sw.astype(jnp.uint32) * _SWAPPED_BIT)
        | rev_flags
    )
    fwd_hit = key_eq & (plane(9) == w9_fwd[:, None])
    rev_hit = key_eq & (plane(9) == w9_rev[:, None])

    stash = jnp.asarray(snapshot.stash)
    s_key = jnp.ones((daddr.shape[0], stash.shape[0]), bool)
    for k in range(4):
        s_key = s_key & (stash[None, :, k] == lo[:, k : k + 1])
        s_key = s_key & (stash[None, :, 4 + k] == hi[:, k : k + 1])
    s_key = s_key & (stash[None, :, 8] == ((lo_p << 16) | hi_p)[:, None])
    s_fwd = s_key & (stash[None, :, 9] == w9_fwd[:, None])
    s_rev = s_key & (stash[None, :, 9] == w9_rev[:, None])

    def pick(hits, s_hits):
        return jnp.sum(
            jnp.where(hits, plane(10), 0), axis=1, dtype=jnp.uint32
        ) + jnp.sum(
            jnp.where(s_hits, stash[None, :, 10], 0),
            axis=1,
            dtype=jnp.uint32,
        )

    fwd_found = jnp.any(fwd_hit, axis=1) | jnp.any(s_fwd, axis=1)
    rev_found = jnp.any(rev_hit, axis=1) | jnp.any(s_rev, axis=1)
    probed_related = (base_flags & jnp.uint32(TUPLE_F_RELATED)) != 0
    result = jnp.where(
        rev_found,
        jnp.where(probed_related, CT_RELATED, CT_REPLY),
        jnp.where(
            fwd_found,
            jnp.where(probed_related, CT_RELATED, CT_ESTABLISHED),
            CT_NEW,
        ),
    ).astype(jnp.uint8)
    val = jnp.where(rev_found, pick(rev_hit, s_rev), pick(fwd_hit, s_fwd))
    hit = rev_found | fwd_found
    rev_nat = jnp.where(hit, val >> 16, 0).astype(jnp.int32)
    slave = jnp.where(hit, val & 0xFFFF, 0).astype(jnp.int32)
    return result, rev_nat, slave


# -- the fused v6 program ----------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclass
class Datapath6Tables:
    prefilter: Prefilter6
    ipcache: IPCache6Device
    ct: CT6Snapshot
    policy: object  # compiler.tables.PolicyTables (shared with v4)
    tunnel: object = None  # tunnel.TunnelTables6 or None
    lb: object = None  # lb.device6.LB6Inline or None (no v6 services)

    def tree_flatten(self):
        return (
            (
                self.prefilter, self.ipcache, self.ct, self.policy,
                self.tunnel, self.lb,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class Datapath6Verdicts:
    allowed: jax.Array  # u8 [B]
    proxy_port: jax.Array  # i32 [B]
    match_kind: jax.Array  # u8 [B]
    ct_result: jax.Array  # u8 [B]
    pre_dropped: jax.Array  # bool [B]
    sec_id: jax.Array  # u32 [B]
    ct_create: jax.Array  # bool [B]
    ct_delete: jax.Array  # bool [B]
    # u32 [B] remote node IP (v4 underlay) to encapsulate to; 0 =
    # direct/local — all-zero without a tunnel table
    tunnel_endpoint: jax.Array = None
    # post-DNAT destination (lb6_local); equal to the input daddr /
    # dport for non-service flows
    final_daddr: jax.Array = None  # u32 [B, 4]
    final_dport: jax.Array = None  # i32 [B]
    rev_nat: jax.Array = None  # i32 [B]
    lb_slave: jax.Array = None  # i32 [B]

    def tree_flatten(self):
        return (
            (
                self.allowed,
                self.proxy_port,
                self.match_kind,
                self.ct_result,
                self.pre_dropped,
                self.sec_id,
                self.ct_create,
                self.ct_delete,
                self.tunnel_endpoint,
                self.final_daddr,
                self.final_dport,
                self.rev_nat,
                self.lb_slave,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _datapath6_kernel(
    tables: Datapath6Tables, flows: FlowBatch6
) -> Datapath6Verdicts:
    """ipv6_policy (bpf_lxc.c:754): prefilter → lb6_local (service
    DNAT with CT6 service-scope stickiness) → CT6 → ipcache6 →
    shared policy lattice → combine."""
    ingress = flows.direction == INGRESS

    pre_drop = prefilter6_drop(tables.prefilter, flows.saddr)

    # -- lb6_local: v6 service DNAT on egress flows ---------------------
    if tables.lb is not None:
        from cilium_tpu.lb.device6 import lb6_select_batch

        svc_dir = jnp.full_like(flows.direction, CT_SERVICE)
        _, _, svc_slave = ct6_lookup_batch(
            tables.ct,
            flows.daddr,
            flows.saddr,
            flows.dport,
            flows.sport,
            flows.proto,
            svc_dir,
        )
        svc_found, slave, lb_daddr, lb_dport, lb_rev = (
            lb6_select_batch(
                tables.lb,
                flows.saddr,
                flows.daddr,
                flows.sport,
                flows.dport,
                flows.proto,
                ct_slave=svc_slave,
            )
        )
        do_lb = (~ingress) & svc_found
        eff_daddr = jnp.where(
            do_lb[:, None], lb_daddr, flows.daddr.astype(jnp.uint32)
        )
        eff_dport = jnp.where(do_lb, lb_dport, flows.dport)
        rev_nat = jnp.where(do_lb, lb_rev, 0)
        lb_slave = jnp.where(do_lb, slave, 0)
    else:
        zero = jnp.zeros(flows.dport.shape, jnp.int32)
        eff_daddr = flows.daddr.astype(jnp.uint32)
        eff_dport = flows.dport
        rev_nat = zero
        lb_slave = zero

    ct_res, _ct_rev, _ = ct6_lookup_batch(
        tables.ct,
        eff_daddr,
        flows.saddr,
        eff_dport,
        flows.sport,
        flows.proto,
        flows.direction,
    )

    sec_limbs = jnp.where(
        ingress[:, None], flows.saddr, eff_daddr
    )
    looked = ipcache6_lookup(tables.ipcache, sec_limbs)
    sec_id = jnp.where(
        looked == 0, jnp.uint32(RESERVED_WORLD), looked
    ).astype(jnp.uint32)

    resolved = TupleBatch(
        ep_index=flows.ep_index,
        identity=sec_id,
        dport=eff_dport,
        proto=flows.proto,
        direction=flows.direction,
        is_fragment=flows.is_fragment,
    )
    p1, p2, p3, proxy, _j, _idx = _probes(tables.policy, resolved)
    v = _combine(p1, p2, p3, proxy, resolved.is_fragment)

    pol_allow = v.allowed.astype(bool)
    pass_ct = (ct_res == CT_REPLY) | (ct_res == CT_RELATED)
    allowed = (~pre_drop) & (pass_ct | pol_allow)
    ct_delete = (
        (ct_res == CT_ESTABLISHED) & ~pol_allow & ~pass_ct & ~pre_drop
    )
    ct_create = (ct_res == CT_NEW) & allowed
    proxy_out = jnp.where(
        pol_allow
        & ((ct_res == CT_NEW) | (ct_res == CT_ESTABLISHED))
        & allowed,
        v.proxy_port,
        0,
    )
    # overlay decision (the v4 program's stage 7, limb-masked): an
    # allowed egress flow into a remote node's v6 pod CIDR carries
    # that node's (v4 underlay) IP — on the POST-DNAT destination
    if tables.tunnel is not None:
        from cilium_tpu.tunnel import tunnel_select6

        tunnel_ep = jnp.where(
            allowed & ~ingress,
            tunnel_select6(tables.tunnel, eff_daddr),
            jnp.uint32(0),
        )
    else:
        tunnel_ep = jnp.zeros(allowed.shape, jnp.uint32)

    return Datapath6Verdicts(
        allowed=allowed.astype(jnp.uint8),
        proxy_port=proxy_out,
        match_kind=v.match_kind,
        ct_result=ct_res,
        pre_dropped=pre_drop,
        sec_id=sec_id,
        ct_create=ct_create,
        ct_delete=ct_delete,
        tunnel_endpoint=tunnel_ep,
        final_daddr=eff_daddr,
        final_dport=eff_dport,
        rev_nat=rev_nat,
        lb_slave=lb_slave,
    )


datapath6_step = jax.jit(_datapath6_kernel)


def _int_of_limbs(limbs) -> int:
    v = 0
    for k in range(4):
        v = (v << 32) | int(limbs[k])
    return v


def apply_ct_writeback6(
    ct: CTMap, out: Datapath6Verdicts, flows: FlowBatch6, now: int = 0
) -> tuple:
    """Host-side v6 CT mutation after a batch: NEW+allowed flows
    create entries on the post-DNAT tuple (+ the SERVICE-scope
    stickiness entry for load-balanced flows, lb6_local's ct_create6),
    ESTABLISHED-but-denied flows delete.  Returns (created, deleted)
    counts.  Addresses stay 128-bit ints in the host map, exactly as
    compile_ct6 expects them."""
    create = np.asarray(out.ct_create)
    delete = np.asarray(out.ct_delete)
    fdaddr = np.asarray(out.final_daddr)
    fdport = np.asarray(out.final_dport)
    saddr = np.asarray(flows.saddr)
    odaddr = np.asarray(flows.daddr)
    odport = np.asarray(flows.dport)
    sport = np.asarray(flows.sport)
    proto = np.asarray(flows.proto)
    direction = np.asarray(flows.direction)
    rev = np.asarray(out.rev_nat)
    slave = np.asarray(out.lb_slave)
    created = deleted = 0
    for i in np.nonzero(create | delete)[0]:
        d_int = _int_of_limbs(fdaddr[i])
        s_int = _int_of_limbs(saddr[i])
        dirv = int(direction[i])
        flags = TUPLE_F_OUT if dirv == CT_INGRESS else TUPLE_F_IN
        key = CTTuple(
            d_int, s_int, int(fdport[i]), int(sport[i]),
            int(proto[i]), flags,
        )
        if create[i]:
            if key not in ct.entries:
                if ct.create_best_effort(
                    CTTuple(
                        d_int, s_int, int(fdport[i]), int(sport[i]),
                        int(proto[i]),
                    ),
                    dirv, now=now, rev_nat_index=int(rev[i]),
                    slave=int(slave[i]),
                ):
                    created += 1
            if int(rev[i]) > 0:
                o_int = _int_of_limbs(odaddr[i])
                svc_key = CTTuple(
                    o_int, s_int, int(odport[i]), int(sport[i]),
                    int(proto[i]), TUPLE_F_SERVICE,
                )
                if svc_key not in ct.entries:
                    if ct.create_best_effort(
                        CTTuple(
                            o_int, s_int, int(odport[i]),
                            int(sport[i]), int(proto[i]),
                        ),
                        CT_SERVICE, now=now,
                        rev_nat_index=int(rev[i]),
                        slave=int(slave[i]),
                    ):
                        created += 1
        elif delete[i]:
            if ct.entries.pop(key, None) is not None:
                deleted += 1
    return created, deleted
