"""Device-resident table epochs: versioned double-buffered publication.

The serving path used to re-upload every PolicyTables leaf after each
control-plane publish (device_put of ~hundreds of MB of numpy per
flip).  This store keeps TWO device-resident epochs ping-ponging, the
device analog of the realized/backup map shuffle
(pkg/datapath/ipcache/listener.go:167):

  * `publish(tables, delta)` installs the new generation into the
    SPARE epoch.  With a TableDelta covering the spare's stamp, the
    update is a compact jitted scatter (`tables.at[idx].set(rows)`,
    donate_argnums on the spare pytree so XLA patches the resident
    buffers in place) — bytes shipped are proportional to the CHANGE,
    not the world.  Without a delta (shape-class change, stale spare)
    it falls back to a full upload.
  * in-flight batches dispatched against the CURRENT epoch finish on
    it untouched; only the spare's buffers are donated.
  * `check_current` raises for tables whose epoch has since been
    donated — the device-side extension of
    FleetCompiler.check_tables_current's one-flip window.

Replication: pass `shardings` (a PolicyTables pytree of NamedSharding)
and every chip of a mesh receives the same scatter — tables are
replicated across the mesh (engine/sharded.py), so one delta updates
the whole fleet of chips.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from cilium_tpu import tracing
from cilium_tpu.compiler.delta import TableDelta, tables_nbytes
from cilium_tpu.compiler.tables import PolicyTables
from cilium_tpu.metrics import registry as metrics


def _pad_pow2(update):
    """Pad scatter payloads to the next power of two by repeating the
    last entry (duplicate identical writes are deterministic), so the
    jitted updater recompiles per size CLASS instead of per size."""
    k = len(update.values)
    size = 1
    while size < k:
        size <<= 1
    if size == k:
        return update.idx, update.values
    pad = size - k
    idx = tuple(
        np.concatenate([i, np.repeat(i[-1:], pad)]) for i in update.idx
    )
    values = np.concatenate(
        [update.values, np.repeat(update.values[-1:], pad, axis=0)]
    )
    return idx, values


@dataclass
class PublishStats:
    epoch: int
    mode: str  # "full" | "delta"
    bytes_h2d: int
    seconds: float
    scatter_leaves: int = 0
    replaced_leaves: int = 0


class StaleEpochError(ValueError):
    pass


class DeviceTableStore:
    """Two device table epochs with scatter-delta publication."""

    def __init__(self, shardings: Optional[PolicyTables] = None) -> None:
        self._lock = threading.Lock()
        # each slot: dict(tables=<device pytree>, stamp=int, epoch=int)
        self._slots = [None, None]
        self._cur = 0
        self._epoch = 0
        self._shardings = shardings
        self._apply_cache: Dict[tuple, object] = {}

    # -- device placement ----------------------------------------------------

    def _put(self, value, leaf: Optional[str] = None):
        import jax

        if self._shardings is None:
            return jax.device_put(value)
        sharding = (
            getattr(self._shardings, leaf)
            if leaf is not None and hasattr(self._shardings, leaf)
            else None
        )
        if sharding is None:
            # payload arrays replicate (every chip applies the same
            # scatter); use any leaf's mesh via the generation spec
            sharding = self._shardings.generation
        return jax.device_put(value, sharding)

    def _put_tables(self, tables: PolicyTables):
        import jax

        if self._shardings is None:
            return jax.device_put(tables)
        return jax.tree.map(
            lambda leaf, s: (
                None if leaf is None else jax.device_put(leaf, s)
            ),
            tables,
            self._shardings,
            is_leaf=lambda x: x is None,
        )

    # -- scatter updater -----------------------------------------------------

    def _apply_fn(self, fields: Tuple[str, ...]):
        """Jitted donated scatter: patch `fields` of the spare epoch
        in place and stamp the new generation.  Cached per field set
        (payload shapes are pow2-padded, so the per-set jit cache
        stays small)."""
        import jax

        fn = self._apply_cache.get(fields)
        if fn is not None:
            return fn

        def apply(tables, payloads, generation):
            kw = {}
            for name, (idx, values) in zip(fields, payloads):
                kw[name] = getattr(tables, name).at[idx].set(values)
            kw["generation"] = generation
            return dataclasses.replace(tables, **kw)

        # jit-cache observability rides the scatter entry point: a
        # payload outside the known pow2 classes shows up as a miss +
        # compile seconds in the same scrape as the publish bytes
        fn = tracing.track_jit(
            jax.jit(apply, donate_argnums=(0,)), "publish.scatter"
        )
        self._apply_cache[fields] = fn
        return fn

    # -- publication ---------------------------------------------------------

    def publish(
        self, tables: PolicyTables, delta: Optional[TableDelta] = None
    ) -> Tuple[PolicyTables, PublishStats]:
        """Install `tables` (host arrays) as the new current epoch.
        `delta` must describe every change from the SPARE slot's stamp
        to `tables` (see FleetCompiler.delta_for); anything else —
        or delta=None — forces a full upload."""
        import jax

        with self._lock, tracing.tracer.span(
            "publish.epoch", site="engine.publish"
        ) as sp:
            t0 = time.perf_counter()
            spare_i = self._cur ^ 1
            spare = self._slots[spare_i]
            stamp = int(np.asarray(tables.generation))
            use_delta = (
                delta is not None
                and spare is not None
                and spare["stamp"] == delta.base_stamp
                and stamp == delta.new_stamp
            )
            if use_delta:
                try:
                    dev, stats = self._publish_delta(
                        spare["tables"], tables, delta
                    )
                except Exception:
                    # the donated scatter may have consumed the spare
                    # epoch's buffers before failing — de-register the
                    # slot so the next publish full-uploads instead of
                    # scattering into deleted arrays forever
                    self._slots[spare_i] = None
                    self._sample_bytes()
                    raise
                # the standby's resident buffers were donated (patched
                # in place) — HBM reused, not reallocated
                metrics.device_table_retired_bytes.inc(
                    value=spare.get("nbytes", 0)
                )
            else:
                dev = self._put_tables(tables)
                jax.block_until_ready(dev)
                stats = PublishStats(
                    epoch=0, mode="full", bytes_h2d=tables_nbytes(tables),
                    seconds=0.0,
                )
            self._epoch += 1
            self._slots[spare_i] = {
                "tables": dev, "stamp": stamp, "epoch": self._epoch,
                "nbytes": tables_nbytes(tables),
            }
            self._cur = spare_i
            stats.epoch = self._epoch
            stats.seconds = time.perf_counter() - t0
            self._sample_bytes()
            sp.attrs.update(
                mode=stats.mode, epoch=stats.epoch,
                bytes_h2d=stats.bytes_h2d,
                scatter_leaves=stats.scatter_leaves,
                replaced_leaves=stats.replaced_leaves,
            )
            return dev, stats

    def _sample_bytes(self) -> None:
        """cilium_device_table_bytes{epoch}: per-slot resident bytes,
        sampled at every publish (caller holds the lock) — the HBM
        line of the device-resource accounting plane."""
        cur = self._slots[self._cur]
        spare = self._slots[self._cur ^ 1]
        metrics.device_table_bytes.set(
            "live", value=(cur or {}).get("nbytes", 0)
        )
        metrics.device_table_bytes.set(
            "standby", value=(spare or {}).get("nbytes", 0)
        )

    def _publish_delta(
        self,
        spare_dev: PolicyTables,
        tables: PolicyTables,
        delta: TableDelta,
    ):
        import jax

        n_scatter = 0
        n_replace = 0
        # whole-leaf replacements land outside the jit: fresh uploads
        # swapped into the donated pytree (the old leaf is dropped)
        replaced = {}
        for name, arr in delta.replace.items():
            replaced[name] = self._put(arr, name)
            n_replace += 1
        base = spare_dev
        if replaced:
            base = dataclasses.replace(base, **replaced)
        fields = tuple(sorted(delta.updates))
        gen_dev = self._put(np.uint64(np.asarray(tables.generation)))
        if fields:
            payloads = []
            for name in fields:
                idx, values = _pad_pow2(delta.updates[name])
                payloads.append(
                    (
                        tuple(self._put(i) for i in idx),
                        self._put(values),
                    )
                )
                n_scatter += 1
            dev = self._apply_fn(fields)(base, tuple(payloads), gen_dev)
        else:
            dev = dataclasses.replace(base, generation=gen_dev)
        jax.block_until_ready(dev)
        return dev, PublishStats(
            epoch=0, mode="delta", bytes_h2d=delta.bytes_h2d,
            seconds=0.0, scatter_leaves=n_scatter,
            replaced_leaves=n_replace,
        )

    # -- consumers -----------------------------------------------------------

    def current(self) -> Optional[Tuple[int, PolicyTables]]:
        with self._lock:
            slot = self._slots[self._cur]
            if slot is None:
                return None
            return slot["epoch"], slot["tables"]

    def current_stamp(self) -> Optional[int]:
        with self._lock:
            slot = self._slots[self._cur]
            return None if slot is None else slot["stamp"]

    def get(self, stamp: int) -> Optional[PolicyTables]:
        """The live epoch carrying `stamp`, if still resident (a
        reader that snapshotted an older publish reuses its epoch
        instead of flipping the store backward)."""
        with self._lock:
            for slot in self._slots:
                if slot is not None and slot["stamp"] == stamp:
                    return slot["tables"]
            return None

    def spare_stamp(self) -> Optional[int]:
        """Stamp held by the standby epoch — the base the next delta
        must cover."""
        with self._lock:
            spare = self._slots[self._cur ^ 1]
            return None if spare is None else spare["stamp"]

    def live_stamps(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(
                s["stamp"] for s in self._slots if s is not None
            )

    @staticmethod
    def _norm(stamp: int) -> int:
        # without jax x64 the device generation leaf truncates to its
        # low 32 bits (the publish counter); stamps are store-scoped,
        # so comparing the counter bits stays unambiguous
        return int(stamp) & 0xFFFFFFFF

    def holds(self, tables) -> bool:
        """True when `tables` IS one of the live (undonated) epoch
        pytrees.  Object identity, not stamp comparison: a HOST
        snapshot can share a stamp with a lagging device epoch while
        its own stacked buffers have been rewritten — such tables
        must fall through to the compiler's staleness check."""
        with self._lock:
            return any(
                slot is not None and slot["tables"] is tables
                for slot in self._slots
            )

    def check_current(self, tables) -> None:
        """Raise unless `tables` is one of the two live epochs: older
        epochs' buffers have been donated to a newer publish and may
        have been overwritten in place."""
        raw = getattr(tables, "generation", None)
        stamp = self._norm(
            int(np.asarray(raw)) if raw is not None else 0
        )
        live = self.live_stamps()
        if not live or stamp in {self._norm(s) for s in live}:
            return
        raise StaleEpochError(
            f"stale device epoch: generation {stamp} is no longer "
            f"resident (live epochs: {live}) — its buffers were "
            f"donated to a newer publish"
        )
